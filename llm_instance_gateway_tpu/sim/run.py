"""Simulation driver: workload generation, routing sweep, metrics report.

Parity: reference ``src/main.py:13-363`` — sweep QPS x routing policy, emit
per-tier TTFT / TPOT / latency-per-token / SLO-attainment / shed counts.
Workload matches the reference's ShareGPT-derived shape: prompt ~N(202,20),
output ~N(179,17) (``main.py:20-27``), split across criticality tiers with
the reference's 25 ms / 500 ms per-output-token SLOs, plus a LoRA adapter
mix for affinity-sensitive policies.

Routing policies:
- ``random``      uniform over replicas (reference loadbalancer 'random')
- ``least_queue`` min prefill backlog    (reference 'leastPseudo')
- ``least_kv``    min KV utilization     (reference 'least')
- ``least_latency`` min estimated latency/token from live queue state and
  the calibrated latency model (reference 'leastlatency')
- ``production``  the REAL filter tree (gateway.scheduling.Scheduler) over
  live simulated metrics — criticality tiers, LoRA affinity, shedding; what
  the deployed gateway actually does (reference 'smart', minus drift).

Run:  python -m llm_instance_gateway_tpu.sim.run --qps 20 30 --policies random production
"""

from __future__ import annotations

import argparse
import json
import random as pyrandom
from dataclasses import dataclass, field

from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
    Scheduler,
    SchedulingError,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
from llm_instance_gateway_tpu.sim.core import (
    A100_VLLM,
    EventLoop,
    LatencyModel,
    SimRequest,
    SimServer,
    V5E_DEFAULT,
)


@dataclass
class WorkloadConfig:
    qps: float = 20.0
    duration_s: float = 120.0
    prompt_mean: float = 202.0   # main.py:20-27
    prompt_std: float = 20.0
    output_mean: float = 179.0
    output_std: float = 17.0
    critical_fraction: float = 0.3
    sheddable_fraction: float = 0.2
    adapters: tuple[str, ...] = ("sql-lora", "tweet-lora", "chat-lora", "code-lora")
    adapter_fraction: float = 0.8
    slo_critical_s: float = 0.025   # notebook cell 18 tiers
    slo_default_s: float = 0.5
    # Session-prefix traffic (multi-turn chat / per-tenant templates): this
    # fraction of requests carries one of ``n_sessions`` shared prefixes of
    # ``session_prefix_tokens`` — a replica holding the prefix in its cache
    # prefills only the suffix, and prefix-affinity routing tries to land
    # repeats on that replica.
    session_fraction: float = 0.0
    n_sessions: int = 64
    session_prefix_tokens: int = 1024
    # Long-tail adapter universe (the placement plane's target scenario,
    # MinT scale): > 0 replaces the small ``adapters`` tuple with a
    # synthetic universe of this many adapters, traffic drawn from a
    # seeded Zipf(s = adapter_zipf) — the same shape the loadgen's
    # ``--adapter-universe/--adapter-zipf`` flags emit.
    adapter_universe: int = 0
    adapter_zipf: float = 1.1
    seed: int = 0


def zipf_weights(n: int, s: float) -> list[float]:
    """Normalized Zipf weights: w_k proportional to 1/(k+1)^s."""
    raw = [1.0 / (k + 1) ** s for k in range(n)]
    total = sum(raw)
    return [w / total for w in raw]


def universe_name(k: int) -> str:
    return f"zipf-{k:04d}"


def generate_workload(cfg: WorkloadConfig) -> list[SimRequest]:
    rng = pyrandom.Random(cfg.seed)
    universe = weights = None
    if cfg.adapter_universe > 0:
        universe = [universe_name(k) for k in range(cfg.adapter_universe)]
        weights = zipf_weights(cfg.adapter_universe, cfg.adapter_zipf)
    reqs: list[SimRequest] = []
    t = 0.0
    rid = 0
    while t < cfg.duration_s:
        t += rng.expovariate(cfg.qps)
        u = rng.random()
        critical = u < cfg.critical_fraction
        sheddable = u > 1.0 - cfg.sheddable_fraction
        if rng.random() >= cfg.adapter_fraction:
            adapter = None
        elif universe is not None:
            adapter = rng.choices(universe, weights=weights)[0]
        else:
            adapter = rng.choice(cfg.adapters)
        is_critical = critical and not sheddable
        prompt = max(8, int(rng.gauss(cfg.prompt_mean, cfg.prompt_std)))
        prefix_id = None
        prefix_tokens = 0
        if cfg.session_fraction and rng.random() < cfg.session_fraction:
            prefix_id = rng.randrange(cfg.n_sessions)
            prefix_tokens = cfg.session_prefix_tokens
            prompt += prefix_tokens  # suffix stays the base distribution
        reqs.append(
            SimRequest(
                rid=rid,
                arrival_s=t,
                prompt_tokens=prompt,
                output_tokens=max(4, int(rng.gauss(cfg.output_mean, cfg.output_std))),
                model=adapter or "base",
                adapter=adapter,
                critical=is_critical,
                tier=("Critical" if is_critical
                      else "Sheddable" if sheddable else "Default"),
                slo_s_per_token=cfg.slo_critical_s if critical else cfg.slo_default_s,
                prefix_id=prefix_id,
                prefix_tokens=prefix_tokens,
            )
        )
        rid += 1
    return reqs


class _SimProvider:
    def __init__(self, servers: list[SimServer]):
        self.servers = servers

    def all_pod_metrics(self):
        return [s.metrics() for s in self.servers]


def make_router(policy: str, servers: list[SimServer], seed: int = 0,
                scheduler_cfg=None, prefix_index=None,
                placement_advisor=None, pick_ledger=None):
    rng = pyrandom.Random(seed)
    by_name = {s.pod.name: s for s in servers}
    if policy == "random":
        return lambda req: rng.choice(servers)
    if policy == "least_queue":
        return lambda req: min(servers, key=lambda s: len(s.prefill_queue) + len(s.active))
    if policy == "least_kv":
        return lambda req: min(servers, key=lambda s: -s.kv_free())
    if policy == "least_latency":
        # Reference 'leastlatency' (loadbalancer.py:34-85): estimate each
        # pod's expected latency/token for a NEW request from its live queue
        # state and the calibrated latency model, route to the minimum.
        def est(s: SimServer, prompt_tokens: int) -> float:
            lm = s.latency
            batch = len(s.active) + 1
            kv = sum(a.kv_tokens for a in s.active) + prompt_tokens
            step = (lm.decode_base_s + lm.decode_per_kv_token_s * kv
                    + lm.decode_per_seq_s * batch)
            # Prefill backlog ahead of this request delays its first token
            # and steals decode cycles from the batch it joins.
            backlog = sum(
                max(lm.prefill_min_s,
                    lm.prefill_base_s + lm.prefill_per_token_s * r.prompt_tokens)
                for r in s.prefill_queue)
            return step + backlog / max(batch, 1)

        return lambda req: min(
            servers, key=lambda s: est(s, req.prompt_tokens))
    if policy in ("production", "production_affinity",
                  "production_placement"):
        kwargs = {} if scheduler_cfg is None else {"cfg": scheduler_cfg}
        # ``production`` is the no-affinity baseline; ``_affinity`` adds the
        # prefix-cache-aware tie-break (scheduling/prefix_affinity.py) —
        # the session prefix_id stands in for the chained prompt hashes.
        # ``_placement`` wires the REAL PlacementPlanner as the scheduler's
        # placement_advisor in prefer_resident mode (the advisor itself is
        # created and ticked by ``simulate`` — see placement_advisor).
        scheduler = Scheduler(_SimProvider(servers),
                              rng=pyrandom.Random(seed),
                              prefix_aware=(policy == "production_affinity"),
                              prefix_index=prefix_index,
                              **kwargs)
        if policy == "production_placement" and placement_advisor is not None:
            scheduler.placement_advisor = placement_advisor
        if pick_ledger is not None:
            # Decision-ledger seam for sim decision-parity studies: the
            # log-only invariant means attaching it never moves a pick.
            scheduler.pick_ledger = pick_ledger

        def route(req: SimRequest):
            llm_req = LLMRequest(
                model=req.model,
                resolved_target_model=req.adapter or req.model,
                critical=req.critical,
                prompt_tokens=req.prompt_tokens,
                prefix_hashes=((req.prefix_id + 1,)
                               if req.prefix_id is not None else ()),
            )
            pod = scheduler.schedule(llm_req)  # may raise SchedulingError
            return by_name[pod.name]

        return route
    raise ValueError(f"unknown policy {policy!r}")


@dataclass
class SimResult:
    policy: str
    qps: float
    completed: int = 0
    shed: int = 0
    ttfts: list[float] = field(default_factory=list)
    per_token: list[float] = field(default_factory=list)
    slo_hits: int = 0
    slo_total: int = 0
    tokens: int = 0

    # Per-tier GOODPUT: requests finishing within their SLO over ALL tier
    # requests (shed and unfinished count as misses) — the honest number for
    # comparing queueing vs shedding, where "served more, slower" and "served
    # fewer, faster" must be weighed on one scale.
    tier_hits: dict = field(default_factory=dict)
    tier_totals: dict = field(default_factory=dict)
    # Residency-ladder outcome (adapter-universe scenarios): per-adapter
    # TTFT samples (hot-set percentile computation) + pool-wide tier
    # load/transition counts.
    ttft_by_adapter: dict = field(default_factory=dict)
    disk_loads: int = 0
    host_promotes: int = 0
    demotions: int = 0
    # Prefix-cache outcome (session traffic): replica-side hit counts.
    prefix_hits: int = 0
    prefix_misses: int = 0
    prefix_reused_tokens: int = 0

    def goodput(self, tier: str) -> float:
        total = self.tier_totals.get(tier, 0)
        return self.tier_hits.get(tier, 0) / total if total else 1.0

    def summary(self) -> dict:
        def pct(vals, p):
            if not vals:
                return 0.0
            vals = sorted(vals)
            return vals[min(len(vals) - 1, int(p * len(vals)))]

        return {
            "policy": self.policy,
            "qps": self.qps,
            "completed": self.completed,
            "shed": self.shed,
            "tokens": self.tokens,
            "ttft_p50_s": round(pct(self.ttfts, 0.5), 4),
            "ttft_p99_s": round(pct(self.ttfts, 0.99), 4),
            "latency_per_token_p50_s": round(pct(self.per_token, 0.5), 5),
            "slo_attainment": round(self.slo_hits / self.slo_total, 4)
            if self.slo_total else 1.0,
            "slo_goodput_by_tier": {
                t: round(self.goodput(t), 4) for t in sorted(self.tier_totals)
            },
            **({"prefix_hit_rate": round(
                    self.prefix_hits
                    / max(1, self.prefix_hits + self.prefix_misses), 4),
                "prefix_reused_tokens": self.prefix_reused_tokens}
               if self.prefix_hits + self.prefix_misses else {}),
        }


class _SimUsage:
    """Sliding-window traffic shares for the planner's prefetch scoring —
    the sim stand-in for the gateway UsageRollup's EMA step-seconds
    shares (``shares_snapshot`` is the only seam the planner reads)."""

    def __init__(self):
        self.counts: dict[str, float] = {}

    def note(self, adapter: str | None) -> None:
        if adapter:
            self.counts[adapter] = self.counts.get(adapter, 0.0) + 1.0

    def decay(self, factor: float = 0.9) -> None:
        # Slow decay (~10-tick horizon): a one-shot tail request must
        # fade well below the head adapters' steady shares, or transient
        # spikes look hot enough to replicate.
        self.counts = {a: c * factor for a, c in self.counts.items()
                       if c * factor > 1e-3}

    def shares_snapshot(self) -> dict:
        total = sum(self.counts.values())
        if total <= 0:
            return {}
        return {("m", a): c / total for a, c in self.counts.items()}


def simulate(
    policy: str,
    workload: WorkloadConfig,
    n_servers: int = 6,
    latency: LatencyModel = V5E_DEFAULT,
    decode_slots: int = 16,
    admission: "AdmissionConfig | None" = None,
    max_adapters: int = 4,
    host_cache: int = 0,
    preload_all: bool = False,
    planner_cfg=None,
    planner_tick_s: float = 1.0,
) -> SimResult:
    """``policy`` may carry a ``_queued`` suffix (e.g. ``production_queued``):
    sheds then park in the REAL TierQueues policy (gateway
    scheduling.admission) and re-route as capacity frees — the A/B of
    queueing vs pure shedding runs the exact code that deploys."""
    import dataclasses

    from llm_instance_gateway_tpu.gateway.scheduling.admission import TierQueues
    from llm_instance_gateway_tpu.gateway.scheduling.config import (
        AdmissionConfig,
        SchedulerConfig,
        drain_scaled,
    )

    queued = policy.endswith("_queued")
    base_policy = policy[: -len("_queued")] if queued else policy
    preload = None
    if preload_all:
        preload = ([universe_name(k)
                    for k in range(workload.adapter_universe)]
                   if workload.adapter_universe > 0
                   else list(workload.adapters))
    servers = [
        SimServer(f"sim-{i}", latency, decode_slots=decode_slots,
                  max_adapters=(max(max_adapters, len(preload))
                                if preload else max_adapters),
                  host_cache_slots=host_cache, preload=preload)
        for i in range(n_servers)
    ]
    loop = EventLoop(servers)
    # One shared affinity index for arrival AND drain routers — the live
    # wiring (bootstrap injects one shared_prefix_index into both); split
    # indexes would learn conflicting holders.
    prefix_index = None
    if base_policy == "production_affinity":
        from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
            PrefixIndex,
        )

        prefix_index = PrefixIndex()
    # Placement policy: the REAL PlacementPlanner over the sim provider —
    # its pure decision core (plan()) runs against simulated residency/
    # load/share state, its decisions apply through the same verbs the
    # lora_sidecar drives on a live replica.  This is the sim-validation
    # gate the ROADMAP requires before live rollout.
    planner = sim_usage = None
    if base_policy == "production_placement":
        from llm_instance_gateway_tpu.gateway.placement import (
            PlacementConfig,
            PlacementPlanner,
        )

        sim_usage = _SimUsage()
        planner = PlacementPlanner(
            _SimProvider(servers), usage=sim_usage,
            cfg=planner_cfg or PlacementConfig(mode="prefer_resident"))
    router = make_router(base_policy, servers, seed=workload.seed,
                         prefix_index=prefix_index,
                         placement_advisor=planner)
    requests = generate_workload(workload)
    result = SimResult(policy=policy, qps=workload.qps)

    acfg = admission or AdmissionConfig(
        enabled=True, max_wait_s=10.0, max_depth=512, retry_interval_s=0.05)
    tq = TierQueues(acfg, pyrandom.Random(workload.seed)) if queued else None
    # The drain re-admits against hysteresis-scaled thresholds, exactly as
    # the live AdmissionController does (config.drain_scaled).
    drain_router = router
    if queued and base_policy in ("production", "production_affinity",
                                  "production_placement"):
        drain_router = make_router(
            base_policy, servers, seed=workload.seed,
            scheduler_cfg=drain_scaled(dataclasses.replace(
                SchedulerConfig(), admission=acfg)),
            prefix_index=prefix_index,
            placement_advisor=planner,
        )
    parked_at: dict[int, float] = {}

    def shed(req: SimRequest) -> None:
        req.shed = True
        result.shed += 1

    def queue_tier(req: SimRequest) -> str:
        # Unweighted tiers (Critical parked during an empty-membership
        # window) drain at the highest configured weight — TierQueues
        # handles it; the sim must not pre-coerce or the A/B wouldn't
        # exercise the live code path.
        return req.tier

    def arrival(req: SimRequest):
        def fire(lp: EventLoop):
            if sim_usage is not None:
                sim_usage.note(req.adapter)
            try:
                server = router(req)
            except SchedulingError:
                if tq is None:
                    shed(req)
                    return
                accepted, evicted = tq.push(queue_tier(req), req)
                if evicted is not None:
                    # Full-queue inversion fix carried into the sim: a
                    # higher-weight arrival evicts (sheds) the newest
                    # lowest-weight occupant instead of shedding itself.
                    parked_at.pop(evicted.rid, None)
                    shed(evicted)
                if accepted:
                    parked_at[req.rid] = lp.now
                else:
                    shed(req)
                return
            server.prefill_queue.append(req)
            lp.kick(server)

        return fire

    end_s = workload.duration_s * 3

    def pump(lp: EventLoop):
        """Virtual-time drain loop: weighted-dequeue parked requests while
        the filter tree admits again (the dequeueing_signal equivalent)."""
        while True:
            req = tq.pop_weighted()
            if req is None:
                break
            t0 = parked_at.pop(req.rid, lp.now)
            if lp.now - t0 > acfg.max_wait_s:
                shed(req)  # waited out its window -> 429
                continue
            try:
                server = drain_router(req)
            except SchedulingError:
                tq.push_front(queue_tier(req), req)
                parked_at[req.rid] = t0
                break  # still saturated; retry next tick
            server.prefill_queue.append(req)
            lp.kick(server)
        if lp.now + acfg.retry_interval_s < end_s:
            lp.schedule(lp.now + acfg.retry_interval_s, pump)

    by_name_all = {s.pod.name: s for s in servers}

    def planner_pump(lp: EventLoop):
        """Virtual-time planner tick: the REAL planner plans over the sim
        provider's metrics; decisions apply through the SimServer's tier
        verbs (the sidecar-wire equivalent)."""
        planner.tick(now=lp.now)
        for d in planner.debug_payload()["decisions"]:
            server = by_name_all.get(d["pod"])
            if server is None:
                continue
            if d["action"] in ("prefetch", "migrate"):
                server.host_prefetch(d["adapter"])
            elif d["action"] == "demote":
                server.demote(d["adapter"])
            elif d["action"] == "evict":
                server.evict_host(d["adapter"])
        sim_usage.decay()
        if lp.now + planner_tick_s < end_s:
            lp.schedule(lp.now + planner_tick_s, planner_pump)

    for req in requests:
        loop.schedule(req.arrival_s, arrival(req))
    if tq is not None:
        loop.schedule(acfg.retry_interval_s, pump)
    if planner is not None:
        loop.schedule(planner_tick_s, planner_pump)
    # Drain: run past the workload end until queues flush.
    loop.run(until=end_s)

    for req in requests:
        result.tier_totals[req.tier] = result.tier_totals.get(req.tier, 0) + 1
        ok = (not req.shed and req.t_done >= 0
              and req.latency_per_output_token_s <= req.slo_s_per_token)
        if ok:
            result.tier_hits[req.tier] = result.tier_hits.get(req.tier, 0) + 1
        if req.shed or req.t_done < 0:
            continue  # shed, or still in flight at drain cutoff
        result.completed += 1
        result.tokens += req.generated
        result.ttfts.append(req.ttft_s)
        if workload.adapter_universe > 0 and req.adapter:
            result.ttft_by_adapter.setdefault(
                req.adapter, []).append((req.arrival_s, req.ttft_s))
        lpt = req.latency_per_output_token_s
        result.per_token.append(lpt)
        result.slo_total += 1
        if lpt <= req.slo_s_per_token:
            result.slo_hits += 1
    for s in servers:
        result.prefix_hits += s.prefix_hits
        result.prefix_misses += s.prefix_misses
        result.prefix_reused_tokens += s.prefix_reused_tokens
        result.disk_loads += s.disk_loads
        result.host_promotes += s.host_promotes
        result.demotions += s.demotions
    return result


def hot_set(universe: int, zipf: float, coverage: float = 0.5) -> list[str]:
    """Smallest Zipf-rank prefix of the universe covering at least
    ``coverage`` of expected adapter traffic — the 'hot set' whose p99
    TTFT the placement acceptance bar constrains."""
    weights = zipf_weights(universe, zipf)
    acc, names = 0.0, []
    for k, w in enumerate(weights):
        names.append(universe_name(k))
        acc += w
        if acc >= coverage:
            break
    return names


def run_placement_scenario(
    universe: int = 1000,
    zipf: float = 1.1,
    qps: float = 30.0,
    duration_s: float = 120.0,
    n_servers: int = 6,
    max_adapters: int = 16,
    host_cache: int = 128,
    hot_coverage: float = 0.5,
    seed: int = 0,
) -> dict:
    """The placement plane's target scenario (ROADMAP item 2, CPU-
    deterministic, seeded): a long-tail adapter universe where <10% of
    adapters fit slot-resident, Zipf traffic, two cells:

    - ``all_resident``: every adapter preloaded on every replica (the
      physically-impossible-at-scale upper bound the bar compares to).
    - ``tiered``: small slot sets + host-RAM caches, the REAL
      PlacementPlanner prefetching/demoting/evicting on its tick, and
      prefer_resident routing steering picks to resident replicas.

    Acceptance: hot-set (top adapters covering ``hot_coverage`` of
    traffic) p99 TTFT in the tiered cell within 2x the all-resident cell.
    """
    from llm_instance_gateway_tpu.gateway.placement import PlacementConfig

    wl = WorkloadConfig(qps=qps, duration_s=duration_s,
                        adapter_universe=universe, adapter_zipf=zipf,
                        adapter_fraction=1.0, seed=seed)
    base = simulate("production", wl, n_servers=n_servers,
                    preload_all=True)
    # Planner knobs for the long-tail shape: the head-replication bar
    # sits just under the hot set's smallest steady share, and the
    # action budget covers re-replication across the whole pool in one
    # tick (head adapters evicted by tail churn self-heal next tick).
    planner_cfg = PlacementConfig(
        mode="prefer_resident", idle_share=0.005,
        prefetch_min_share=0.015, evict_idle_ticks=12,
        max_actions_per_tick=64)
    tiered = simulate("production_placement", wl, n_servers=n_servers,
                      max_adapters=max_adapters, host_cache=host_cache,
                      planner_cfg=planner_cfg, planner_tick_s=1.0)
    hot = set(hot_set(universe, zipf, hot_coverage))
    # Steady-state window: the first stretch of the run is the cold fill
    # (every adapter's FIRST touch is an unavoidable disk restore — the
    # all-resident cell skips that cost by construction); the acceptance
    # bar is about serving the hot set once the ladder has settled.
    # Applied to BOTH cells equally.
    warmup_s = 0.25 * duration_s

    def hot_p99(res: SimResult) -> float:
        vals = sorted(v for a, lst in res.ttft_by_adapter.items()
                      if a in hot for (t, v) in lst if t >= warmup_s)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

    base_p99, tiered_p99 = hot_p99(base), hot_p99(tiered)
    slot_capacity = n_servers * max_adapters
    report = {
        "scenario": "placement_tiered_vs_all_resident",
        "universe": universe, "zipf_s": zipf, "qps": qps,
        "duration_s": duration_s, "n_servers": n_servers,
        "max_adapters": max_adapters, "host_cache": host_cache,
        "seed": seed,
        "resident_fraction": round(slot_capacity / universe, 4),
        "hot_set_size": len(hot),
        "hot_coverage": hot_coverage,
        "cells": {
            "all_resident": dict(base.summary(),
                                 hot_ttft_p99_s=round(base_p99, 4)),
            "tiered": dict(tiered.summary(),
                           hot_ttft_p99_s=round(tiered_p99, 4),
                           disk_loads=tiered.disk_loads,
                           host_promotes=tiered.host_promotes,
                           demotions=tiered.demotions),
        },
        "hot_ttft_p99_ratio": round(tiered_p99 / base_p99, 3)
        if base_p99 > 0 else None,
        # The acceptance bar: <10% resident AND hot-set p99 within 2x.
        "ok": (slot_capacity < 0.1 * universe
               and base_p99 > 0 and tiered_p99 <= 2.0 * base_p99),
    }
    return report


def run_decode_lever_scenario(seed: int = 0) -> dict:
    """CPU-deterministic simulator twin of the engine decode levers
    (PR 15): the LatencyModel's ``steps_per_dispatch`` and
    ``stream_lanes`` knobs driven over a fixed workload, committed as
    ``SIM_DECODE_LEVERS.json`` so the elastic-topology autoscaler work
    (ROADMAP item 3) can reuse this PR's cost model without re-deriving
    it.

    Two cells per lever, single server, direct-driven (no router):

    - **fused dispatch**: 8 decode-heavy requests at steps-per-dispatch
      1 vs 8 — tokens/s ratio shows the dispatch-base amortization the
      adaptive planner buys (bounded by decode_base_s's share of a step).
    - **stream lanes**: a 2048-token prompt ahead of a 512-token prompt
      plus short traffic, chunk 256, lanes 1 vs 2 — the second prompt's
      TTFT stops serializing behind the first.
    """
    import dataclasses as _dc

    def drive(server: SimServer, reqs: "list[SimRequest]") -> float:
        now = 0.0
        for r in reqs:
            server.prefill_queue.append(r)
        while (server.prefill_queue or server.active or server.streaming):
            d = server.step(now)
            now += d if d > 0 else 0.01
        return now

    def fused_cell(steps: int) -> dict:
        lat = _dc.replace(V5E_DEFAULT, steps_per_dispatch=steps)
        server = SimServer("sim-0", lat, decode_slots=8)
        reqs = [SimRequest(rid=i, arrival_s=0.0, prompt_tokens=128,
                           output_tokens=256, model="m")
                for i in range(8)]
        wall = drive(server, reqs)
        toks = server.tokens_generated
        return {"steps_per_dispatch": steps, "wall_s": round(wall, 4),
                "tokens": toks, "tok_per_s": round(toks / wall, 1)}

    def lane_cell(lanes: int) -> dict:
        lat = _dc.replace(V5E_DEFAULT, stream_lanes=lanes)
        server = SimServer("sim-0", lat, decode_slots=8,
                           kv_capacity_tokens=16384, chunk_tokens=256)
        long_a = SimRequest(rid=0, arrival_s=0.0, prompt_tokens=2048,
                            output_tokens=32, model="m")
        long_b = SimRequest(rid=1, arrival_s=0.0, prompt_tokens=512,
                            output_tokens=32, model="m")
        shorts = [SimRequest(rid=2 + i, arrival_s=0.0, prompt_tokens=64,
                             output_tokens=64, model="m") for i in range(2)]
        wall = drive(server, [long_a, long_b, *shorts])
        return {"stream_lanes": lanes, "wall_s": round(wall, 4),
                "second_long_ttft_s": round(long_b.ttft_s, 4),
                "first_long_ttft_s": round(long_a.ttft_s, 4)}

    one, eight = fused_cell(1), fused_cell(8)
    lane1, lane2 = lane_cell(1), lane_cell(2)
    return {
        "scenario": "decode_levers",
        "seed": seed,
        "latency_model": "v5e_default",
        "fused_dispatch": {
            "cells": [one, eight],
            "tok_per_s_ratio": round(
                eight["tok_per_s"] / one["tok_per_s"], 4),
        },
        "stream_lanes": {
            "cells": [lane1, lane2],
            "second_ttft_ratio": round(
                lane1["second_long_ttft_s"] / lane2["second_long_ttft_s"],
                4) if lane2["second_long_ttft_s"] > 0 else None,
        },
        # The reuse contract for item-3: both levers visible in the cost
        # model, deterministically.
        "ok": (eight["tok_per_s"] > one["tok_per_s"]
               and lane2["second_long_ttft_s"]
               < lane1["second_long_ttft_s"]),
    }


def twin_ttft_p95(
    model: LatencyModel,
    rate_rps: float,
    prompt_mean: float = 202.0,
    output_mean: float = 179.0,
    decode_slots: int = 16,
    duration_s: float = 4.0,
    seed: int = 0,
) -> float:
    """TTFT p95 of ONE simulated server at ``rate_rps`` — the capacity
    twin's probe primitive.  Right-censored requests (still queued at the
    drain cutoff, i.e. the server is past its knee) count as +inf samples
    so saturation reads as a breach instead of vanishing from the
    percentile; a run completing <70% of its arrivals is saturated outright.
    """
    if rate_rps <= 0:
        return 0.0
    wl = WorkloadConfig(
        qps=rate_rps, duration_s=duration_s,
        prompt_mean=prompt_mean, prompt_std=max(1.0, 0.1 * prompt_mean),
        output_mean=output_mean, output_std=max(1.0, 0.1 * output_mean),
        critical_fraction=0.0, sheddable_fraction=0.0,
        adapter_fraction=0.0, seed=seed)
    res = simulate("least_queue", wl, n_servers=1, latency=model,
                   decode_slots=decode_slots)
    total = sum(res.tier_totals.values())
    if total == 0:
        return 0.0
    censored = total - res.completed  # still queued at cutoff, or shed
    if not res.ttfts or res.completed < 0.7 * total:
        return float("inf")
    vals = sorted(res.ttfts) + [float("inf")] * censored
    return vals[min(len(vals) - 1, int(0.95 * len(vals)))]


def twin_knee_rate(
    model: LatencyModel,
    prompt_mean: float = 202.0,
    output_mean: float = 179.0,
    slo_ttft_s: float = 0.5,
    decode_slots: int = 16,
    duration_s: float = 4.0,
    seed: int = 0,
    probes: int = 5,
) -> float:
    """Per-server knee: the highest arrival rate whose simulated TTFT p95
    still meets ``slo_ttft_s``, found by bisection between an analytic
    bracket's edges.  The analytic bound (slots over slot-residency time,
    min'd with the prefill service rate) seeds the bracket so the DES
    probes stay cheap (tens of requests each); a pool's knee is this times
    its replica count (capacity scales linearly in replicas under
    least-queue routing, which the gateway's production tree approximates
    at saturation)."""
    kv_est = decode_slots * (prompt_mean + output_mean / 2.0)
    step_s = model.decode_s(kv_est, decode_slots)
    decode_bound = decode_slots / max(1e-9, output_mean * step_s)
    prefill_bound = 1.0 / max(1e-9, model.prefill_s(prompt_mean))
    lo, hi = 0.0, 2.0 * min(decode_bound, prefill_bound)

    def probe(rate: float) -> float:
        return twin_ttft_p95(model, rate, prompt_mean=prompt_mean,
                             output_mean=output_mean,
                             decode_slots=decode_slots,
                             duration_s=duration_s, seed=seed)

    for _ in range(4):  # grow until the top of the bracket breaches
        if probe(hi) > slo_ttft_s:
            break
        lo, hi = hi, hi * 2.0
    for _ in range(probes):
        mid = (lo + hi) / 2.0
        if probe(mid) <= slo_ttft_s:
            lo = mid
        else:
            hi = mid
    return lo


def run_twin_scenario(seed: int = 0,
                      calibration_path: str | None = None) -> dict:
    """The ``make sim-check`` pre-merge gate (ROADMAP item 5's throughput
    proxy): CPU-deterministic, seeded, three assertions in one report:

    1. **Calibration recovery** — observables generated from the known
       ``V5E_DEFAULT`` model round-trip through
       ``calibrate_from_observables`` with every fitted constant within
       10% (the twin's self-calibration is trustworthy).
    2. **Committed artifact** — the fit reproduces
       ``TWIN_CALIBRATION.json`` byte-for-byte (the repo's committed twin
       constants are exactly what the code produces).
    3. **Knee sanity** — the fitted model's knee rate (bisected TTFT-p95
       DES probes) separates load: 60% of knee meets the TTFT SLO, 160%
       breaches it (the headroom forecast's foundation discriminates).
    """
    import os

    from llm_instance_gateway_tpu.sim import calibrate as cal

    slo_ttft_s = 0.5
    obs = cal.sim_observables(V5E_DEFAULT, seed=seed, windows=24)
    fitted, residuals = cal.calibrate_from_observables(obs)
    truth = {
        "prefill_base_s": V5E_DEFAULT.prefill_base_s,
        "prefill_per_token_s": V5E_DEFAULT.prefill_per_token_s,
        "decode_base_s": V5E_DEFAULT.decode_base_s,
        "decode_per_kv_token_s": V5E_DEFAULT.decode_per_kv_token_s,
        "decode_per_seq_s": V5E_DEFAULT.decode_per_seq_s,
    }
    errors = {k: round(abs(getattr(fitted, k) - v) / v, 4)
              for k, v in truth.items()}
    recovered = max(errors.values()) <= 0.10

    if calibration_path is None:
        calibration_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "TWIN_CALIBRATION.json")
    artifact_ok = False
    artifact_err = ""
    try:
        committed, _art = cal.load_calibration(calibration_path)
        artifact_ok = cal.model_to_dict(committed) == cal.model_to_dict(fitted)
        if not artifact_ok:
            artifact_err = "committed constants differ from the seeded fit"
    except (OSError, ValueError, KeyError) as e:
        artifact_err = str(e)

    knee = twin_knee_rate(fitted, slo_ttft_s=slo_ttft_s, seed=seed)
    under = twin_ttft_p95(fitted, 0.6 * knee, seed=seed)
    over = twin_ttft_p95(fitted, 1.6 * knee, seed=seed)
    knee_ok = knee > 0 and under <= slo_ttft_s < over

    return {
        "scenario": "twin_calibration",
        "seed": seed,
        "latency_model": "v5e_default",
        "slo_ttft_s": slo_ttft_s,
        "fit": {"constants": cal.model_to_dict(fitted),
                "relative_errors": errors,
                "residuals": residuals,
                "recovered_within_10pct": recovered},
        "artifact": {"path": calibration_path, "ok": artifact_ok,
                     **({"error": artifact_err} if artifact_err else {})},
        "knee": {"knee_rps_per_server": round(knee, 3),
                 "ttft_p95_at_60pct_s": round(under, 4),
                 "ttft_p95_at_160pct_s": (round(over, 4)
                                          if over != float("inf") else "inf"),
                 "ok": knee_ok},
        "ok": recovered and artifact_ok and knee_ok,
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="routing-policy simulator")
    parser.add_argument("--qps", type=float, nargs="+", default=[20.0, 30.0])
    parser.add_argument("--policies", nargs="+",
                        default=["random", "least_queue", "production",
                                 "production_queued"])
    parser.add_argument("--servers", type=int, default=6)
    parser.add_argument("--duration", type=float, default=120.0)
    parser.add_argument("--latency-model", choices=["v5e", "a100"], default="v5e")
    parser.add_argument("--session-fraction", type=float, default=0.0,
                        help="fraction of requests carrying a shared session "
                             "prefix (enables the prefix-affinity A/B)")
    parser.add_argument("--sessions", type=int, default=64)
    parser.add_argument("--prefix-tokens", type=int, default=1024)
    parser.add_argument("--csv", default=None, metavar="PATH",
                        help="also write results as CSV (reference main.py parity)")
    parser.add_argument("--adapter-universe", type=int, default=0,
                        help="long-tail adapter universe size (>0 replaces "
                             "the fixed adapter tuple with a seeded Zipf "
                             "draw — the placement plane's traffic shape)")
    parser.add_argument("--adapter-zipf", type=float, default=1.1,
                        help="Zipf exponent for --adapter-universe traffic")
    parser.add_argument("--placement-scenario", action="store_true",
                        help="run the tiered-vs-all-resident placement "
                             "acceptance scenario (1000-adapter Zipf by "
                             "default) and print its report instead of the "
                             "policy sweep")
    parser.add_argument("--decode-lever-scenario", action="store_true",
                        help="run the deterministic decode-lever scenario "
                             "(steps-per-dispatch and stream-lane knobs; "
                             "the committed SIM_DECODE_LEVERS.json) and "
                             "print its report instead of the policy sweep")
    parser.add_argument("--twin-scenario", action="store_true",
                        help="run the deterministic capacity-twin "
                             "calibration scenario (the `make sim-check` "
                             "pre-merge gate: calibration recovery, "
                             "committed TWIN_CALIBRATION.json "
                             "reproduction, knee sanity) and print its "
                             "report instead of the policy sweep")
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="also write the placement-scenario report JSON "
                             "to this path (the committed artifact)")
    args = parser.parse_args(argv)
    latency = V5E_DEFAULT if args.latency_model == "v5e" else A100_VLLM
    if args.twin_scenario:
        report = run_twin_scenario()
        print(json.dumps(report, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
        raise SystemExit(0 if report["ok"] else 1)
    if args.decode_lever_scenario:
        report = run_decode_lever_scenario()
        print(json.dumps(report, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
        raise SystemExit(0 if report["ok"] else 1)
    if args.placement_scenario:
        universe = args.adapter_universe or 1000
        report = run_placement_scenario(
            universe=universe, zipf=args.adapter_zipf,
            qps=args.qps[0] if args.qps else 30.0,
            duration_s=args.duration, n_servers=args.servers)
        print(json.dumps(report, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
        raise SystemExit(0 if report["ok"] else 1)
    rows = []
    for qps in args.qps:
        for policy in args.policies:
            cfg = WorkloadConfig(qps=qps, duration_s=args.duration,
                                 session_fraction=args.session_fraction,
                                 n_sessions=args.sessions,
                                 session_prefix_tokens=args.prefix_tokens,
                                 adapter_universe=args.adapter_universe,
                                 adapter_zipf=args.adapter_zipf)
            result = simulate(policy, cfg, n_servers=args.servers, latency=latency)
            summary = result.summary()
            rows.append(summary)
            print(json.dumps(summary))
    if args.csv:
        import csv

        with open(args.csv, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=list(rows[0]))
            writer.writeheader()
            writer.writerows(rows)


if __name__ == "__main__":
    main()
