"""Simulation driver: workload generation, routing sweep, metrics report.

Parity: reference ``src/main.py:13-363`` — sweep QPS x routing policy, emit
per-tier TTFT / TPOT / latency-per-token / SLO-attainment / shed counts.
Workload matches the reference's ShareGPT-derived shape: prompt ~N(202,20),
output ~N(179,17) (``main.py:20-27``), split across criticality tiers with
the reference's 25 ms / 500 ms per-output-token SLOs, plus a LoRA adapter
mix for affinity-sensitive policies.

Routing policies:
- ``random``      uniform over replicas (reference loadbalancer 'random')
- ``least_queue`` min prefill backlog    (reference 'leastPseudo')
- ``least_kv``    min KV utilization     (reference 'least')
- ``least_latency`` min estimated latency/token from live queue state and
  the calibrated latency model (reference 'leastlatency')
- ``production``  the REAL filter tree (gateway.scheduling.Scheduler) over
  live simulated metrics — criticality tiers, LoRA affinity, shedding; what
  the deployed gateway actually does (reference 'smart', minus drift).

Run:  python -m llm_instance_gateway_tpu.sim.run --qps 20 30 --policies random production
"""

from __future__ import annotations

import argparse
import json
import random as pyrandom
from dataclasses import dataclass, field

from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
    Scheduler,
    SchedulingError,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
from llm_instance_gateway_tpu.sim.core import (
    A100_VLLM,
    EventLoop,
    LatencyModel,
    SimRequest,
    SimServer,
    V5E_DEFAULT,
)


@dataclass
class WorkloadConfig:
    qps: float = 20.0
    duration_s: float = 120.0
    prompt_mean: float = 202.0   # main.py:20-27
    prompt_std: float = 20.0
    output_mean: float = 179.0
    output_std: float = 17.0
    critical_fraction: float = 0.3
    sheddable_fraction: float = 0.2
    adapters: tuple[str, ...] = ("sql-lora", "tweet-lora", "chat-lora", "code-lora")
    adapter_fraction: float = 0.8
    slo_critical_s: float = 0.025   # notebook cell 18 tiers
    slo_default_s: float = 0.5
    # Session-prefix traffic (multi-turn chat / per-tenant templates): this
    # fraction of requests carries one of ``n_sessions`` shared prefixes of
    # ``session_prefix_tokens`` — a replica holding the prefix in its cache
    # prefills only the suffix, and prefix-affinity routing tries to land
    # repeats on that replica.
    session_fraction: float = 0.0
    n_sessions: int = 64
    session_prefix_tokens: int = 1024
    seed: int = 0


def generate_workload(cfg: WorkloadConfig) -> list[SimRequest]:
    rng = pyrandom.Random(cfg.seed)
    reqs: list[SimRequest] = []
    t = 0.0
    rid = 0
    while t < cfg.duration_s:
        t += rng.expovariate(cfg.qps)
        u = rng.random()
        critical = u < cfg.critical_fraction
        sheddable = u > 1.0 - cfg.sheddable_fraction
        adapter = (
            rng.choice(cfg.adapters)
            if rng.random() < cfg.adapter_fraction
            else None
        )
        is_critical = critical and not sheddable
        prompt = max(8, int(rng.gauss(cfg.prompt_mean, cfg.prompt_std)))
        prefix_id = None
        prefix_tokens = 0
        if cfg.session_fraction and rng.random() < cfg.session_fraction:
            prefix_id = rng.randrange(cfg.n_sessions)
            prefix_tokens = cfg.session_prefix_tokens
            prompt += prefix_tokens  # suffix stays the base distribution
        reqs.append(
            SimRequest(
                rid=rid,
                arrival_s=t,
                prompt_tokens=prompt,
                output_tokens=max(4, int(rng.gauss(cfg.output_mean, cfg.output_std))),
                model=adapter or "base",
                adapter=adapter,
                critical=is_critical,
                tier=("Critical" if is_critical
                      else "Sheddable" if sheddable else "Default"),
                slo_s_per_token=cfg.slo_critical_s if critical else cfg.slo_default_s,
                prefix_id=prefix_id,
                prefix_tokens=prefix_tokens,
            )
        )
        rid += 1
    return reqs


class _SimProvider:
    def __init__(self, servers: list[SimServer]):
        self.servers = servers

    def all_pod_metrics(self):
        return [s.metrics() for s in self.servers]


def make_router(policy: str, servers: list[SimServer], seed: int = 0,
                scheduler_cfg=None, prefix_index=None):
    rng = pyrandom.Random(seed)
    by_name = {s.pod.name: s for s in servers}
    if policy == "random":
        return lambda req: rng.choice(servers)
    if policy == "least_queue":
        return lambda req: min(servers, key=lambda s: len(s.prefill_queue) + len(s.active))
    if policy == "least_kv":
        return lambda req: min(servers, key=lambda s: -s.kv_free())
    if policy == "least_latency":
        # Reference 'leastlatency' (loadbalancer.py:34-85): estimate each
        # pod's expected latency/token for a NEW request from its live queue
        # state and the calibrated latency model, route to the minimum.
        def est(s: SimServer, prompt_tokens: int) -> float:
            lm = s.latency
            batch = len(s.active) + 1
            kv = sum(a.kv_tokens for a in s.active) + prompt_tokens
            step = (lm.decode_base_s + lm.decode_per_kv_token_s * kv
                    + lm.decode_per_seq_s * batch)
            # Prefill backlog ahead of this request delays its first token
            # and steals decode cycles from the batch it joins.
            backlog = sum(
                max(lm.prefill_min_s,
                    lm.prefill_base_s + lm.prefill_per_token_s * r.prompt_tokens)
                for r in s.prefill_queue)
            return step + backlog / max(batch, 1)

        return lambda req: min(
            servers, key=lambda s: est(s, req.prompt_tokens))
    if policy in ("production", "production_affinity"):
        kwargs = {} if scheduler_cfg is None else {"cfg": scheduler_cfg}
        # ``production`` is the no-affinity baseline; ``_affinity`` adds the
        # prefix-cache-aware tie-break (scheduling/prefix_affinity.py) —
        # the session prefix_id stands in for the chained prompt hashes.
        scheduler = Scheduler(_SimProvider(servers),
                              rng=pyrandom.Random(seed),
                              prefix_aware=(policy == "production_affinity"),
                              prefix_index=prefix_index,
                              **kwargs)

        def route(req: SimRequest):
            llm_req = LLMRequest(
                model=req.model,
                resolved_target_model=req.adapter or req.model,
                critical=req.critical,
                prompt_tokens=req.prompt_tokens,
                prefix_hashes=((req.prefix_id + 1,)
                               if req.prefix_id is not None else ()),
            )
            pod = scheduler.schedule(llm_req)  # may raise SchedulingError
            return by_name[pod.name]

        return route
    raise ValueError(f"unknown policy {policy!r}")


@dataclass
class SimResult:
    policy: str
    qps: float
    completed: int = 0
    shed: int = 0
    ttfts: list[float] = field(default_factory=list)
    per_token: list[float] = field(default_factory=list)
    slo_hits: int = 0
    slo_total: int = 0
    tokens: int = 0

    # Per-tier GOODPUT: requests finishing within their SLO over ALL tier
    # requests (shed and unfinished count as misses) — the honest number for
    # comparing queueing vs shedding, where "served more, slower" and "served
    # fewer, faster" must be weighed on one scale.
    tier_hits: dict = field(default_factory=dict)
    tier_totals: dict = field(default_factory=dict)
    # Prefix-cache outcome (session traffic): replica-side hit counts.
    prefix_hits: int = 0
    prefix_misses: int = 0
    prefix_reused_tokens: int = 0

    def goodput(self, tier: str) -> float:
        total = self.tier_totals.get(tier, 0)
        return self.tier_hits.get(tier, 0) / total if total else 1.0

    def summary(self) -> dict:
        def pct(vals, p):
            if not vals:
                return 0.0
            vals = sorted(vals)
            return vals[min(len(vals) - 1, int(p * len(vals)))]

        return {
            "policy": self.policy,
            "qps": self.qps,
            "completed": self.completed,
            "shed": self.shed,
            "tokens": self.tokens,
            "ttft_p50_s": round(pct(self.ttfts, 0.5), 4),
            "ttft_p99_s": round(pct(self.ttfts, 0.99), 4),
            "latency_per_token_p50_s": round(pct(self.per_token, 0.5), 5),
            "slo_attainment": round(self.slo_hits / self.slo_total, 4)
            if self.slo_total else 1.0,
            "slo_goodput_by_tier": {
                t: round(self.goodput(t), 4) for t in sorted(self.tier_totals)
            },
            **({"prefix_hit_rate": round(
                    self.prefix_hits
                    / max(1, self.prefix_hits + self.prefix_misses), 4),
                "prefix_reused_tokens": self.prefix_reused_tokens}
               if self.prefix_hits + self.prefix_misses else {}),
        }


def simulate(
    policy: str,
    workload: WorkloadConfig,
    n_servers: int = 6,
    latency: LatencyModel = V5E_DEFAULT,
    decode_slots: int = 16,
    admission: "AdmissionConfig | None" = None,
) -> SimResult:
    """``policy`` may carry a ``_queued`` suffix (e.g. ``production_queued``):
    sheds then park in the REAL TierQueues policy (gateway
    scheduling.admission) and re-route as capacity frees — the A/B of
    queueing vs pure shedding runs the exact code that deploys."""
    import dataclasses

    from llm_instance_gateway_tpu.gateway.scheduling.admission import TierQueues
    from llm_instance_gateway_tpu.gateway.scheduling.config import (
        AdmissionConfig,
        SchedulerConfig,
        drain_scaled,
    )

    queued = policy.endswith("_queued")
    base_policy = policy[: -len("_queued")] if queued else policy
    servers = [
        SimServer(f"sim-{i}", latency, decode_slots=decode_slots)
        for i in range(n_servers)
    ]
    loop = EventLoop(servers)
    # One shared affinity index for arrival AND drain routers — the live
    # wiring (bootstrap injects one shared_prefix_index into both); split
    # indexes would learn conflicting holders.
    prefix_index = None
    if base_policy == "production_affinity":
        from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
            PrefixIndex,
        )

        prefix_index = PrefixIndex()
    router = make_router(base_policy, servers, seed=workload.seed,
                         prefix_index=prefix_index)
    requests = generate_workload(workload)
    result = SimResult(policy=policy, qps=workload.qps)

    acfg = admission or AdmissionConfig(
        enabled=True, max_wait_s=10.0, max_depth=512, retry_interval_s=0.05)
    tq = TierQueues(acfg, pyrandom.Random(workload.seed)) if queued else None
    # The drain re-admits against hysteresis-scaled thresholds, exactly as
    # the live AdmissionController does (config.drain_scaled).
    drain_router = router
    if queued and base_policy in ("production", "production_affinity"):
        drain_router = make_router(
            base_policy, servers, seed=workload.seed,
            scheduler_cfg=drain_scaled(dataclasses.replace(
                SchedulerConfig(), admission=acfg)),
            prefix_index=prefix_index,
        )
    parked_at: dict[int, float] = {}

    def shed(req: SimRequest) -> None:
        req.shed = True
        result.shed += 1

    def queue_tier(req: SimRequest) -> str:
        # Unweighted tiers (Critical parked during an empty-membership
        # window) drain at the highest configured weight — TierQueues
        # handles it; the sim must not pre-coerce or the A/B wouldn't
        # exercise the live code path.
        return req.tier

    def arrival(req: SimRequest):
        def fire(lp: EventLoop):
            try:
                server = router(req)
            except SchedulingError:
                if tq is None:
                    shed(req)
                    return
                accepted, evicted = tq.push(queue_tier(req), req)
                if evicted is not None:
                    # Full-queue inversion fix carried into the sim: a
                    # higher-weight arrival evicts (sheds) the newest
                    # lowest-weight occupant instead of shedding itself.
                    parked_at.pop(evicted.rid, None)
                    shed(evicted)
                if accepted:
                    parked_at[req.rid] = lp.now
                else:
                    shed(req)
                return
            server.prefill_queue.append(req)
            lp.kick(server)

        return fire

    end_s = workload.duration_s * 3

    def pump(lp: EventLoop):
        """Virtual-time drain loop: weighted-dequeue parked requests while
        the filter tree admits again (the dequeueing_signal equivalent)."""
        while True:
            req = tq.pop_weighted()
            if req is None:
                break
            t0 = parked_at.pop(req.rid, lp.now)
            if lp.now - t0 > acfg.max_wait_s:
                shed(req)  # waited out its window -> 429
                continue
            try:
                server = drain_router(req)
            except SchedulingError:
                tq.push_front(queue_tier(req), req)
                parked_at[req.rid] = t0
                break  # still saturated; retry next tick
            server.prefill_queue.append(req)
            lp.kick(server)
        if lp.now + acfg.retry_interval_s < end_s:
            lp.schedule(lp.now + acfg.retry_interval_s, pump)

    for req in requests:
        loop.schedule(req.arrival_s, arrival(req))
    if tq is not None:
        loop.schedule(acfg.retry_interval_s, pump)
    # Drain: run past the workload end until queues flush.
    loop.run(until=end_s)

    for req in requests:
        result.tier_totals[req.tier] = result.tier_totals.get(req.tier, 0) + 1
        ok = (not req.shed and req.t_done >= 0
              and req.latency_per_output_token_s <= req.slo_s_per_token)
        if ok:
            result.tier_hits[req.tier] = result.tier_hits.get(req.tier, 0) + 1
        if req.shed or req.t_done < 0:
            continue  # shed, or still in flight at drain cutoff
        result.completed += 1
        result.tokens += req.generated
        result.ttfts.append(req.ttft_s)
        lpt = req.latency_per_output_token_s
        result.per_token.append(lpt)
        result.slo_total += 1
        if lpt <= req.slo_s_per_token:
            result.slo_hits += 1
    for s in servers:
        result.prefix_hits += s.prefix_hits
        result.prefix_misses += s.prefix_misses
        result.prefix_reused_tokens += s.prefix_reused_tokens
    return result


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="routing-policy simulator")
    parser.add_argument("--qps", type=float, nargs="+", default=[20.0, 30.0])
    parser.add_argument("--policies", nargs="+",
                        default=["random", "least_queue", "production",
                                 "production_queued"])
    parser.add_argument("--servers", type=int, default=6)
    parser.add_argument("--duration", type=float, default=120.0)
    parser.add_argument("--latency-model", choices=["v5e", "a100"], default="v5e")
    parser.add_argument("--session-fraction", type=float, default=0.0,
                        help="fraction of requests carrying a shared session "
                             "prefix (enables the prefix-affinity A/B)")
    parser.add_argument("--sessions", type=int, default=64)
    parser.add_argument("--prefix-tokens", type=int, default=1024)
    parser.add_argument("--csv", default=None, metavar="PATH",
                        help="also write results as CSV (reference main.py parity)")
    args = parser.parse_args(argv)
    latency = V5E_DEFAULT if args.latency_model == "v5e" else A100_VLLM
    rows = []
    for qps in args.qps:
        for policy in args.policies:
            cfg = WorkloadConfig(qps=qps, duration_s=args.duration,
                                 session_fraction=args.session_fraction,
                                 n_sessions=args.sessions,
                                 session_prefix_tokens=args.prefix_tokens)
            result = simulate(policy, cfg, n_servers=args.servers, latency=latency)
            summary = result.summary()
            rows.append(summary)
            print(json.dumps(summary))
    if args.csv:
        import csv

        with open(args.csv, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=list(rows[0]))
            writer.writeheader()
            writer.writerows(rows)


if __name__ == "__main__":
    main()
