"""Fit the simulator's latency model from the live engine on real hardware.

The reference calibrated its simulator constants offline against vLLM on
A100 (``constants.py:1-8``, notebook cells 2 & 5); this module does the same
against OUR engine on the TPU it will serve from, so retuned scheduler
thresholds transfer (SURVEY.md §7 step 7: "refit prefill/decode constants to
TPU continuous batching ... before burning TPU hours").

Method: time the engine's jitted prefill across bucket lengths (linear fit
prefill = c0 + c1 * tokens) and decode blocks across batch sizes and cache
fills (least-squares fit decode = c3 + c4 * kv_tokens + c_batch * batch),
all including the host dispatch/readback overhead the serving loop actually
pays.

Run:  python -m llm_instance_gateway_tpu.sim.calibrate          # bench model
"""

from __future__ import annotations

import json
import time

import numpy as np

from llm_instance_gateway_tpu.sim.core import LatencyModel


def _time_call(fn, n: int = 5) -> float:
    """Average seconds per call.  ``fn`` returns a device array; only the
    final handle is synced, so the n dispatches pipeline and the (large,
    on a tunneled chip) per-call host round-trip is amortized instead of
    being paid n times — it would otherwise swamp the per-token slope the
    fit is after."""
    np.asarray(fn())  # warm (compile) + sync
    t0 = time.perf_counter()
    h = None
    for _ in range(n):
        h = fn()
    np.asarray(h)
    return (time.perf_counter() - t0) / n


def calibrate_from_engine(
    engine,
    prefill_lengths: tuple[int, ...] = (64, 128, 256, 384),
    decode_fills: tuple[int, ...] = (32, 128, 256, 448),
    repeats: int = 10,
) -> LatencyModel:
    import jax
    import jax.numpy as jnp

    cfg = engine.model_cfg
    usable = [
        b for b in prefill_lengths
        if b in engine.cfg.prefill_buckets and b < engine.cfg.max_seq_len
    ] or [engine.cfg.prefill_buckets[0]]

    # --- prefill: one padded prompt per bucket, incl. first-token readback.
    xs, ys = [], []
    for bucket in usable:
        tokens = jnp.zeros((1, bucket), jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(bucket), (1, bucket)).astype(jnp.int32)

        def call(bucket=bucket, tokens=tokens, positions=positions):
            first, k, v, _ = engine._jit_prefill(
                engine.params, engine._lora_buffers(), tokens, positions,
                jnp.int32(bucket), jnp.int32(-1),
                jnp.float32(0.0), jnp.int32(0), jnp.float32(1.0),
                jax.random.PRNGKey(0),
            )
            return first

        xs.append(bucket)
        ys.append(_time_call(call, repeats))
    if len(xs) >= 2:
        c1, c0 = np.polyfit(np.asarray(xs, np.float64), np.asarray(ys, np.float64), 1)
        c1 = max(float(c1), 1e-7)
        c0 = max(float(c0), 1e-4)
    else:
        c0, c1 = ys[0], 1e-6

    # --- decode: the engine always steps ALL slots (lockstep batching), so
    # batch size is structurally constant and cannot be a regressor — the
    # varying signal is cache occupancy.  Fit per-step cost against total KV
    # tokens read (b_slots * fill); attribute the batch-proportional part of
    # the base cost to per_seq so the sim scales sanely at other slot counts.
    n_steps = max(1, engine.cfg.decode_steps_per_sync)
    b_slots = engine.cfg.decode_slots
    kv_totals, times = [], []
    for fill in decode_fills:
        if fill >= engine.cfg.max_seq_len:
            continue
        tokens = jnp.zeros((b_slots,), jnp.int32)
        positions = jnp.full((b_slots,), fill, jnp.int32)
        slots = jnp.full((b_slots,), -1, jnp.int32)
        t = jnp.zeros((b_slots,), jnp.float32)
        k = jnp.zeros((b_slots,), jnp.int32)
        p = jnp.ones((b_slots,), jnp.float32)

        remaining = jnp.full((b_slots,), 1 << 20, jnp.int32)  # rows stay live

        def call(tokens=tokens, positions=positions, slots=slots, t=t, k=k,
                 p=p, remaining=remaining):
            out = engine._jit_decode(
                engine.params, engine._lora_buffers(), engine.cache,
                tokens, positions, slots, t, k, p,
                jax.random.PRNGKey(0), remaining, jnp.int32(-1),
                n_steps=n_steps,
            )
            engine.cache = out[-1]  # donated in; reassign the new buffer
            return out[0]

        kv_totals.append(float(b_slots * fill))
        times.append(_time_call(call, repeats) / n_steps)
    if len(kv_totals) >= 2:
        c4, c3 = np.polyfit(np.asarray(kv_totals), np.asarray(times), 1)
        c4 = max(float(c4), 0.0)
        c3 = max(float(c3), 1e-5)
    else:
        c3, c4 = times[0], 0.0
    # Split the fixed per-step cost: half stays as the step floor, half
    # scales with batch (a heuristic the fit cannot identify — documented).
    c_batch = (c3 / 2.0) / b_slots
    c3 = c3 / 2.0

    return LatencyModel(
        prefill_min_s=min(ys),
        prefill_base_s=c0,
        prefill_per_token_s=c1,
        decode_base_s=c3,
        decode_per_kv_token_s=c4,
        decode_per_seq_s=c_batch,
    )


def main() -> None:
    import jax
    import jax.numpy as jnp
    import runpy
    import os

    bench = runpy.run_path(
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "bench.py")
    )
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.server.engine import Engine, EngineConfig

    cfg = bench["bench_model_cfg"]()
    on_cpu = jax.default_backend() == "cpu"
    dtype = jnp.float32 if on_cpu else jnp.bfloat16
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    engine = Engine(
        cfg, params,
        EngineConfig(decode_slots=4 if on_cpu else 16,
                     max_seq_len=cfg.max_seq_len,
                     prefill_buckets=(32, 64, 128) if on_cpu
                     else (64, 128, 256, 384),
                     decode_steps_per_sync=1 if on_cpu else 8),
        dtype=dtype,
    )
    model = calibrate_from_engine(engine)
    print(json.dumps({
        "model": cfg.name,
        "prefill_min_s": round(model.prefill_min_s, 6),
        "prefill_base_s": round(model.prefill_base_s, 6),
        "prefill_per_token_s": round(model.prefill_per_token_s, 9),
        "decode_base_s": round(model.decode_base_s, 6),
        "decode_per_kv_token_s": round(model.decode_per_kv_token_s, 12),
        "decode_per_seq_s": round(model.decode_per_seq_s, 9),
    }))


if __name__ == "__main__":
    main()
