"""Fit the simulator's latency model — from the live engine, or from the
observables the gateway already scrapes.

The reference calibrated its simulator constants offline against vLLM on
A100 (``constants.py:1-8``, notebook cells 2 & 5); this module does the same
against OUR engine on the TPU it will serve from, so retuned scheduler
thresholds transfer (SURVEY.md §7 step 7: "refit prefill/decode constants to
TPU continuous batching ... before burning TPU hours").

Two calibration paths:

- ``calibrate_from_engine`` times the engine's jitted prefill across bucket
  lengths (linear fit prefill = c0 + c1 * tokens) and decode blocks across
  cache fills (least-squares fit decode = c3 + c4 * kv_tokens + c_batch *
  batch), all including the host dispatch/readback overhead the serving
  loop actually pays.  Needs a live TPU (or the CPU bench engine).
- ``calibrate_from_observables`` fits the SAME constants by least squares
  from per-window means of the histogram families every replica already
  exports (``tpu:prefill_seconds``, ``tpu:decode_step_seconds``,
  ``tpu:decode_batch_occupancy``, KV occupancy) — so the capacity twin
  (gateway/capacity.py) self-calibrates from live traffic with **no TPU
  access**.  Each observation window is a dict of window means:
  ``{prefill_tokens_mean, prefill_s_mean, kv_tokens_mean, batch_mean,
  decode_step_s_mean}``.

Either path can emit the versioned committed artifact
(``TWIN_CALIBRATION.json``, format ``lig-twin-calibration/1``) with fit
residuals; the gateway loads it via ``load_calibration``.

Run:  python -m llm_instance_gateway_tpu.sim.calibrate                # engine
      python -m llm_instance_gateway_tpu.sim.calibrate --source sim \
          --out TWIN_CALIBRATION.json                       # deterministic fit
"""

from __future__ import annotations

import json
import random
import time

import numpy as np

from llm_instance_gateway_tpu.sim.core import LatencyModel, V5E_DEFAULT

# Versioned artifact schema: bump the suffix on a breaking change so a
# twin never silently consumes constants fitted under different semantics.
CALIBRATION_FORMAT = "lig-twin-calibration/1"

# Constant -> decimal places in the artifact.  Rounded on WRITE (stable,
# diffable, reproducible byte-for-byte by tests); load returns the rounded
# values so the committed artifact IS the model the twin runs.
_MODEL_ROUND = {
    "prefill_min_s": 6,
    "prefill_base_s": 6,
    "prefill_per_token_s": 9,
    "decode_base_s": 6,
    "decode_per_kv_token_s": 12,
    "decode_per_seq_s": 9,
}


def _time_call(fn, n: int = 5) -> float:
    """Average seconds per call.  ``fn`` returns a device array; only the
    final handle is synced, so the n dispatches pipeline and the (large,
    on a tunneled chip) per-call host round-trip is amortized instead of
    being paid n times — it would otherwise swamp the per-token slope the
    fit is after."""
    np.asarray(fn())  # warm (compile) + sync
    t0 = time.perf_counter()
    h = None
    for _ in range(n):
        h = fn()
    np.asarray(h)
    return (time.perf_counter() - t0) / n


def calibrate_from_engine(
    engine,
    prefill_lengths: tuple[int, ...] = (64, 128, 256, 384),
    decode_fills: tuple[int, ...] = (32, 128, 256, 448),
    repeats: int = 10,
) -> LatencyModel:
    import jax
    import jax.numpy as jnp

    cfg = engine.model_cfg
    usable = [
        b for b in prefill_lengths
        if b in engine.cfg.prefill_buckets and b < engine.cfg.max_seq_len
    ] or [engine.cfg.prefill_buckets[0]]

    # --- prefill: one padded prompt per bucket, incl. first-token readback.
    xs, ys = [], []
    for bucket in usable:
        tokens = jnp.zeros((1, bucket), jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(bucket), (1, bucket)).astype(jnp.int32)

        def call(bucket=bucket, tokens=tokens, positions=positions):
            first, k, v, _ = engine._jit_prefill(
                engine.params, engine._lora_buffers(), tokens, positions,
                jnp.int32(bucket), jnp.int32(-1),
                jnp.float32(0.0), jnp.int32(0), jnp.float32(1.0),
                jax.random.PRNGKey(0),
            )
            return first

        xs.append(bucket)
        ys.append(_time_call(call, repeats))
    if len(xs) >= 2:
        c1, c0 = np.polyfit(np.asarray(xs, np.float64), np.asarray(ys, np.float64), 1)
        c1 = max(float(c1), 1e-7)
        c0 = max(float(c0), 1e-4)
    else:
        c0, c1 = ys[0], 1e-6

    # --- decode: the engine always steps ALL slots (lockstep batching), so
    # batch size is structurally constant and cannot be a regressor — the
    # varying signal is cache occupancy.  Fit per-step cost against total KV
    # tokens read (b_slots * fill); attribute the batch-proportional part of
    # the base cost to per_seq so the sim scales sanely at other slot counts.
    n_steps = max(1, engine.cfg.decode_steps_per_sync)
    b_slots = engine.cfg.decode_slots
    kv_totals, times = [], []
    for fill in decode_fills:
        if fill >= engine.cfg.max_seq_len:
            continue
        tokens = jnp.zeros((b_slots,), jnp.int32)
        positions = jnp.full((b_slots,), fill, jnp.int32)
        slots = jnp.full((b_slots,), -1, jnp.int32)
        t = jnp.zeros((b_slots,), jnp.float32)
        k = jnp.zeros((b_slots,), jnp.int32)
        p = jnp.ones((b_slots,), jnp.float32)

        remaining = jnp.full((b_slots,), 1 << 20, jnp.int32)  # rows stay live

        def call(tokens=tokens, positions=positions, slots=slots, t=t, k=k,
                 p=p, remaining=remaining):
            out = engine._jit_decode(
                engine.params, engine._lora_buffers(), engine.cache,
                tokens, positions, slots, t, k, p,
                jax.random.PRNGKey(0), remaining, jnp.int32(-1),
                n_steps=n_steps,
            )
            engine.cache = out[-1]  # donated in; reassign the new buffer
            return out[0]

        kv_totals.append(float(b_slots * fill))
        times.append(_time_call(call, repeats) / n_steps)
    if len(kv_totals) >= 2:
        c4, c3 = np.polyfit(np.asarray(kv_totals), np.asarray(times), 1)
        c4 = max(float(c4), 0.0)
        c3 = max(float(c3), 1e-5)
    else:
        c3, c4 = times[0], 0.0
    # Split the fixed per-step cost: half stays as the step floor, half
    # scales with batch (a heuristic the fit cannot identify — documented).
    c_batch = (c3 / 2.0) / b_slots
    c3 = c3 / 2.0

    return LatencyModel(
        prefill_min_s=min(ys),
        prefill_base_s=c0,
        prefill_per_token_s=c1,
        decode_base_s=c3,
        decode_per_kv_token_s=c4,
        decode_per_seq_s=c_batch,
    )


def calibrate_from_observables(
    observations: list,
    min_windows: int = 4,
) -> tuple[LatencyModel, dict]:
    """Fit ``LatencyModel`` constants from scraped observation windows.

    Each observation is one scrape-tick window of per-replica histogram
    deltas, reduced to means::

        {"prefill_tokens_mean": ..,   # Δtokens / Δprefills   (adapter_tokens)
         "prefill_s_mean": ..,        # Δtpu:prefill_seconds_sum / _count
         "kv_tokens_mean": ..,        # mean KV tokens held during the window
         "batch_mean": ..,            # Δtpu:decode_batch_occupancy_sum/_count
         "decode_step_s_mean": ..}    # Δtpu:decode_step_seconds_sum / _count

    Prefill is a line in prompt tokens (c0 + c1·tokens, polyfit); decode is
    a plane in (kv_tokens, batch) (c3 + c4·kv + c_batch·batch, lstsq over
    the [1, kv, batch] design matrix).  Returns ``(model, residuals)`` where
    residuals carries relative RMS fit error per phase — the artifact's
    honesty signal and the drift detector's prior.

    Raises ``ValueError`` when the windows can't identify the constants:
    fewer than ``min_windows``, no spread in prompt tokens, or a
    rank-deficient decode design (kv and batch moving in lockstep).
    """
    obs = [o for o in observations
           if o.get("prefill_s_mean", 0) > 0 and o.get("decode_step_s_mean", 0) > 0]
    if len(obs) < min_windows:
        raise ValueError(
            f"insufficient calibration windows: {len(obs)} < {min_windows}")

    # --- prefill line.
    xs = np.asarray([o["prefill_tokens_mean"] for o in obs], np.float64)
    ys = np.asarray([o["prefill_s_mean"] for o in obs], np.float64)
    if float(np.ptp(xs)) < 1.0:
        raise ValueError("degenerate prefill windows: no prompt-length spread")
    # Closed-form simple regression (identical least squares to a deg-1
    # polyfit, minus the SVD): this runs on the gateway's tick thread
    # every refit cadence, where three LAPACK round-trips per refit were
    # the dominant capacity-plane cost.
    mx = float(np.mean(xs))
    my = float(np.mean(ys))
    dx = xs - mx
    c1 = float(dx @ (ys - my)) / float(dx @ dx)
    c0 = my - c1 * mx
    c1 = max(c1, 0.0)
    c0 = max(c0, 1e-6)
    prefill_pred = c0 + c1 * xs
    prefill_rms = float(np.sqrt(np.mean((prefill_pred - ys) ** 2)))

    # --- decode plane.
    kv = np.asarray([o["kv_tokens_mean"] for o in obs], np.float64)
    batch = np.asarray([o["batch_mean"] for o in obs], np.float64)
    zs = np.asarray([o["decode_step_s_mean"] for o in obs], np.float64)
    design = np.stack([np.ones_like(kv), kv, batch], axis=1)
    # Normal equations on the 3x3 Gram matrix instead of an SVD lstsq
    # (same refit-on-tick-thread cost argument as the prefill line).
    # Columns are scaled to unit magnitude first so the degeneracy
    # check measures collinearity, not the kv-vs-batch unit gap.
    scale = np.maximum(np.abs(design).max(axis=0), 1e-12)
    scaled = design / scale
    gram = scaled.T @ scaled
    a11, a12, a13, a21, a22, a23, a31, a32, a33 = gram.ravel().tolist()
    det = (a11 * (a22 * a33 - a23 * a32)
           - a12 * (a21 * a33 - a23 * a31)
           + a13 * (a21 * a32 - a22 * a31))
    # Collinearity guard via the Hadamard ratio det/(g00*g11*g22) of
    # the scaled Gram — closed form where np.linalg.cond would run a
    # full SVD (the dominant term of a refit on the tick thread).  The
    # ratio falls off the same cliff near singularity the old
    # cond > 1e12 check caught: ~1e-3..1 for identifiable windows,
    # float-epsilon scale for collinear ones.
    if not det > 1e-12 * (a11 * a22 * a33):
        raise ValueError(
            "degenerate decode windows: kv/batch regressors are collinear")
    b1, b2, b3 = (scaled.T @ zs).tolist()
    # Cramer's rule for the 3x3 solve (guard above keeps it
    # well-conditioned; the decode_rms residual below audits the fit).
    x1 = (b1 * (a22 * a33 - a23 * a32)
          - a12 * (b2 * a33 - a23 * b3)
          + a13 * (b2 * a32 - a22 * b3)) / det
    x2 = (a11 * (b2 * a33 - a23 * b3)
          - b1 * (a21 * a33 - a23 * a31)
          + a13 * (a21 * b3 - b2 * a31)) / det
    x3 = (a11 * (a22 * b3 - a23 * b2)
          - a12 * (a21 * b3 - b2 * a31)
          + b1 * (a21 * a32 - a22 * a31)) / det
    s1, s2, s3 = scale.tolist()
    c3 = max(x1 / s1, 1e-6)
    c4 = max(x2 / s2, 0.0)
    c_batch = max(x3 / s3, 0.0)
    decode_pred = design @ np.asarray([c3, c4, c_batch])
    decode_rms = float(np.sqrt(np.mean((decode_pred - zs) ** 2)))

    model = LatencyModel(
        prefill_min_s=float(np.min(ys)),
        prefill_base_s=c0,
        prefill_per_token_s=c1,
        decode_base_s=c3,
        decode_per_kv_token_s=c4,
        decode_per_seq_s=c_batch,
    )
    residuals = {
        "windows": len(obs),
        "prefill_rms_s": round(prefill_rms, 9),
        "prefill_rms_rel": round(prefill_rms / max(float(np.mean(ys)), 1e-9), 6),
        "decode_rms_s": round(decode_rms, 9),
        "decode_rms_rel": round(decode_rms / max(float(np.mean(zs)), 1e-9), 6),
    }
    return model, residuals


def sim_observables(
    model: LatencyModel,
    seed: int = 0,
    windows: int = 24,
    noise: float = 0.0,
) -> list:
    """Deterministic observation windows a known ``model`` would produce.

    Seeded draws of window-mean regressors (prompt tokens, KV occupancy,
    decode batch) pushed through the model's own ``prefill_s``/``decode_s``
    — the ground-truth half of the calibration recovery test, and the
    source of the committed artifact (``--source sim``).  ``noise`` adds a
    seeded relative perturbation to the timing means so the recovery test
    can exercise the 10% tolerance rather than an exact algebraic inverse.
    """
    rng = random.Random(seed)
    out = []
    for _ in range(windows):
        # Stay above the prefill_min clamp region so the line is identifiable.
        tokens = rng.uniform(96.0, 768.0)
        kv = rng.uniform(2_000.0, 60_000.0)
        batch = rng.uniform(1.0, 16.0)
        jitter = (lambda: 1.0 + rng.uniform(-noise, noise)) if noise else (lambda: 1.0)
        out.append({
            "prefill_tokens_mean": round(tokens, 3),
            "prefill_s_mean": round(model.prefill_s(tokens) * jitter(), 9),
            "kv_tokens_mean": round(kv, 3),
            "batch_mean": round(batch, 4),
            "decode_step_s_mean": round(model.decode_s(kv, batch) * jitter(), 12),
        })
    return out


def model_to_dict(model: LatencyModel) -> dict:
    """The artifact's ``model`` block: rounded, key order = schema order."""
    return {k: round(getattr(model, k), nd) for k, nd in _MODEL_ROUND.items()}


def model_from_dict(d: dict) -> LatencyModel:
    return LatencyModel(**{k: float(d[k]) for k in _MODEL_ROUND})


def calibration_artifact(model: LatencyModel, residuals: dict,
                         source: str, seed: int | None = None) -> dict:
    art = {
        "format": CALIBRATION_FORMAT,
        "source": source,
        "model": model_to_dict(model),
        "residuals": residuals,
    }
    if seed is not None:
        art["seed"] = seed
    return art


def write_calibration(path: str, artifact: dict) -> None:
    """Stable serialization (sorted keys, indent 1, trailing newline) so the
    committed artifact is byte-for-byte reproducible by the tests."""
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")


def load_calibration(path: str) -> tuple[LatencyModel, dict]:
    """Load an artifact; raises ``ValueError`` on an unknown format."""
    with open(path) as f:
        art = json.load(f)
    fmt = art.get("format")
    if fmt != CALIBRATION_FORMAT:
        raise ValueError(f"unknown calibration format: {fmt!r}")
    return model_from_dict(art["model"]), art


def _engine_fit() -> tuple[LatencyModel, dict, str]:
    import jax
    import jax.numpy as jnp
    import runpy
    import os

    bench = runpy.run_path(
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "bench.py")
    )
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.server.engine import Engine, EngineConfig

    cfg = bench["bench_model_cfg"]()
    on_cpu = jax.default_backend() == "cpu"
    dtype = jnp.float32 if on_cpu else jnp.bfloat16
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    engine = Engine(
        cfg, params,
        EngineConfig(decode_slots=4 if on_cpu else 16,
                     max_seq_len=cfg.max_seq_len,
                     prefill_buckets=(32, 64, 128) if on_cpu
                     else (64, 128, 256, 384),
                     decode_steps_per_sync=1 if on_cpu else 8),
        dtype=dtype,
    )
    model = calibrate_from_engine(engine)
    return model, {"windows": 0, "note": "engine-timed fit, no residuals"}, cfg.name


def main(argv: list | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="fit the simulator LatencyModel and emit the versioned "
                    "calibration artifact the capacity twin loads")
    parser.add_argument("--source", choices=("engine", "sim"), default="engine",
                        help="engine: time the live/bench engine (needs "
                        "jax); sim: deterministic observables from a known "
                        "model through calibrate_from_observables (no TPU)")
    parser.add_argument("--seed", type=int, default=0,
                        help="seed for --source sim window generation")
    parser.add_argument("--windows", type=int, default=24,
                        help="observation windows for --source sim")
    parser.add_argument("--out", default="",
                        help="write the artifact JSON here (e.g. "
                        "TWIN_CALIBRATION.json); default prints to stdout")
    args = parser.parse_args(argv)

    if args.source == "sim":
        obs = sim_observables(V5E_DEFAULT, seed=args.seed, windows=args.windows)
        model, residuals = calibrate_from_observables(obs)
        artifact = calibration_artifact(model, residuals, "sim", seed=args.seed)
    else:
        model, residuals, name = _engine_fit()
        artifact = calibration_artifact(model, residuals, f"engine:{name}")

    if args.out:
        write_calibration(args.out, artifact)
        print(f"wrote {args.out} ({artifact['source']}, "
              f"{artifact['residuals'].get('windows', 0)} windows)")
    else:
        print(json.dumps(artifact, indent=1, sort_keys=True))


if __name__ == "__main__":
    main()
