"""Simulation core: TPU continuous-batching replica + event loop.

Server model (the TPU analog of the reference's ``llmactor.py`` +
``continous_batching.py``): each replica is a prefill/decode disaggregated
engine with ``decode_slots`` concurrent sequences and a token-denominated KV
budget.  Per iteration it either prefills one queued request (bucketed) or
advances every active slot one token — the same policy as
``server/engine.py``'s loop, so simulated queues/latencies have the same
shape as the real engine's.

Latency model (BASELINE.md form, TPU-recalibrated):
    T_prefill = max(c_min, c0 + c1 * prompt_tokens)
    T_decode  = c3 + c4 * total_kv_tokens_in_batch + c_batch * batch_size
Defaults come from measuring this repo's engine on a v5e chip via
``sim.calibrate`` (see bench.py); the reference's A100 constants
(``constants.py:1-8``) remain available as ``A100_VLLM`` for comparison.
"""

from __future__ import annotations

import heapq
from collections import OrderedDict
from dataclasses import dataclass

from llm_instance_gateway_tpu.gateway.types import Metrics, Pod, PodMetrics


@dataclass(frozen=True)
class LatencyModel:
    prefill_min_s: float
    prefill_base_s: float
    prefill_per_token_s: float
    decode_base_s: float
    decode_per_kv_token_s: float
    decode_per_seq_s: float
    adapter_load_s: float = 0.5     # Orbax restore of one adapter (disk tier)
    # Host-RAM promotion (residency ladder, server/lora_manager.py): the
    # adapter's weights are already in host memory, so a "load" is one
    # device put of a few-MB delta — tens of milliseconds, versus the
    # DISK tier's full Orbax restore (adapter_load_s).
    host_promote_s: float = 0.02
    # Decode fast-path knobs (engine PR 15 — the cost model item-3's
    # autoscaler loop reuses):
    # Fused decode steps per dispatch (EngineConfig.adaptive_steps /
    # decode_steps_per_sync): the dispatch base cost ``decode_base_s`` is
    # paid ONCE per dispatch while the per-kv/per-seq terms scale with the
    # fused step count — exactly the amortization the adaptive planner
    # buys on the real engine.
    steps_per_dispatch: int = 1
    # Concurrent chunk-stream lanes (EngineConfig.stream_lanes): how many
    # long prompts a SimServer advances chunk-by-chunk at once.
    stream_lanes: int = 1

    def prefill_s(self, prompt_tokens: int) -> float:
        return max(
            self.prefill_min_s,
            self.prefill_base_s + self.prefill_per_token_s * prompt_tokens,
        )

    def decode_s(self, total_kv_tokens: int, batch: int) -> float:
        return (
            self.decode_base_s
            + self.decode_per_kv_token_s * total_kv_tokens
            + self.decode_per_seq_s * batch
        )

    def decode_block_s(self, total_kv_tokens: int, batch: int) -> float:
        """One fused dispatch advancing every sequence
        ``steps_per_dispatch`` tokens: base paid once, marginal terms per
        step (kv integral approximated at the block's starting size)."""
        k = max(1, self.steps_per_dispatch)
        return (
            self.decode_base_s
            + k * (self.decode_per_kv_token_s * total_kv_tokens
                   + self.decode_per_seq_s * batch)
        )


# Reference calibration: A100-40GB, llama-3 arch on vLLM (constants.py:1-8).
A100_VLLM = LatencyModel(
    prefill_min_s=0.04,
    prefill_base_s=0.01969,
    prefill_per_token_s=6.769375513e-5,
    decode_base_s=0.014,
    decode_per_kv_token_s=5.353485087e-7,
    decode_per_seq_s=1.026494433e-4,
)

# v5e-1, fitted by sim.calibrate from the live engine (bench-llama-1b,
# 16 decode slots, K=8 fused steps, pipelined dispatch so the tunnel
# round-trip is amortized — 2026-07-29 run, values rounded):
#   prefill  = 0.0205 + 1.52e-6 * prompt_tokens      (weight-stream bound
#              at batch 1: the base is HBM weights + dispatch, the
#              per-token slope is small until prompts reach thousands)
#   decode   = 0.0045 + 4.5e-8 * kv_tokens + 2.8e-4 * batch   per step
V5E_DEFAULT = LatencyModel(
    prefill_min_s=0.0176,
    prefill_base_s=0.0205,
    prefill_per_token_s=1.52e-6,
    decode_base_s=0.0045,
    decode_per_kv_token_s=4.5e-8,
    decode_per_seq_s=2.81e-4,
)


@dataclass
class SimRequest:
    rid: int
    arrival_s: float
    prompt_tokens: int
    output_tokens: int
    model: str
    adapter: str | None = None
    critical: bool = False
    tier: str = "Default"  # Critical / Default / Sheddable
    slo_s_per_token: float = 0.025
    # Shared-prefix modeling (session templates / multi-turn context): the
    # leading ``prefix_tokens`` of the prompt are identical across every
    # request with the same ``prefix_id`` — a replica holding it in its
    # prefix cache prefills only the suffix (models/paged.py semantics).
    prefix_id: int | None = None
    prefix_tokens: int = 0
    # lifecycle
    t_first_token: float = -1.0
    t_done: float = -1.0
    generated: int = 0
    shed: bool = False

    @property
    def ttft_s(self) -> float:
        return self.t_first_token - self.arrival_s if self.t_first_token >= 0 else -1

    @property
    def latency_per_output_token_s(self) -> float:
        if self.t_done < 0 or self.output_tokens == 0:
            return -1
        return (self.t_done - self.arrival_s) / self.output_tokens


@dataclass
class _ActiveSeq:
    request: SimRequest
    kv_tokens: int


class SimServer:
    """One TPU replica: prefill queue + decode slots + KV budget + adapters."""

    def __init__(
        self,
        name: str,
        latency: LatencyModel,
        decode_slots: int = 16,
        kv_capacity_tokens: int = 44_448,
        max_adapters: int = 4,
        prefix_cache_size: int = 32,
        host_cache_slots: int = 0,
        preload: "list[str] | None" = None,
        chunk_tokens: int = 0,
        kv_block_tokens: int = 16,
    ):
        self.name = name
        self.pod = Pod(name=name, address=f"{name}:8000")
        self.latency = latency
        self.decode_slots = decode_slots
        self.kv_capacity_tokens = kv_capacity_tokens
        self.max_adapters = max_adapters
        self.prefill_queue: list[SimRequest] = []
        self.active: list[_ActiveSeq] = []
        # Slot tier: adapter -> in-flight refcount (the engine's device
        # slot buffers).  ``preload`` models the all-resident baseline —
        # adapters resident at t=0 with no load charge.
        self.resident_adapters: dict[str, int] = {
            a: 0 for a in (preload or [])}
        # Host-RAM tier (residency ladder): adapters whose weights are in
        # host memory — promotion costs host_promote_s instead of the
        # full adapter_load_s disk restore.  LRU, bounded.
        self.host_cache_slots = host_cache_slots
        self.host_cache: "OrderedDict[str, None]" = OrderedDict()
        # Per-tier load counters (the sim twin of tpu:adapter_loads_total).
        self.disk_loads = 0
        self.host_promotes = 0
        self.demotions = 0
        # In-flight adapter loads: adapter -> sim time the weights become
        # slot-resident.  Loads run OFF the step path (the engine restores
        # in an executor thread; only the waiting request pays the
        # latency) — a cold adapter must not freeze every active slot.
        self.loading: dict[str, float] = {}
        # Last admission time per slot-resident adapter: slot pressure
        # demotes the least-recently-USED idle adapter, so a hot adapter
        # that is momentarily idle between requests is not the one the
        # cold tail displaces.
        self.last_used: dict[str, float] = {}
        self.busy_until = 0.0
        self.tokens_generated = 0
        # Prefix cache: retained prefix_ids, LRU-capped (the engine's
        # zero-ref cached blocks, abstracted to whole prefixes; their pool
        # occupancy is evict-on-demand and not charged against kv_free).
        self.prefix_cache_size = prefix_cache_size
        self.cached_prefixes: "OrderedDict[int, int]" = OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_reused_tokens = 0
        # KV economy twin (server/kv_ledger.py): the sim's token-denominated
        # budget quantized to block-equivalents so ``kv_snapshot()`` emits
        # the SAME snapshot shape the engine ledger exports — per-prefix
        # heatmap rows keyed by the workload's shared prefix_id rendered as
        # a 16-hex id (identical across replicas, the duplication join key).
        self.kv_block_tokens = max(1, kv_block_tokens)
        self.prefix_stats: dict[int, dict] = {}   # pid -> hits/saved/touch
        self.prefix_registers = 0
        self.prefix_evictions = 0
        self.prefilled_tokens = 0  # cumulative prompt tokens computed
        self._kv_syncs = 0
        # Chunk-stream lanes (engine PR 15): prompts beyond chunk_tokens
        # stream one chunk per iteration into up to latency.stream_lanes
        # concurrent lanes (fair round-robin), interleaved with decode —
        # 0 disables (monolithic prefill, the pre-lever model).
        self.chunk_tokens = chunk_tokens
        self.streaming: list[dict] = []   # {"req": SimRequest, "done": int}
        self._lane_rr = 0

    # -- KV economy twin ---------------------------------------------------
    @staticmethod
    def _prefix_label(pid: int) -> str:
        """The workload's integer prefix_id as a 16-hex id — the same
        width as the engine's content-addressed ids, and identical across
        every sim replica serving the prefix (the duplication join key)."""
        return "%016x" % (pid % (1 << 64))

    def kv_snapshot(self) -> dict:
        """The engine ledger's ``snapshot()`` shape from sim state
        (server/kv_ledger.py contract; tests/test_sim.py pins key parity).
        Token-denominated sim KV quantizes to ``kv_block_tokens``-sized
        block-equivalents; cached prefixes sit outside the charged budget
        exactly like the engine's zero-ref evictable blocks."""
        from llm_instance_gateway_tpu.server.kv_ledger import (
            FREE_RUN_BUCKETS, PARKED_SHARE_BUCKETS)
        from llm_instance_gateway_tpu.tracing import Histogram

        self._kv_syncs += 1
        block = self.kv_block_tokens
        pool = max(1, self.kv_capacity_tokens // block)
        active = sum(-(-a.kv_tokens // block) for a in self.active
                     if a.kv_tokens > 0)
        resident = {pid: -(-tok // block)
                    for pid, tok in self.cached_prefixes.items() if tok > 0}
        free = max(0, pool - active - sum(resident.values()))
        prefixes = []
        for pid, blocks in resident.items():
            stats = self.prefix_stats.get(pid) or {
                "hits": 0, "tokens_saved": 0, "last_touch": 0.0}
            prefixes.append({
                "prefix": self._prefix_label(pid),
                "hits": stats["hits"],
                "tokens_saved": stats["tokens_saved"],
                "blocks": blocks,
                "age_s": 0.0,
            })
        prefixes.sort(key=lambda e: (-e["hits"], -e["tokens_saved"],
                                     e["prefix"]))
        free_runs = Histogram(FREE_RUN_BUCKETS)
        if free:
            # The sim has no physical block ids: its free space is one
            # contiguous run (an upper bound on contiguity, stated here).
            free_runs.observe(float(free))
        parked_share = Histogram(PARKED_SHARE_BUCKETS)
        parked_share.observe(0.0)
        return {
            "blocks_total": pool,
            "pool_blocks": pool,
            "block_tokens": block,
            "states": {"free": free, "active": active,
                       "prefix_resident": sum(resident.values()),
                       "parked": 0},
            "parked_tokens": 0,
            "events": {"reuse_hit": self.prefix_hits,
                       "register": self.prefix_registers,
                       "evict": self.prefix_evictions},
            "prefixes": prefixes,
            "prefix_table_size": len(resident),
            "prefix_table_evictions": self.prefix_evictions,
            "free_runs": free_runs.state(),
            "parked_share": parked_share.state(),
            "ring": [],
            "syncs": self._kv_syncs,
        }

    # -- metrics the production scheduler consumes -------------------------
    def metrics(self) -> PodMetrics:
        used = sum(a.kv_tokens for a in self.active)
        tiers = {name: "slot" for name in self.resident_adapters}
        for name in self.host_cache:
            tiers.setdefault(name, "host")
        kv = self.kv_snapshot()
        return PodMetrics(
            pod=self.pod,
            metrics=Metrics(
                active_adapters=dict(self.resident_adapters),
                max_active_adapters=self.max_adapters,
                adapter_tiers=tiers,
                running_adapters=frozenset(
                    s.request.adapter for s in self.active
                    if s.request.adapter),
                waiting_adapters=frozenset(
                    r.adapter for r in self.prefill_queue if r.adapter),
                running_queue_size=len(self.active),
                waiting_queue_size=len(self.prefill_queue),
                prefill_queue_size=len(self.prefill_queue),
                decode_queue_size=0,
                kv_cache_usage_percent=used / self.kv_capacity_tokens,
                kv_tokens_capacity=self.kv_capacity_tokens,
                kv_tokens_free=self.kv_capacity_tokens - used,
                # KV economy twin: the same fields metrics_client parses
                # from a real pod's tpu:kv_* families, so the gateway's
                # kvobs rollup (and KV_BASELINE generation) runs over sim
                # fleets unchanged.
                prefix_reused_tokens=self.prefix_reused_tokens,
                adapter_tokens={("sim", "base", "prefill"):
                                float(self.prefilled_tokens)},
                kv_blocks=dict(kv["states"]),
                kv_blocks_total=kv["blocks_total"],
                kv_block_tokens=kv["block_tokens"],
                kv_block_events=dict(kv["events"]),
                kv_prefix_hits={e["prefix"]: e["hits"]
                                for e in kv["prefixes"]},
                kv_prefix_tokens_saved={e["prefix"]: e["tokens_saved"]
                                        for e in kv["prefixes"]},
                kv_prefix_resident_blocks={e["prefix"]: e["blocks"]
                                           for e in kv["prefixes"]},
            ),
        )

    # -- residency ladder (planner-drivable verbs) -------------------------
    def _host_put(self, adapter: str) -> None:
        if self.host_cache_slots <= 0:
            return
        self.host_cache[adapter] = None
        self.host_cache.move_to_end(adapter)
        while len(self.host_cache) > self.host_cache_slots:
            self.host_cache.popitem(last=False)  # LRU falls to disk

    def _start_load(self, adapter: str, now: float) -> None:
        """Kick an async slot load: host_promote_s off the host tier, the
        full Orbax adapter_load_s off disk.  The requesting sequence stays
        queued until the load lands; other traffic keeps flowing."""
        if adapter in self.loading:
            return
        if adapter in self.host_cache:
            del self.host_cache[adapter]
            cost = self.latency.host_promote_s
            self.host_promotes += 1
        else:
            cost = self.latency.adapter_load_s
            self.disk_loads += 1
        self.loading[adapter] = now + cost

    def _finish_loads(self, now: float) -> None:
        """Land finished restores into slots, displacing least-recently-
        used IDLE adapters under pressure (vLLM-style slot LRU; the
        pre-ladder sim's self-eviction, now demoting into the host tier).
        When every resident adapter is mid-decode there is nothing safe
        to displace, so the set transiently exceeds ``max_adapters`` —
        and is squeezed back down as decodes finish and later landings
        re-apply pressure."""
        for adapter, ready_at in list(self.loading.items()):
            if ready_at > now:
                continue
            del self.loading[adapter]
            self.resident_adapters.setdefault(adapter, 0)
            while len(self.resident_adapters) > self.max_adapters:
                before = len(self.resident_adapters)
                self._slot_pressure(adapter)
                if len(self.resident_adapters) == before:
                    break  # everything else is busy: transient overflow

    def _slot_pressure(self, keep: str) -> None:
        """Engine-side LRU displacement: demote the least-recently-used
        idle adapter to host RAM to make room (vLLM-style slot LRU; the
        planner's demote/evict decisions ride on top of this backstop)."""
        idle = [name for name, refs in self.resident_adapters.items()
                if refs == 0 and name != keep]
        if not idle:
            return
        victim = min(idle, key=lambda n: (self.last_used.get(n, -1.0), n))
        del self.resident_adapters[victim]
        self._host_put(victim)
        self.demotions += 1

    def host_prefetch(self, adapter: str) -> None:
        """Planner 'prefetch'/'migrate' verb: disk -> host RAM.  Free of
        TPU step time — the Orbax restore runs host-side off the decode
        loop (the engine loads in an executor thread); only the later
        promotion's device put charges the step path."""
        if adapter in self.resident_adapters or adapter in self.host_cache:
            return
        self._host_put(adapter)

    def demote(self, adapter: str) -> None:
        """Planner 'demote' verb: slot -> host RAM; refused (no-op) while
        in-flight requests pin the slot — AdapterBusyError semantics."""
        if self.resident_adapters.get(adapter) == 0:
            del self.resident_adapters[adapter]
            self._host_put(adapter)
            self.demotions += 1

    def evict_host(self, adapter: str) -> None:
        """Planner 'evict' verb: host RAM -> disk."""
        self.host_cache.pop(adapter, None)

    # -- engine iteration (mirrors server/engine.py:_loop) ------------------
    def kv_free(self) -> int:
        return self.kv_capacity_tokens - sum(a.kv_tokens for a in self.active)

    def _admit_would_fit(self, req: SimRequest) -> bool:
        return req.prompt_tokens + req.output_tokens <= self.kv_free()

    def step(self, now: float) -> float:
        """Run one engine iteration starting at ``now``; return its duration.

        Returns 0.0 when idle (nothing to do).
        """
        self._finish_loads(now)
        # Admission: prefill one queued request if a slot is free and the
        # full sequence fits in KV (the engine's slot admission gate).
        # Requests whose adapter is still loading are SKIPPED, not head-
        # blocking: the engine's executor-thread restore lets other
        # traffic keep flowing while the waiting request pays the latency.
        req = None
        lanes = max(1, self.latency.stream_lanes)
        active_streams = len(self.streaming) + len(self.active)
        if self.prefill_queue and active_streams < self.decode_slots:
            for i, queued in enumerate(self.prefill_queue):
                if (queued.adapter is not None
                        and queued.adapter not in self.resident_adapters):
                    self._start_load(queued.adapter, now)
                    continue  # waiting on its load; later traffic flows
                if not self._admit_would_fit(queued):
                    break  # KV capacity head-block at the first admissible
                if (self.chunk_tokens
                        and queued.prompt_tokens > self.chunk_tokens):
                    # Long prompt: takes a chunk-stream lane (no compute
                    # this iteration; chunks advance below).  No lane free
                    # = head-of-line wait, the engine's FIFO contract.
                    if len(self.streaming) >= lanes:
                        break
                    self.streaming.append(
                        {"req": self.prefill_queue.pop(i), "done": 0})
                    break
                req = self.prefill_queue.pop(i)
                break
        if req is not None:
            prefill_tokens = req.prompt_tokens
            if req.prefix_id is not None:
                stats = self.prefix_stats.setdefault(
                    req.prefix_id,
                    {"hits": 0, "tokens_saved": 0, "last_touch": now})
                stats["last_touch"] = now
                if req.prefix_id in self.cached_prefixes:
                    # Cache hit: only the suffix prefills (the prefix's KV
                    # blocks map into the row's table, zero compute).
                    prefill_tokens = max(
                        0, req.prompt_tokens - req.prefix_tokens)
                    self.prefix_hits += 1
                    self.prefix_reused_tokens += req.prefix_tokens
                    stats["hits"] += 1
                    stats["tokens_saved"] += req.prefix_tokens
                else:
                    self.prefix_misses += 1
                    self.prefix_registers += 1
                self.cached_prefixes[req.prefix_id] = req.prefix_tokens
                self.cached_prefixes.move_to_end(req.prefix_id)
                while len(self.cached_prefixes) > self.prefix_cache_size:
                    evicted, _tok = self.cached_prefixes.popitem(last=False)
                    self.prefix_stats.pop(evicted, None)
                    self.prefix_evictions += 1
            self.prefilled_tokens += prefill_tokens
            duration = self.latency.prefill_s(prefill_tokens)
            if req.adapter:
                self.resident_adapters[req.adapter] = (
                    self.resident_adapters.get(req.adapter, 0) + 1
                )
                self.last_used[req.adapter] = now
            req.t_first_token = now + duration
            req.generated = 1
            self.tokens_generated += 1
            if req.generated >= req.output_tokens:
                req.t_done = now + duration  # single-token request: done
                if req.adapter:  # release the refcount taken above
                    refs = self.resident_adapters.get(req.adapter, 1)
                    self.resident_adapters[req.adapter] = max(0, refs - 1)
            else:
                self.active.append(_ActiveSeq(req, req.prompt_tokens + 1))
            return duration

        duration = 0.0
        if self.streaming:
            # One chunk of ONE lane per iteration (fair round-robin),
            # interleaved with the decode block below — the engine loop's
            # cycle shape, so N long prompts advance concurrently instead
            # of head-of-line serializing.
            self._lane_rr %= len(self.streaming)
            lane = self.streaming[self._lane_rr]
            self._lane_rr += 1
            r = lane["req"]
            chunk = min(self.chunk_tokens, r.prompt_tokens - lane["done"])
            duration += self.latency.prefill_s(chunk)
            lane["done"] += chunk
            self.prefilled_tokens += chunk
            if lane["done"] >= r.prompt_tokens:
                # Final chunk: the lane activates as a live decode slot
                # and the first token is emitted (engine _stream_step).
                self.streaming.remove(lane)
                r.t_first_token = now + duration
                r.generated = 1
                self.tokens_generated += 1
                if r.adapter:
                    self.resident_adapters[r.adapter] = (
                        self.resident_adapters.get(r.adapter, 0) + 1)
                    self.last_used[r.adapter] = now
                if r.generated >= r.output_tokens:
                    r.t_done = now + duration
                    if r.adapter:
                        refs = self.resident_adapters.get(r.adapter, 1)
                        self.resident_adapters[r.adapter] = max(0, refs - 1)
                else:
                    self.active.append(_ActiveSeq(r, r.prompt_tokens + 1))
        if self.active:
            total_kv = sum(a.kv_tokens for a in self.active)
            steps = max(1, self.latency.steps_per_dispatch)
            duration += self.latency.decode_block_s(total_kv,
                                                    len(self.active))
            finished = []
            for seq in self.active:
                adv = min(steps,
                          seq.request.output_tokens - seq.request.generated)
                seq.request.generated += adv
                seq.kv_tokens += adv
                self.tokens_generated += adv
                if seq.request.generated >= seq.request.output_tokens:
                    seq.request.t_done = now + duration
                    finished.append(seq)
            for seq in finished:
                self.active.remove(seq)
                if seq.request.adapter:
                    refs = self.resident_adapters.get(seq.request.adapter, 1)
                    self.resident_adapters[seq.request.adapter] = max(0, refs - 1)
            return duration
        if duration > 0:
            return duration
        if self.loading:
            # Idle except for in-flight adapter loads: stay scheduled
            # until the earliest one lands (the event loop only re-kicks
            # idle servers on arrivals).  A restore that is ready but
            # waiting for the planner to free a slot polls at a coarse
            # cadence instead of busy-spinning the event loop.
            return max(0.01, min(self.loading.values()) - now)
        return 0.0


class EventLoop:
    """Minimal DES driver: servers advance via their own iteration events."""

    def __init__(self, servers: list[SimServer]):
        self.servers = servers
        self.now = 0.0
        self._events: list[tuple[float, int, object]] = []
        self._seq = 0

    def schedule(self, t: float, item) -> None:
        heapq.heappush(self._events, (t, self._seq, item))
        self._seq += 1

    def kick(self, server: SimServer) -> None:
        """Ensure a server has a pending iteration event."""
        if server.busy_until <= self.now:
            self.schedule(self.now, server)

    def run(self, until: float) -> None:
        while self._events:
            t, _, item = heapq.heappop(self._events)
            if t > until:
                break
            self.now = t
            if isinstance(item, SimServer):
                duration = item.step(self.now)
                if duration > 0:
                    item.busy_until = self.now + duration
                    self.schedule(item.busy_until, item)
                # idle servers get re-kicked on arrival
            elif callable(item):
                item(self)
