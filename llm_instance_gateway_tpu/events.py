"""Flight recorder: a bounded structured event journal.

The tracing layer (``tracing.py``) answers "where did THIS request spend its
time?"; this module answers the operator's next question — "what was the
SYSTEM doing in the 30 seconds before the breach?".  Both the gateway proxy
and the model server keep one ``EventJournal``: a bounded ring of structured
events (admission rejections, pick outcomes, disaggregation fallbacks, role
changes, scrape failures, SLO state transitions) carrying trace ids so an
event row correlates with the request timeline that produced it.

Design constraints, in order:

- **Emit is hot-path-adjacent**: one dict build + a ``deque.append`` (the
  append is GIL-atomic on a maxlen-bounded ring, same trick as the span
  recorder); only the seq/counter bump takes a lock.
- **Bounded**: the ring holds ``LIG_EVENTS_CAPACITY`` (default 2048) events;
  old ones age out.  Per-kind COUNTERS are cumulative forever — the exported
  ``*_events_total{kind=...}`` family keeps rate()-able history even after
  the rows themselves rotate out.
- **Queryable**: ``/debug/events?since=<seq>`` serves incremental reads (a
  poller passes the last seq it saw), ``?kind=`` filters, and
  ``snapshot()`` feeds the black-box dump written on an SLO fast burn.
"""

from __future__ import annotations

import collections
import os

from llm_instance_gateway_tpu.lockwitness import witness_lock
import time

# Event kinds.  One flat namespace shared by the gateway and the model
# server; the journal accepts any string, these are the kinds the framework
# itself emits (tools/blackbox_report.py knows how to narrate them).
ADMISSION_REJECT = "admission_reject"   # 4xx/5xx before a pod was picked
SHED = "shed"                           # load-shed drop (429)
PICK = "pick"                           # scheduler outcome for a request
DISAGG_FALLBACK = "disagg_fallback"     # two-hop path degraded to single-hop
UPSTREAM_ERROR = "upstream_error"       # replica connection/stream failure
ROLE_CHANGE = "role_change"             # replica role/drain state change
SCRAPE_FAILURE = "scrape_failure"       # metrics scrape of a pod failed
SLO_TRANSITION = "slo_transition"       # objective entered/left a burn state
HEALTH_TRANSITION = "health_transition"  # pod health state changed
BREACH_DUMP = "breach_dump"             # black-box dump written
CIRCUIT_TRANSITION = "circuit_transition"  # per-pod breaker state changed
RETRY = "retry"                         # proxy retried a failed attempt
HEDGE = "hedge"                         # TTFT hedge fired (and its outcome)
POLICY_ESCAPE = "policy_escape"         # avoid-policy last-resort pick
CLIENT_DISCONNECT = "client_disconnect"  # client dropped a live stream
KV_RELEASE = "kv_release"               # abandoned handoff KV released
KV_EVICT = "kv_evict"                   # cached prefix blocks evicted LRU
KV_DUPLICATION = "kv_duplication"       # prefix became fleet-duplicated (kvobs)
FAULT_INJECT = "fault_inject"           # chaos harness applied a fault
NOISY_NEIGHBOR = "noisy_neighbor"       # adapter usage flag changed (usage.py)
QUOTA_THROTTLE = "quota_throttle"       # tenant over quota (fairness.py)
FAIRNESS_DEMOTE = "fairness_demote"     # over-quota request demoted one tier
FAIRNESS_ESCAPE = "fairness_escape"     # fairness pick filter last-resort
PLACEMENT_DECISION = "placement_decision"  # planner emitted a tier action
PLACEMENT_ESCAPE = "placement_escape"   # no resident candidate: full set served
STATEBUS_STALE = "statebus_stale"       # peers quiet: local-only enforcement
STATEBUS_REJOIN = "statebus_rejoin"     # fresh peer state after a stale spell
FLEET_PEER_ERROR = "fleet_peer_error"   # fleet collector pull failed (fleetobs)
PICK_SAMPLE = "pick_sample"             # routing decision record captured
PICK_ESCAPE_EXPLAINED = "pick_escape_explained"  # sampled pick hit escape hatch
TWIN_DRIFT = "twin_drift"               # capacity twin diverged from observed
CAPACITY_FORECAST = "capacity_forecast"  # time-to-breach entered the horizon


class EventJournal:
    """Bounded, thread-safe structured event ring with cumulative counters."""

    def __init__(self, capacity: int | None = None, clock=time.time):
        if capacity is None:
            capacity = int(os.environ.get("LIG_EVENTS_CAPACITY", "2048"))
        self.capacity = max(1, capacity)
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._clock = clock
        self._lock = witness_lock("EventJournal._lock")
        self._seq = 0
        # kind -> cumulative count (survives ring rotation; exported as a
        # labeled counter family on the owning surface's /metrics).
        self.counts: dict[str, int] = {}

    def emit(self, kind: str, trace_id: str = "", **attrs) -> int:
        """Record one event; returns its monotonic sequence number."""
        event = {"seq": 0, "ts": round(self._clock(), 6), "kind": kind}
        if trace_id:
            event["trace_id"] = trace_id
        if attrs:
            event["attrs"] = attrs
        # Seq assignment AND the append happen under the lock: emitters run
        # on the event loop, the provider refresh thread, and the engine
        # drain thread, and an out-of-order append would make a since-
        # cursor poller skip the late event forever.
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self._ring.append(event)
        return event["seq"]

    @property
    def seq(self) -> int:
        return self._seq

    def events(self, since: int = 0, limit: int = 256,
               kind: str | None = None) -> list[dict]:
        """The oldest ``limit`` events with seq > ``since``, oldest first.

        ``since`` makes polling incremental AND lossless: pass the last
        seq already seen and the next page comes back — a burst larger
        than ``limit`` is paged through, never silently skipped (trimming
        the newest rows would drop the oldest ones unrecoverably, exactly
        the pre-breach record a flight recorder exists to keep).  A poller
        that sees ``events[0]["seq"] > since + 1`` knows rows rotated out
        of the bounded ring between polls.
        """
        rows = [e for e in list(self._ring) if e["seq"] > since
                and (kind is None or e["kind"] == kind)]
        return rows[:max(0, limit)]

    def snapshot(self) -> dict:
        """Full journal state for the black-box dump."""
        with self._lock:
            counts = dict(self.counts)
            seq = self._seq
        return {"seq": seq, "capacity": self.capacity, "counts": counts,
                "events": list(self._ring)}

    def render_prom(self, family: str) -> list[str]:
        """Cumulative per-kind counters as one Prometheus counter family
        (``family{kind="..."}``; an unlabeled 0 line when nothing has been
        emitted — the shared renderer in tracing.py)."""
        from llm_instance_gateway_tpu.tracing import render_counter

        with self._lock:
            counts = dict(self.counts)
        return render_counter(family, counts, "kind")


def debug_events_payload(journal: EventJournal, query) -> dict:
    """The shared ``/debug/events`` response body (proxy and api_http):
    ``?since=<seq>`` incremental cursor, ``?kind=`` filter, ``?limit=``
    page size (1..2048, default 256).  Pages are oldest-first; poll with
    ``since=next_since`` until ``next_since == seq`` (the journal head) to
    drain a backlog without losing events."""
    try:
        since = max(0, int(query.get("since", "0")))
    except ValueError:
        since = 0
    try:
        limit = max(1, min(int(query.get("limit", "256")), 2048))
    except ValueError:
        limit = 256
    kind = query.get("kind") or None
    rows = journal.events(since=since, limit=limit, kind=kind)
    return {"seq": journal.seq,
            "next_since": rows[-1]["seq"] if rows else journal.seq,
            "events": rows}
