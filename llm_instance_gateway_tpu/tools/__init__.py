"""Operational tools: the dynamic LoRA rollout sidecar."""
