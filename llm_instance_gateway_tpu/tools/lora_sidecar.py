"""Dynamic LoRA rollout sidecar: config-driven adapter reconciliation.

Parity: reference ``tools/dynamic-lora-sidecar/sidecar/sidecar.py`` — a
per-replica reconciler that watches a mounted ConfigMap file and drives the
model server's adapter set to match:

- config schema is ``tpuLoRAConfig`` with the same shape as the reference's
  ``vLLMLoRAConfig`` (host/port, ``ensureExist.models[]``,
  ``ensureNotExist.models[]`` with id/source/base-model) — validated with
  jsonschema like the reference (``sidecar.py:68-80``, ``validation.yaml``);
  the legacy ``vLLMLoRAConfig`` key is accepted for drop-in compatibility.
- reconcile = health-gate (poll ``/health`` up to 300 s, ``sidecar.py:158-175``)
  -> diff ``ensureExist - ensureNotExist`` against ``GET /v1/models``
  (``:140-155``, ``:215-239``) -> ``POST /v1/load_lora_adapter`` /
  ``/v1/unload_lora_adapter`` (``:177-213``).

The TPU difference is entirely server-side: ``source`` is an Orbax
checkpoint path and the load endpoint restores it into a pre-allocated JAX
adapter slot (``server/lora_manager.py``) instead of vLLM pulling
safetensors into CUDA memory.  File watching is mtime-polling (the watchdog
package isn't in this image; the reference used PollingObserver anyway).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

import yaml

try:
    import jsonschema
except ImportError:  # pragma: no cover
    jsonschema = None

logger = logging.getLogger(__name__)

CONFIG_SCHEMA = {
    "title": "tpuLoRAConfig",
    "type": "object",
    "properties": {
        "tpuLoRAConfig": {"$ref": "#/$defs/config"},
        "vLLMLoRAConfig": {"$ref": "#/$defs/config"},  # drop-in compat
    },
    "$defs": {
        "config": {
            "type": "object",
            "properties": {
                "host": {"type": "string", "default": "localhost"},
                "port": {"type": "integer", "default": 8000},
                "name": {"type": "string"},
                "ensureExist": {
                    "type": "object",
                    "properties": {"models": {"$ref": "#/$defs/models"}},
                    "required": ["models"],
                },
                "ensureNotExist": {
                    "type": "object",
                    "properties": {"models": {"$ref": "#/$defs/models"}},
                    "required": ["models"],
                },
            },
        },
        "models": {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "id": {"type": "string"},
                    "source": {"type": "string"},
                    "base-model": {"type": "string"},
                },
                "required": ["id", "source"],
            },
        },
    },
}


@dataclass(frozen=True)
class LoraAdapter:
    """sidecar.py:46-60 (identity is the id, like the reference's __eq__)."""

    id: str
    source: str = ""
    base_model: str = ""

    def __eq__(self, other):
        return isinstance(other, LoraAdapter) and self.id == other.id

    def __hash__(self):
        return hash(self.id)


# Planner decision action -> model-server endpoint (the residency-ladder
# verbs api_http exposes; ``migrate`` executes as a load on the TARGET
# replica — a promote when the weights were prefetched, an Orbax restore
# otherwise).
PLANNER_ACTION_ENDPOINTS = {
    "prefetch": "/v1/prefetch_lora_adapter",
    "migrate": "/v1/load_lora_adapter",
    "demote": "/v1/demote_lora_adapter",
    "evict": "/v1/evict_lora_adapter",
    "load": "/v1/load_lora_adapter",
    "unload": "/v1/unload_lora_adapter",
}


class LoraReconciler:
    def __init__(
        self,
        config_file: str,
        config_validation: bool = True,
        health_check_timeout_s: float = 300.0,
        health_check_interval_s: float = 15.0,
        http_timeout_s: float = 60.0,
        planner_url: str | None = None,
        pod_name: str | None = None,
    ):
        self.config_file = config_file
        self.config_validation = config_validation
        self.health_check_timeout_s = health_check_timeout_s
        self.health_check_interval_s = health_check_interval_s
        self.http_timeout_s = http_timeout_s
        # Planner mode (--planner-url): decisions come from the gateway's
        # /debug/placement instead of the static ensureExist/ensureNotExist
        # sections; the config file (when present) still supplies host/
        # port and the adapter-id -> source registry for decisions whose
        # ``path`` is empty.  Without a planner URL, behavior is byte-
        # identical to the static-file sidecar (regression-pinned).
        self.planner_url = planner_url.rstrip("/") if planner_url else None
        self.pod_name = pod_name

    # -- config (sidecar.py:82-96) ------------------------------------------
    @property
    def config(self) -> dict:
        try:
            with open(self.config_file) as f:
                c = yaml.safe_load(f) or {}
            if self.config_validation and jsonschema is not None:
                jsonschema.validate(instance=c, schema=CONFIG_SCHEMA)
            return c.get("tpuLoRAConfig") or c.get("vLLMLoRAConfig") or {}
        except (OSError, yaml.YAMLError) as e:
            logger.error("cannot load config %s: %s", self.config_file, e)
            return {}
        except Exception as e:  # jsonschema.ValidationError
            logger.error("config validation error for %s: %s", self.config_file, e)
            return {}

    @property
    def model_server(self) -> str:
        c = self.config
        return f"{c.get('host', 'localhost')}:{c.get('port', 8000)}"

    def _adapters(self, key: str) -> set[LoraAdapter]:
        models = self.config.get(key, {}).get("models", [])
        return {
            LoraAdapter(m["id"], m.get("source", ""), m.get("base-model", ""))
            for m in models
        }

    # -- HTTP helpers ---------------------------------------------------------
    def _get(self, path: str) -> dict:
        url = f"http://{self.model_server}{path}"
        with urllib.request.urlopen(url, timeout=self.http_timeout_s) as resp:
            return json.loads(resp.read())

    def _post(self, path: str, payload: dict) -> tuple[int, str]:
        url = f"http://{self.model_server}{path}"
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.http_timeout_s) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    # -- reconcile steps ------------------------------------------------------
    def is_server_healthy(self) -> bool:
        """sidecar.py:158-175: poll /health until 200 or timeout.

        Status-only check: /health bodies are free-form (our server returns
        plain text, vLLM returns empty) — never parse them.
        """
        deadline = time.monotonic() + self.health_check_timeout_s
        while time.monotonic() < deadline:
            try:
                url = f"http://{self.model_server}/health"
                with urllib.request.urlopen(url, timeout=self.http_timeout_s) as resp:
                    if resp.status == 200:
                        return True
            except (OSError, urllib.error.URLError):
                pass
            logger.info("server %s not healthy yet, retrying", self.model_server)
            time.sleep(self.health_check_interval_s)
        return False

    def registered_adapters(self) -> set[str]:
        """sidecar.py:140-155: adapter ids currently on the server."""
        try:
            data = self._get("/v1/models")
        except (OSError, urllib.error.URLError, json.JSONDecodeError) as e:
            logger.error("cannot list models: %s", e)
            return set()
        return {m["id"] for m in data.get("data", []) if m.get("parent")}

    def load_adapter(self, adapter: LoraAdapter) -> str | None:
        """sidecar.py:177-195: skip if registered, else POST load."""
        if adapter.id in self.registered_adapters():
            logger.info("adapter %s already loaded", adapter.id)
            return None
        status, body = self._post(
            "/v1/load_lora_adapter",
            {"lora_name": adapter.id, "lora_path": adapter.source},
        )
        if status != 200:
            return f"load {adapter.id}: HTTP {status} {body}"
        logger.info("loaded adapter %s from %s", adapter.id, adapter.source)
        return None

    def unload_adapter(self, adapter: LoraAdapter) -> str | None:
        """sidecar.py:197-213: skip if absent, else POST unload."""
        if adapter.id not in self.registered_adapters():
            return None
        status, body = self._post(
            "/v1/unload_lora_adapter", {"lora_name": adapter.id}
        )
        if status != 200:
            return f"unload {adapter.id}: HTTP {status} {body}"
        logger.info("unloaded adapter %s", adapter.id)
        return None

    # -- planner mode ---------------------------------------------------------
    def source_registry(self) -> dict[str, str]:
        """Adapter id -> checkpoint source from the config's ensureExist
        section — the path fallback for planner decisions that carry
        none (the planner may not know the checkpoint layout)."""
        return {a.id: a.source for a in self._adapters("ensureExist")}

    def planner_decisions(self) -> list[dict]:
        """Fetch /debug/placement and keep the decisions addressed to
        THIS replica: by pod name when --pod-name was given, else by the
        decision's ``address`` matching our model server."""
        url = f"{self.planner_url}/debug/placement"
        try:
            with urllib.request.urlopen(url, timeout=self.http_timeout_s) as resp:
                payload = json.loads(resp.read())
        except (OSError, urllib.error.URLError, json.JSONDecodeError) as e:
            logger.error("cannot poll planner %s: %s", url, e)
            return []
        mine = []
        for d in payload.get("decisions", []):
            if self.pod_name is not None:
                if d.get("pod") != self.pod_name:
                    continue
            elif d.get("address") != self.model_server:
                continue
            mine.append(d)
        return mine

    def apply_decision(self, decision: dict,
                       sources: dict[str, str]) -> str | None:
        """Execute one planner decision over the adapter wire; returns an
        error string, or None (success / benign refusal).  409s are
        EXPECTED steady-state refusals (in-flight requests pin a demote;
        a load may race a slot filling) — the planner re-emits next tick
        if still warranted, exactly like the static reconciler retries."""
        action = decision.get("action", "")
        adapter = decision.get("adapter", "")
        endpoint = PLANNER_ACTION_ENDPOINTS.get(action)
        if endpoint is None or not adapter:
            return f"unintelligible decision {decision!r}"
        payload = {"lora_name": adapter}
        if action in ("prefetch", "migrate", "load"):
            path = decision.get("path") or sources.get(adapter, "")
            if not path:
                return (f"{action} {adapter}: no checkpoint path (planner "
                        "sent none and the config registry has no source)")
            payload["lora_path"] = path
        status, body = self._post(endpoint, payload)
        if status == 409:
            logger.info("%s %s deferred: %s", action, adapter, body)
            return None
        if status not in (200, 404):  # 404 = already gone (evict/demote)
            return f"{action} {adapter}: HTTP {status} {body}"
        logger.info("applied planner decision: %s %s", action, adapter)
        return None

    def reconcile_planner(self) -> list[str]:
        """Planner-mode reconcile: health-gate, then apply this replica's
        slice of the gateway's placement plan."""
        if not self.is_server_healthy():
            msg = f"server {self.model_server} unhealthy past timeout"
            logger.error(msg)
            return [msg]
        sources = self.source_registry()
        errors = []
        for decision in self.planner_decisions():
            err = self.apply_decision(decision, sources)
            if err:
                errors.append(err)
        logger.info("planner reconcile complete (%d errors)", len(errors))
        return errors

    def reconcile(self) -> list[str]:
        """sidecar.py:215-239: health-gate, then drive to desired state.

        Returns accumulated errors (empty = converged).  Planner mode
        delegates to ``reconcile_planner`` — decisions instead of the
        static ensureExist/ensureNotExist diff.
        """
        if self.planner_url is not None:
            return self.reconcile_planner()
        if not self.is_server_healthy():
            msg = f"server {self.model_server} unhealthy past timeout"
            logger.error(msg)
            return [msg]
        errors = []
        ensure_exist = self._adapters("ensureExist")
        ensure_not_exist = self._adapters("ensureNotExist")
        to_load = ensure_exist - ensure_not_exist  # sidecar.py:230
        for adapter in sorted(to_load, key=lambda a: a.id):
            err = self.load_adapter(adapter)
            if err:
                errors.append(err)
        for adapter in sorted(ensure_not_exist, key=lambda a: a.id):
            err = self.unload_adapter(adapter)
            if err:
                errors.append(err)
        logger.info("reconcile complete (%d errors)", len(errors))
        return errors


def watch(reconciler: LoraReconciler, poll_interval_s: float = 2.0) -> None:
    """Mtime-gated watch loop (PollingObserver equivalent, sidecar.py:242-261).

    Planner mode polls EVERY interval — decisions change with the
    gateway's tick, not with a config file's mtime."""
    last_mtime = 0.0
    reconciler.reconcile()
    while True:
        if reconciler.planner_url is not None:
            reconciler.reconcile()
        else:
            try:
                mtime = os.stat(reconciler.config_file).st_mtime
                if mtime != last_mtime:
                    last_mtime = mtime
                    reconciler.reconcile()
            except OSError:
                pass
        time.sleep(poll_interval_s)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="dynamic LoRA rollout sidecar")
    parser.add_argument(
        "--config",
        default=os.environ.get("DYNAMIC_LORA_ROLLOUT_CONFIG", "/config/config.yaml"),
        help="adapter rollout config file (ConfigMap mount)",
    )
    parser.add_argument("--once", action="store_true", help="reconcile once and exit")
    parser.add_argument("--poll-interval", type=float, default=2.0)
    parser.add_argument(
        "--planner-url", default=None, metavar="URL",
        help="gateway base URL whose /debug/placement decisions drive "
             "this replica's residency ladder (prefetch/demote/evict/"
             "migrate) INSTEAD of the static ensureExist/ensureNotExist "
             "diff; the config file still supplies host/port and the "
             "adapter source registry")
    parser.add_argument(
        "--pod-name", default=None,
        help="this replica's pod name in the gateway's pool (planner "
             "decisions filter on it; default: match by host:port)")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    reconciler = LoraReconciler(args.config, planner_url=args.planner_url,
                                pod_name=args.pod_name)
    if args.once:
        errors = reconciler.reconcile()
        raise SystemExit(1 if errors else 0)
    watch(reconciler, args.poll_interval)


if __name__ == "__main__":
    main()
