// Native scheduler hot path: the filter decision tree over flat arrays.
//
// The gateway's per-request work is pure CPU: walk the filter tree over a
// snapshot of pod metrics (reference hot loop #2, SURVEY.md §3.2).  The
// reference runs this in Go; our Python tree is the semantic source of truth
// and this C++ mirror exists for large pools where the Python loop's
// per-pod overhead dominates pick latency (200+ pods at tens of kHz).
//
// Contract: lig_schedule_candidates() fills `out` with the indices of the
// surviving candidate set (the final random pick stays in Python so RNG
// behavior is unchanged) and returns the count; returns LIG_SHED (-1) for
// the load-shedding drop and LIG_ERROR (-2) on invalid input.  Semantics
// mirror gateway/scheduling/{filter,scheduler}.py exactly; the parity test
// (tests/test_native_scheduler.py) fuzzes both against each other.
//
// Build: make -C llm_instance_gateway_tpu/native  (emits libligsched.so)

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

struct Pods {
  int n;
  const int32_t* waiting;        // total queue depth
  const int32_t* prefill;        // prefill queue depth
  const double* kv_usage;        // 0..1
  const int64_t* kv_free;        // free KV tokens
  const uint8_t* has_affinity;   // request's adapter resident on pod?
  const int32_t* n_active;       // resident adapter count
  const int32_t* max_active;     // adapter slot count
};

struct Config {
  double kv_cache_threshold;
  int32_t queue_threshold_critical;
  int32_t queueing_threshold_lora;
  double token_headroom_factor;
  int32_t prefill_queue_threshold;
  bool token_aware;
  bool prefill_aware;
};

using Set = std::vector<int32_t>;

// Bucketing filters: keep pods in [min, min + (max-min)/len(set)]
// (integer division for queues, float for kv — filter.go:117/:149 parity).

Set least_queuing(const Pods& p, const Set& in) {
  int32_t lo = INT32_MAX, hi = 0;
  for (int32_t i : in) {
    lo = p.waiting[i] < lo ? p.waiting[i] : lo;
    hi = p.waiting[i] > hi ? p.waiting[i] : hi;
  }
  const int32_t cut = lo + (hi - lo) / static_cast<int32_t>(in.size());
  Set out;
  for (int32_t i : in)
    if (p.waiting[i] <= cut) out.push_back(i);
  return out;
}

Set least_prefill(const Pods& p, const Set& in) {
  int32_t lo = INT32_MAX, hi = 0;
  for (int32_t i : in) {
    lo = p.prefill[i] < lo ? p.prefill[i] : lo;
    hi = p.prefill[i] > hi ? p.prefill[i] : hi;
  }
  const int32_t cut = lo + (hi - lo) / static_cast<int32_t>(in.size());
  Set out;
  for (int32_t i : in)
    if (p.prefill[i] <= cut) out.push_back(i);
  return out;
}

Set least_kv(const Pods& p, const Set& in) {
  double lo = 1e300, hi = 0.0;
  for (int32_t i : in) {
    lo = p.kv_usage[i] < lo ? p.kv_usage[i] : lo;
    hi = p.kv_usage[i] > hi ? p.kv_usage[i] : hi;
  }
  const double cut = lo + (hi - lo) / static_cast<double>(in.size());
  Set out;
  for (int32_t i : in)
    if (p.kv_usage[i] <= cut) out.push_back(i);
  return out;
}

// Queue stage: optional prefill bucketing, then total-queue bucketing
// (scheduler.py queue_filter()).
Set queue_stage(const Pods& p, const Config& c, const Set& in) {
  Set s = in;
  if (c.prefill_aware) s = least_prefill(p, s);
  return least_queuing(p, s);
}

// queueAndKVCacheFilter (scheduler.go:49-56).
Set queue_kv(const Pods& p, const Config& c, const Set& in) {
  return least_kv(p, queue_stage(p, c, in));
}

// queueLoRAAndKVCacheFilter (scheduler.go:35-46): queue -> low-cost-LoRA
// predicate (failure passes the queue-stage output through) -> least-KV.
Set queue_lora_kv(const Pods& p, const Config& c, const Set& in) {
  Set q = queue_stage(p, c, in);
  Set lora;
  for (int32_t i : q)
    if (p.has_affinity[i] || p.n_active[i] < p.max_active[i]) lora.push_back(i);
  return least_kv(p, lora.empty() ? q : lora);
}

}  // namespace

extern "C" {

constexpr int32_t LIG_SHED = -1;
constexpr int32_t LIG_ERROR = -2;

int32_t lig_schedule_candidates(
    int32_t n_pods, const int32_t* waiting, const int32_t* prefill,
    const double* kv_usage, const int64_t* kv_free,
    const int64_t* kv_capacity, const uint8_t* has_affinity,
    const int32_t* n_active, const int32_t* max_active,
    // request
    uint8_t critical, int64_t prompt_tokens,
    // config
    double kv_cache_threshold, int32_t queue_threshold_critical,
    int32_t queueing_threshold_lora, double token_headroom_factor,
    int32_t prefill_queue_threshold, uint8_t token_aware,
    uint8_t prefill_aware,
    // out: caller-allocated buffer of n_pods ints
    int32_t* out) {
  if (n_pods <= 0 || !waiting || !prefill || !kv_usage || !kv_free ||
      !kv_capacity || !has_affinity || !n_active || !max_active || !out)
    return LIG_ERROR;

  const Pods p{n_pods, waiting, prefill, kv_usage, kv_free,
               has_affinity, n_active, max_active};
  const Config c{kv_cache_threshold, queue_threshold_critical,
                 queueing_threshold_lora, token_headroom_factor,
                 prefill_queue_threshold, token_aware != 0,
                 prefill_aware != 0};

  Set all(n_pods);
  for (int32_t i = 0; i < n_pods; ++i) all[i] = i;

  // Token-headroom gate (advisory: falls back to the full set).  Pods that
  // don't export KV-token metrics (capacity <= 0) pass trivially — filter.py
  // token_headroom parity.
  Set pool = all;
  if (c.token_aware && prompt_tokens > 0) {
    const int64_t need =
        static_cast<int64_t>(prompt_tokens * c.token_headroom_factor);
    Set fit;
    for (int32_t i : all)
      if (kv_capacity[i] <= 0 || kv_free[i] >= need) fit.push_back(i);
    if (!fit.empty()) pool = fit;
  }

  Set result;
  if (critical) {
    // lowLatencyFilter (scheduler.go:58-72).
    Set lowq;
    for (int32_t i : pool)
      if (p.waiting[i] < c.queueing_threshold_lora) lowq.push_back(i);
    if (!lowq.empty()) {
      Set aff;
      for (int32_t i : lowq)
        if (p.has_affinity[i]) aff.push_back(i);
      if (!aff.empty()) {
        result = queue_kv(p, c, aff);
      } else {
        Set room;
        for (int32_t i : lowq)
          if (p.n_active[i] < p.max_active[i]) room.push_back(i);
        result = queue_kv(p, c, room.empty() ? lowq : room);
      }
    } else {
      result = queue_lora_kv(p, c, pool);
    }
  } else {
    // sheddableRequestFilter (scheduler.go:74-90).
    Set ok;
    for (int32_t i : pool)
      if (p.waiting[i] <= c.queue_threshold_critical &&
          p.kv_usage[i] <= c.kv_cache_threshold)
        ok.push_back(i);
    if (ok.empty()) return LIG_SHED;
    result = queue_lora_kv(p, c, ok);
  }

  if (result.empty()) return LIG_SHED;  // tree exhausted: drop (parity)
  for (std::size_t k = 0; k < result.size(); ++k) out[k] = result[k];
  return static_cast<int32_t>(result.size());
}

}  // extern "C"
