// Native scheduler hot path: the filter decision tree over flat arrays.
//
// The gateway's per-request work is pure CPU: walk the filter tree over a
// snapshot of pod metrics (reference hot loop #2, SURVEY.md §3.2).  The
// reference runs this in Go; our Python tree is the semantic source of truth
// and this C++ mirror exists for large pools where the Python loop's
// per-pod overhead dominates pick latency (200+ pods at tens of kHz).
//
// Two entry families:
//
// 1. Stateless (legacy): lig_schedule_candidates() takes every pod array per
//    call.  Kept as the reference entry — zero hidden state, and the
//    cross-checking fuzz can drive it directly.
//
// 2. Snapshot-resident (the data-plane fast path): the caller allocates a
//    State handle (lig_state_new), pushes the pod arrays + policy avoid
//    marks + adapter-residency table + config into it ONCE per
//    observability/scrape tick (lig_state_update), and the per-pick call
//    (lig_pick / batched lig_pick_many) crosses the FFI with only request
//    scalars: (adapter_id, critical, prompt_tokens).  The candidate set is
//    written into a caller-owned buffer; the final random draw stays in
//    Python so RNG behavior is byte-identical to the Python Scheduler
//    (the parity oracle — tests/test_native_scheduler.py fuzzes both).
//
//    Policy semantics mirror scheduler.py filter_by_policy(): with
//    policy_mode=1 (avoid) the candidate set narrows to non-avoided pods,
//    falling back to the full set (escape hatch, flag bit 0) when every
//    candidate is avoidable; policy_mode=2 (strict) sheds instead
//    (LIG_SHED_STRICT).  policy_mode=0 (log_only) never filters.
//
//    Usage-deprioritization marks (gateway/usage.py noisy set) ride the
//    snapshot as per-adapter bits; a pick whose adapter is marked returns
//    flag bit 1 — the log-only observable stays in Python.  With
//    fairness_mode=1 (gateway/fairness.py deprioritize/enforce) the marks
//    become load-bearing: at marshal time each pod gets a "hog" bit (any
//    flagged adapter resident), and the candidate set narrows AFTER the
//    health/circuit policy filter — a quiet request (req_noisy=0) prefers
//    unmarked pods (all-marked sets escape with flag bit 2), a flagged
//    request (req_noisy=1, matched against the live noisy set in Python)
//    prefers the marked pods it already dominates.  Semantics mirror
//    scheduler.py filter_by_fairness() exactly.
//
// Contract: candidate-filling calls return the survivor count, LIG_SHED
// (-1) for the load-shedding drop, LIG_ERROR (-2) on invalid input, and
// LIG_SHED_STRICT (-3) for the strict-policy shed.  Semantics mirror
// gateway/scheduling/{filter,scheduler}.py exactly.
//
// Build: make -C llm_instance_gateway_tpu/native  (emits libligsched.so)

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <new>
#include <vector>

namespace {

struct PodArrays {
  int32_t n;
  const int32_t* waiting;        // total queue depth
  const int32_t* prefill;        // prefill queue depth
  const double* kv_usage;        // 0..1
  const int64_t* kv_free;        // free KV tokens
  const int64_t* kv_capacity;    // KV token capacity (<=0: not exported)
  const int32_t* n_active;       // resident adapter count
  const int32_t* max_active;     // adapter slot count
};

struct Config {
  double kv_cache_threshold;
  int32_t queue_threshold_critical;
  int32_t queueing_threshold_lora;
  double token_headroom_factor;
  int32_t prefill_queue_threshold;
  bool token_aware;
  bool prefill_aware;
};

using Set = std::vector<int32_t>;

inline bool has_aff(const uint8_t* aff, int32_t i) {
  return aff != nullptr && aff[i] != 0;
}

// Bucketing filters: keep pods in [min, min + (max-min)/len(set)]
// (integer division for queues, float for kv — filter.go:117/:149 parity).

// Bucket math runs in int64: a hostile replica can report INT32_MIN /
// INT32_MAX queue depths (the gateway scrapes foreign /metrics text), and
// (hi - lo) would overflow int32 — signed-overflow UB the ASan/UBSan fuzz
// (make native-asan) exercises explicitly.
Set least_queuing(const PodArrays& p, const Set& in) {
  int64_t lo = INT64_MAX, hi = INT64_MIN;
  for (int32_t i : in) {
    lo = p.waiting[i] < lo ? p.waiting[i] : lo;
    hi = p.waiting[i] > hi ? p.waiting[i] : hi;
  }
  const int64_t cut = lo + (hi - lo) / static_cast<int64_t>(in.size());
  Set out;
  for (int32_t i : in)
    if (p.waiting[i] <= cut) out.push_back(i);
  return out;
}

Set least_prefill(const PodArrays& p, const Set& in) {
  int64_t lo = INT64_MAX, hi = INT64_MIN;
  for (int32_t i : in) {
    lo = p.prefill[i] < lo ? p.prefill[i] : lo;
    hi = p.prefill[i] > hi ? p.prefill[i] : hi;
  }
  const int64_t cut = lo + (hi - lo) / static_cast<int64_t>(in.size());
  Set out;
  for (int32_t i : in)
    if (p.prefill[i] <= cut) out.push_back(i);
  return out;
}

Set least_kv(const PodArrays& p, const Set& in) {
  double lo = 1e300, hi = 0.0;
  for (int32_t i : in) {
    lo = p.kv_usage[i] < lo ? p.kv_usage[i] : lo;
    hi = p.kv_usage[i] > hi ? p.kv_usage[i] : hi;
  }
  const double cut = lo + (hi - lo) / static_cast<double>(in.size());
  Set out;
  for (int32_t i : in)
    if (p.kv_usage[i] <= cut) out.push_back(i);
  return out;
}

// Queue stage: optional prefill bucketing, then total-queue bucketing
// (scheduler.py queue_filter()).
Set queue_stage(const PodArrays& p, const Config& c, const Set& in) {
  Set s = in;
  if (c.prefill_aware) s = least_prefill(p, s);
  return least_queuing(p, s);
}

// queueAndKVCacheFilter (scheduler.go:49-56).
Set queue_kv(const PodArrays& p, const Config& c, const Set& in) {
  return least_kv(p, queue_stage(p, c, in));
}

// queueLoRAAndKVCacheFilter (scheduler.go:35-46): queue -> low-cost-LoRA
// predicate (failure passes the queue-stage output through) -> least-KV.
Set queue_lora_kv(const PodArrays& p, const Config& c, const uint8_t* aff,
                  const Set& in) {
  Set q = queue_stage(p, c, in);
  Set lora;
  for (int32_t i : q)
    if (has_aff(aff, i) || p.n_active[i] < p.max_active[i]) lora.push_back(i);
  return least_kv(p, lora.empty() ? q : lora);
}

constexpr int32_t kShed = -1;
constexpr int32_t kError = -2;
constexpr int32_t kShedStrict = -3;

// The full default tree (scheduler.go:26-91) over ``aff`` as the request's
// per-pod adapter-affinity view (nullptr = no pod holds the adapter).
// Fills ``result`` with surviving indices; returns count or kShed.
int32_t run_tree(const PodArrays& p, const Config& c, const uint8_t* aff,
                 uint8_t critical, int64_t prompt_tokens, Set* result) {
  Set all(p.n);
  for (int32_t i = 0; i < p.n; ++i) all[i] = i;

  // Token-headroom gate (advisory: falls back to the full set).  Pods that
  // don't export KV-token metrics (capacity <= 0) pass trivially — filter.py
  // token_headroom parity.
  Set pool = all;
  if (c.token_aware && prompt_tokens > 0) {
    // Clamp before the float->int cast: INT64_MAX prompt_tokens * 1.2
    // exceeds int64 range and the cast is UB (UBSan float-cast-overflow;
    // caught by the make native-asan fuzz).  The cast runs ONLY inside
    // the proven-finite range so a NaN factor (both comparisons false)
    // lands in the else branch (need=0: the advisory gate passes
    // everyone) instead of casting NaN — same defect class, same fate.
    const double need_f = prompt_tokens * c.token_headroom_factor;
    const int64_t need =
        (need_f > 0.0 && need_f < 9.2e18)
            ? static_cast<int64_t>(need_f)
            : (need_f >= 9.2e18 ? INT64_MAX : 0);
    Set fit;
    for (int32_t i : all)
      if (p.kv_capacity[i] <= 0 || p.kv_free[i] >= need) fit.push_back(i);
    if (!fit.empty()) pool = fit;
  }

  if (critical) {
    // lowLatencyFilter (scheduler.go:58-72).
    Set lowq;
    for (int32_t i : pool)
      if (p.waiting[i] < c.queueing_threshold_lora) lowq.push_back(i);
    if (!lowq.empty()) {
      Set a;
      for (int32_t i : lowq)
        if (has_aff(aff, i)) a.push_back(i);
      if (!a.empty()) {
        *result = queue_kv(p, c, a);
      } else {
        Set room;
        for (int32_t i : lowq)
          if (p.n_active[i] < p.max_active[i]) room.push_back(i);
        *result = queue_kv(p, c, room.empty() ? lowq : room);
      }
    } else {
      *result = queue_lora_kv(p, c, aff, pool);
    }
  } else {
    // sheddableRequestFilter (scheduler.go:74-90).
    Set ok;
    for (int32_t i : pool)
      if (p.waiting[i] <= c.queue_threshold_critical &&
          p.kv_usage[i] <= c.kv_cache_threshold)
        ok.push_back(i);
    if (ok.empty()) return kShed;
    *result = queue_lora_kv(p, c, aff, ok);
  }

  if (result->empty()) return kShed;  // tree exhausted: drop (parity)
  return static_cast<int32_t>(result->size());
}

// Snapshot-resident pod state: everything the tick-time update marshals so
// the per-pick crossing carries request scalars only.
struct State {
  int32_t n = 0;
  std::vector<int32_t> waiting, prefill_q, n_active, max_active;
  std::vector<double> kv_usage;
  std::vector<int64_t> kv_free, kv_capacity;
  std::vector<uint8_t> avoid;    // health/circuit avoid marks, per pod
  int32_t n_adapters = 0;
  std::vector<uint8_t> resident;  // n_adapters x n bitmap (row = adapter)
  std::vector<uint8_t> noisy;     // per-adapter usage-deprioritize marks
  std::vector<uint8_t> hog;       // per-pod: hosts any flagged adapter
  // Placement plane (gateway/placement.py): per-(adapter, pod) RAM-tier
  // residency marks (2 = slot tier, 1 = host tier, 0 = absent — the pick
  // narrows to the BEST tier present among candidates: a slot pick
  // decodes now, a host pick pays the promote) and a per-adapter
  // "resident anywhere in the POOL" bit, which may be set even when no
  // marshalled pod holds the adapter (role subsets) — that is exactly
  // the Python escape-hatch condition.
  std::vector<uint8_t> placed;       // n_adapters x n tier values
  std::vector<uint8_t> placed_any;   // per-adapter: resident somewhere
  Config cfg{};
  uint8_t policy_mode = 0;        // 0 log_only, 1 avoid, 2 strict
  uint8_t fairness_mode = 0;      // 0 log_only, 1 deprioritize/enforce
  uint8_t placement_mode = 0;     // 0 log_only, 1 prefer_resident
  bool ready = false;

  PodArrays view() const {
    return PodArrays{n, waiting.data(), prefill_q.data(), kv_usage.data(),
                     kv_free.data(), kv_capacity.data(), n_active.data(),
                     max_active.data()};
  }
};

int32_t pick_into(State* st, int32_t adapter_id, uint8_t critical,
                  uint8_t req_noisy, int64_t prompt_tokens, int32_t* out,
                  uint8_t* flags) {
  uint8_t f = 0;
  const uint8_t* aff = nullptr;
  if (adapter_id >= 0 && adapter_id < st->n_adapters) {
    aff = st->resident.data() + static_cast<size_t>(adapter_id) * st->n;
    if (st->noisy[adapter_id]) f |= 2;  // usage-deprioritization mark
  }
  Set result;
  const PodArrays p = st->view();
  const int32_t rc = run_tree(p, st->cfg, aff, critical, prompt_tokens,
                              &result);
  if (rc < 0) {
    if (flags) *flags = f;
    return rc;
  }
  if (st->policy_mode != 0) {
    // filter_by_policy parity: narrow to non-avoided candidates BEFORE the
    // RNG draw; an all-avoidable set escapes (avoid) or sheds (strict).
    Set preferred;
    for (int32_t i : result)
      if (!st->avoid[i]) preferred.push_back(i);
    if (!preferred.empty()) {
      result.swap(preferred);
    } else {
      bool any_marks = false;
      for (int32_t i : result)
        if (st->avoid[i]) { any_marks = true; break; }
      if (any_marks) {
        if (st->policy_mode == 2) {
          if (flags) *flags = f;
          return kShedStrict;
        }
        f |= 1;  // escape hatch: full set serves, Python counts it
      }
    }
  }
  if (st->fairness_mode != 0 && !st->hog.empty()) {
    // filter_by_fairness parity (scheduler.py): quiet requests prefer
    // non-hog pods (all-hog sets escape, flag bit 2); a flagged request
    // prefers the hog pods it already dominates (no hog candidate is not
    // an escape — nothing to avoid).
    Set pref;
    if (req_noisy) {
      for (int32_t i : result)
        if (st->hog[i]) pref.push_back(i);
      if (!pref.empty()) result.swap(pref);
    } else {
      for (int32_t i : result)
        if (!st->hog[i]) pref.push_back(i);
      if (!pref.empty()) {
        result.swap(pref);
      } else {
        bool any_marks = false;
        for (int32_t i : result)
          if (st->hog[i]) { any_marks = true; break; }
        if (any_marks) f |= 4;  // fairness escape: full set serves
      }
    }
  }
  if (st->placement_mode != 0 && adapter_id >= 0 &&
      adapter_id < st->n_adapters && !st->placed_any.empty() &&
      st->placed_any[adapter_id]) {
    // filter_by_placement parity (scheduler.py): the adapter is resident
    // SOMEWHERE in the pool — narrow to the candidates holding it at the
    // BEST tier present (slot=2 beats host=1); none resident among the
    // candidates escapes with flag bit 3.  An adapter resident nowhere
    // (placed_any == 0) never filters and never escapes — the planner's
    // prefetch rule owns the cold tail.
    const uint8_t* prow =
        st->placed.data() + static_cast<size_t>(adapter_id) * st->n;
    uint8_t best = 0;
    for (int32_t i : result)
      if (prow[i] > best) best = prow[i];
    if (best > 0) {
      Set pref;
      for (int32_t i : result)
        if (prow[i] == best) pref.push_back(i);
      result.swap(pref);
    } else {
      f |= 8;  // placement escape: full set serves, Python counts it
    }
  }
  for (std::size_t k = 0; k < result.size(); ++k) out[k] = result[k];
  if (flags) *flags = f;
  return static_cast<int32_t>(result.size());
}

}  // namespace

extern "C" {

constexpr int32_t LIG_SHED = kShed;
constexpr int32_t LIG_ERROR = kError;
constexpr int32_t LIG_SHED_STRICT = kShedStrict;

// Bump on ANY exported-signature change (the loader refuses mismatches
// and falls back to Python — an arity change against a prebuilt .so would
// otherwise scramble arguments or segfault in the routing hot path).
// The invariant linter (`make lint`, abi-drift rule) cross-checks these
// signatures against the ctypes marshals and the checked-in fingerprint,
// so a signature change without a bump fails in the TREE, not at load.
// 2 = fairness plane: lig_state_update +fairness_mode, lig_pick /
// lig_pick_many +req_noisy, escape flag bit 2.
// 3 = placement plane: lig_state_update +placed CSR (+placed_any bits)
// and +placement_mode, escape flag bit 3.
// 4 = sanitized-build hardening: lig_state_update +res_ids_len /
// +placed_ids_len so hostile CSR shapes (truncated offsets, id buffers
// shorter than offsets claim) are REJECTED with LIG_ERROR instead of
// read out of bounds.
int32_t lig_abi_version(void) { return 4; }

// ---- stateless reference entry (legacy ABI, unchanged semantics) ---------

int32_t lig_schedule_candidates(
    int32_t n_pods, const int32_t* waiting, const int32_t* prefill,
    const double* kv_usage, const int64_t* kv_free,
    const int64_t* kv_capacity, const uint8_t* has_affinity,
    const int32_t* n_active, const int32_t* max_active,
    // request
    uint8_t critical, int64_t prompt_tokens,
    // config
    double kv_cache_threshold, int32_t queue_threshold_critical,
    int32_t queueing_threshold_lora, double token_headroom_factor,
    int32_t prefill_queue_threshold, uint8_t token_aware,
    uint8_t prefill_aware,
    // out: caller-allocated buffer of n_pods ints
    int32_t* out) {
  if (n_pods <= 0 || !waiting || !prefill || !kv_usage || !kv_free ||
      !kv_capacity || !has_affinity || !n_active || !max_active || !out)
    return LIG_ERROR;
  const PodArrays p{n_pods, waiting, prefill, kv_usage, kv_free,
                    kv_capacity, n_active, max_active};
  const Config c{kv_cache_threshold, queue_threshold_critical,
                 queueing_threshold_lora, token_headroom_factor,
                 prefill_queue_threshold, token_aware != 0,
                 prefill_aware != 0};
  Set result;
  const int32_t rc = run_tree(p, c, has_affinity, critical, prompt_tokens,
                              &result);
  if (rc < 0) return rc;
  for (std::size_t k = 0; k < result.size(); ++k) out[k] = result[k];
  return rc;
}

// ---- snapshot-resident fast path -----------------------------------------

void* lig_state_new(void) { return new (std::nothrow) State(); }

void lig_state_free(void* h) { delete static_cast<State*>(h); }

// Marshal the whole routable world once per tick.  ``resident`` arrives as
// CSR (res_offsets[n_pods+1] into res_ids, res_ids_len entries) and is
// exploded into an adapter-major bitmap here so the per-pick affinity view
// is one row pointer.  The explicit ``*_len`` buffer lengths (ABI v4) let
// hostile CSR shapes — offsets claiming more entries than the id buffer
// holds, non-monotonic offsets — be rejected with LIG_ERROR instead of
// read out of bounds (the make native-asan fuzz drives exactly these).
// Returns 0 on success.
int32_t lig_state_update(
    void* h, int32_t n_pods,
    const int32_t* waiting, const int32_t* prefill, const double* kv_usage,
    const int64_t* kv_free, const int64_t* kv_capacity,
    const int32_t* n_active, const int32_t* max_active,
    const uint8_t* avoid,
    int32_t n_adapters, const int32_t* res_offsets, const int32_t* res_ids,
    int32_t res_ids_len, const uint8_t* adapter_noisy,
    const int32_t* placed_offsets, const int32_t* placed_ids,
    int32_t placed_ids_len, const uint8_t* placed_tiers,
    const uint8_t* placed_any,
    double kv_cache_threshold, int32_t queue_threshold_critical,
    int32_t queueing_threshold_lora, double token_headroom_factor,
    int32_t prefill_queue_threshold, uint8_t token_aware,
    uint8_t prefill_aware, uint8_t policy_mode, uint8_t fairness_mode,
    uint8_t placement_mode) {
  State* st = static_cast<State*>(h);
  if (!st || n_pods <= 0 || n_adapters < 0 || !waiting || !prefill ||
      !kv_usage || !kv_free || !kv_capacity || !n_active || !max_active ||
      !avoid || (n_adapters > 0 && (!res_offsets || !adapter_noisy)) ||
      (placement_mode != 0 && n_adapters > 0 &&
       (!placed_offsets || !placed_any)))
    return LIG_ERROR;
  // CSR shape validation: offsets must be monotonically non-decreasing,
  // start at 0, and end exactly at the id-buffer length (a truncated or
  // oversized offsets table is a marshal bug or a hostile caller, never
  // something to walk).
  if (n_adapters > 0) {
    if (res_ids_len < 0 || (res_ids_len > 0 && !res_ids)) return LIG_ERROR;
    if (res_offsets[0] != 0 || res_offsets[n_pods] != res_ids_len)
      return LIG_ERROR;
    for (int32_t pod = 0; pod < n_pods; ++pod)
      if (res_offsets[pod] > res_offsets[pod + 1]) return LIG_ERROR;
    if (placement_mode != 0) {
      if (placed_ids_len < 0 || (placed_ids_len > 0 && !placed_ids))
        return LIG_ERROR;
      if (placed_offsets[0] != 0 ||
          placed_offsets[n_pods] != placed_ids_len)
        return LIG_ERROR;
      for (int32_t pod = 0; pod < n_pods; ++pod)
        if (placed_offsets[pod] > placed_offsets[pod + 1]) return LIG_ERROR;
    }
  }
  st->ready = false;
  st->n = n_pods;
  st->waiting.assign(waiting, waiting + n_pods);
  st->prefill_q.assign(prefill, prefill + n_pods);
  st->kv_usage.assign(kv_usage, kv_usage + n_pods);
  st->kv_free.assign(kv_free, kv_free + n_pods);
  st->kv_capacity.assign(kv_capacity, kv_capacity + n_pods);
  st->n_active.assign(n_active, n_active + n_pods);
  st->max_active.assign(max_active, max_active + n_pods);
  st->avoid.assign(avoid, avoid + n_pods);
  st->n_adapters = n_adapters;
  st->resident.assign(
      static_cast<size_t>(n_adapters) * static_cast<size_t>(n_pods), 0);
  st->hog.assign(static_cast<size_t>(n_pods), 0);
  if (n_adapters > 0) {
    for (int32_t pod = 0; pod < n_pods; ++pod) {
      for (int32_t k = res_offsets[pod]; k < res_offsets[pod + 1]; ++k) {
        const int32_t a = res_ids[k];
        if (a < 0 || a >= n_adapters) return LIG_ERROR;
        st->resident[static_cast<size_t>(a) * n_pods + pod] = 1;
        if (adapter_noisy[a]) st->hog[pod] = 1;  // hosts a flagged adapter
      }
    }
    st->noisy.assign(adapter_noisy, adapter_noisy + n_adapters);
  } else {
    st->noisy.clear();
  }
  st->placed.clear();
  st->placed_any.clear();
  if (placement_mode != 0 && n_adapters > 0) {
    st->placed.assign(
        static_cast<size_t>(n_adapters) * static_cast<size_t>(n_pods), 0);
    st->placed_any.assign(placed_any, placed_any + n_adapters);
    for (int32_t pod = 0; pod < n_pods; ++pod) {
      for (int32_t k = placed_offsets[pod]; k < placed_offsets[pod + 1];
           ++k) {
        const int32_t a = placed_ids[k];
        if (a < 0 || a >= n_adapters) return LIG_ERROR;
        st->placed[static_cast<size_t>(a) * n_pods + pod] =
            placed_tiers ? placed_tiers[k] : 1;
      }
    }
  }
  st->cfg = Config{kv_cache_threshold, queue_threshold_critical,
                   queueing_threshold_lora, token_headroom_factor,
                   prefill_queue_threshold, token_aware != 0,
                   prefill_aware != 0};
  st->policy_mode = policy_mode;
  st->fairness_mode = fairness_mode;
  st->placement_mode = placement_mode;
  st->ready = true;
  return 0;
}

// One pick: request scalars in, candidate set out (caller buffer of n_pods
// ints).  Returns the count, LIG_SHED/LIG_SHED_STRICT, or LIG_ERROR.
// ``req_noisy``: the request's {model,adapter} is currently flagged noisy
// (matched against the live noisy-name set in Python, mirroring
// note_pick).  ``flags``: bit 3 = placement escape hatch (adapter
// resident in the pool but on no candidate); bit 0 = policy escape hatch used; bit 1 =
// adapter carries a usage-deprioritization mark; bit 2 = fairness escape
// hatch (every candidate hosted a flagged adapter).
int32_t lig_pick(void* h, int32_t adapter_id, uint8_t critical,
                 uint8_t req_noisy, int64_t prompt_tokens, int32_t* out,
                 uint8_t* flags) {
  State* st = static_cast<State*>(h);
  if (!st || !st->ready || !out) return LIG_ERROR;
  return pick_into(st, adapter_id, critical, req_noisy, prompt_tokens, out,
                   flags);
}

// Batched picks: one FFI crossing for n_reqs requests.  out_counts[i] gets
// the per-request count/shed code; out_cands is an (n_reqs x n_pods)
// row-major buffer; out_flags one byte per request.  Returns 0, or
// LIG_ERROR on invalid input.
int32_t lig_pick_many(void* h, int32_t n_reqs, const int32_t* adapter_ids,
                      const uint8_t* criticals, const uint8_t* req_noisies,
                      const int64_t* prompt_tokens,
                      int32_t* out_counts, int32_t* out_cands,
                      uint8_t* out_flags) {
  State* st = static_cast<State*>(h);
  if (!st || !st->ready || n_reqs <= 0 || !adapter_ids || !criticals ||
      !req_noisies || !prompt_tokens || !out_counts || !out_cands ||
      !out_flags)
    return LIG_ERROR;
  for (int32_t r = 0; r < n_reqs; ++r) {
    out_counts[r] = pick_into(
        st, adapter_ids[r], criticals[r], req_noisies[r], prompt_tokens[r],
        out_cands + static_cast<size_t>(r) * st->n, out_flags + r);
  }
  return 0;
}

}  // extern "C"
