// Prometheus text-exposition scanner: the metrics plane's hot loop.
//
// The provider scrapes every pod every 50ms (reference provider.go:14); at
// the 200-pod loadgen scale the pure-Python parser costs ~33% of the tick
// budget on one core (measured; see tests/test_native_prom.py).  This
// scanner does the per-line work — tokenizing, label-block bracketing,
// value/timestamp parsing — in one pass over the buffer and returns OFFSETS
// into the caller's text plus parsed numbers, so Python touches only real
// samples (and unescapes labels only for the rare labeled family it reads).
//
// Semantics mirror utils/prom_parse.py EXACTLY (fuzz-pinned by
// tests/test_native_prom.py), including its quirks: the label block spans
// the first '{' to the LAST '}' on the line; a line whose value token fails
// to parse is skipped; extra tokens after the timestamp are ignored.
//
// Build: make -C llm_instance_gateway_tpu/native (auto-run on staleness by
// utils/prom_parse._load_native).

// Known divergences from the Python parser, documented and fuzz-excluded
// (neither occurs in exposition text, which is ASCII by format):
// - PEP-515 underscore literals ("1_0") parse in Python, rejected here;
// - non-ASCII whitespace (e.g. NBSP U+00A0) separates tokens for Python's
//   str.split() but is treated as ordinary bytes here.
// Unicode LINE separators (U+0085/U+2028/U+2029) ARE honored in their
// UTF-8 encodings.

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

struct LigPromSample {
  int32_t name_off;
  int32_t name_len;
  int32_t labels_off;   // raw inner label block (no braces); -1 if none
  int32_t labels_len;
  double value;
  int64_t ts_ms;        // INT64_MIN = absent
};

static const int64_t TS_NONE = INT64_MIN;

static inline bool is_ws(char c) {
  // Within-line whitespace (str.split()): space and tab.  \r/\v/\f are
  // LINE BREAKS in Python's splitlines() and are handled by the line
  // scanner, so they never appear inside a line here.
  return c == ' ' || c == '\t';
}

// Bytes after text[p] that terminate a line, matching str.splitlines() on
// the UTF-8 encoding: \n \r \v \f \x1c \x1d \x1e, NEL (C2 85), and LS/PS
// (E2 80 A8/A9).  Returns the terminator's byte length (0 = not a break).
static inline int32_t line_break_len(const char* t, int32_t p, int32_t len) {
  unsigned char c = (unsigned char)t[p];
  if (c == '\n' || c == '\v' || c == '\f'
      || c == 0x1c || c == 0x1d || c == 0x1e) return 1;
  if (c == '\r') {
    return (p + 1 < len && t[p + 1] == '\n') ? 2 : 1;  // \r\n is ONE break
  }
  if (c == 0xC2 && p + 1 < len && (unsigned char)t[p + 1] == 0x85) return 2;
  if (c == 0xE2 && p + 2 < len && (unsigned char)t[p + 1] == 0x80
      && ((unsigned char)t[p + 2] == 0xA8 || (unsigned char)t[p + 2] == 0xA9))
    return 3;
  return 0;
}

// Parse one whitespace-delimited token as a double the way Python float()
// does: the WHOLE token must be consumed.  std::from_chars is locale-free
// and rejects the C99 hex floats / nan(seq) forms strtod would accept;
// Python's single leading '+' (which from_chars rejects) is skipped by hand.
static bool parse_token_double(const char* s, int32_t len, double* out) {
  if (len <= 0) return false;
  const char* p = s;
  const char* end = s + len;
  for (const char* q = p; q != end; q++) {
    if (*q == '(') return false;  // from_chars accepts nan(seq); Python doesn't
  }
  if (*p == '+') {
    p++;
    if (p == end || *p == '+' || *p == '-') return false;
  }
  auto res = std::from_chars(p, end, *out, std::chars_format::general);
  return res.ec == std::errc() && res.ptr == end;
}

int32_t lig_prom_parse(const char* text, int32_t len,
                       LigPromSample* out, int32_t cap) {
  int32_t n_out = 0;
  int32_t i = 0;
  while (i < len && n_out < cap) {
    // One line: [i, eol), terminated per str.splitlines().
    int32_t eol = i;
    int32_t brk = 0;
    while (eol < len && (brk = line_break_len(text, eol, len)) == 0) eol++;
    int32_t a = i, b = eol;
    i = eol + (brk > 0 ? brk : 1);
    while (a < b && is_ws(text[a])) a++;
    while (b > a && is_ws(text[b - 1])) b--;
    if (a == b || text[a] == '#') continue;

    int32_t name_off, name_len, labels_off = -1, labels_len = 0;
    int32_t rest;  // first index of the value/timestamp region
    // First '{' within the trimmed line?
    int32_t brace = -1;
    for (int32_t p = a; p < b; p++) {
      if (text[p] == '{') { brace = p; break; }
    }
    if (brace >= 0) {
      // Label block: first '{' .. LAST '}' (parity with the Python
      // parser's line.rfind("}")); unbalanced braces skip the line.
      int32_t close = -1;
      for (int32_t p = b - 1; p > brace; p--) {
        if (text[p] == '}') { close = p; break; }
      }
      if (close < 0) continue;
      name_off = a;
      name_len = brace - a;
      while (name_len > 0 && is_ws(text[name_off + name_len - 1])) name_len--;
      labels_off = brace + 1;
      labels_len = close - labels_off;
      rest = close + 1;
    } else {
      name_off = a;
      int32_t p = a;
      while (p < b && !is_ws(text[p])) p++;
      name_len = p - a;
      rest = p;
    }

    // Tokenize the rest: value [timestamp] [ignored...]
    int32_t p = rest;
    while (p < b && is_ws(text[p])) p++;
    if (p >= b) continue;  // no value token
    int32_t v0 = p;
    while (p < b && !is_ws(text[p])) p++;
    double value;
    if (!parse_token_double(text + v0, p - v0, &value)) continue;

    int64_t ts = TS_NONE;
    while (p < b && is_ws(text[p])) p++;
    if (p < b) {
      int32_t t0 = p;
      while (p < b && !is_ws(text[p])) p++;
      double tv;
      if (parse_token_double(text + t0, p - t0, &tv)
          && std::isfinite(tv)
          && tv >= -9223372036854775808.0   // int64 range: the cast of a
          && tv < 9223372036854775808.0) {  // NaN/Inf/overflow double is UB
        ts = (int64_t)tv;  // Python int(float(x)): truncate toward zero
      }
    }

    LigPromSample* s = &out[n_out++];
    s->name_off = name_off;
    s->name_len = name_len;
    s->labels_off = labels_off;
    s->labels_len = labels_len;
    s->value = value;
    s->ts_ms = ts;
  }
  return n_out;
}

}  // extern "C"
