// Hostile-snapshot + threaded FFI fuzzer for libligsched (built and run
// by `make native-asan` with -fsanitize=address,undefined, and by
// `make native-tsan` with -fsanitize=thread).
//
// The ctypes marshal in scheduling/native.py is a trusted caller, but the
// ABI is extern "C": any process that dlopens the .so can hand it garbage,
// and a marshal BUG (the exact class the PR-7 ABI drift shipped) would do
// the same from inside the gateway.  This harness drives the snapshot API
// with the hostile shapes the contract must reject gracefully
// (LIG_ERROR, never a read out of bounds):
//
//   - truncated CSR: offsets claiming more entries than the id buffer holds
//   - non-monotonic / non-zero-based CSR offsets
//   - out-of-range adapter ids inside the CSR payload
//   - zero- and negative-pod pools, negative batch sizes
//   - picks against never-updated and failed-update (not-ready) states
//   - null pointers where the v4 ABI expects buffers ("stale-ABI shape":
//     a caller marshalling the v3 arity would pass nulls/garbage in the
//     new slots — nulls must fail loudly, not scramble)
//
// plus a deterministic random-walk load (seeded LCG, no libc rand) of
// valid snapshots and pick/pick_many batches so the legitimate paths run
// under ASan/UBSan too.  Exit 0 = clean; any sanitizer report aborts.
//
// Threaded stages (the `make native-tsan` tentpole; also run under ASan
// for the extra coverage):
//
//   - fuzz_threaded_protocol: N picker threads calling lig_pick_many race
//     an updater thread swapping snapshots on the SAME state handle, all
//     serialized by a mutex mirroring NativeScheduler._call_lock — TSan
//     proves the real locking protocol race-free against the library's
//     actual memory accesses (the Python-side lock is only correct if the
//     library hides no unsynchronized global state behind it).
//   - fuzz_concurrent_const_picks: threads call lig_pick / lig_pick_many
//     concurrently with NO lock and no writer.  The pick path's contract
//     is const — candidate computation reads the snapshot and writes only
//     caller buffers.  A hidden mutable cache inside State would race
//     here and TSan would catch it; this is the property that lets the
//     gateway copy candidates out and run the finish seams unlocked.
//
// Build: make -C llm_instance_gateway_tpu/native asan   (ASan/UBSan)
//        make -C llm_instance_gateway_tpu/native tsan   (TSan)

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <limits>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
int32_t lig_abi_version(void);
void* lig_state_new(void);
void lig_state_free(void* h);
int32_t lig_state_update(
    void* h, int32_t n_pods, const int32_t* waiting, const int32_t* prefill,
    const double* kv_usage, const int64_t* kv_free,
    const int64_t* kv_capacity, const int32_t* n_active,
    const int32_t* max_active, const uint8_t* avoid, int32_t n_adapters,
    const int32_t* res_offsets, const int32_t* res_ids, int32_t res_ids_len,
    const uint8_t* adapter_noisy, const int32_t* placed_offsets,
    const int32_t* placed_ids, int32_t placed_ids_len,
    const uint8_t* placed_tiers, const uint8_t* placed_any,
    double kv_cache_threshold, int32_t queue_threshold_critical,
    int32_t queueing_threshold_lora, double token_headroom_factor,
    int32_t prefill_queue_threshold, uint8_t token_aware,
    uint8_t prefill_aware, uint8_t policy_mode, uint8_t fairness_mode,
    uint8_t placement_mode);
int32_t lig_pick(void* h, int32_t adapter_id, uint8_t critical,
                 uint8_t req_noisy, int64_t prompt_tokens, int32_t* out,
                 uint8_t* flags);
int32_t lig_pick_many(void* h, int32_t n_reqs, const int32_t* adapter_ids,
                      const uint8_t* criticals, const uint8_t* req_noisies,
                      const int64_t* prompt_tokens, int32_t* out_counts,
                      int32_t* out_cands, uint8_t* out_flags);
}

namespace {

constexpr int32_t kError = -2;

std::atomic<int> g_failures{0};  // threads bump it too

#define CHECK(cond, what)                                          \
  do {                                                             \
    if (!(cond)) {                                                 \
      std::fprintf(stderr, "FUZZ FAIL: %s (line %d)\n", what,      \
                   __LINE__);                                      \
      ++g_failures;                                                \
    }                                                              \
  } while (0)

// Deterministic PRNG (no libc rand: reproducible across platforms).
// Struct form so each fuzz thread owns an independent stream — a shared
// global seed would itself be the data race TSan reports first.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed = 0x9e3779b97f4a7c15ull) : s(seed) {}
  uint64_t next_u64() {
    s = s * 6364136223846793005ull + 1442695040888963407ull;
    return s >> 11;
  }
  int64_t rnd(int64_t lo, int64_t hi) {  // inclusive range
    return lo + static_cast<int64_t>(next_u64() % (hi - lo + 1));
  }
};

Rng g_rng;  // single-threaded stages only
int64_t rnd(int64_t lo, int64_t hi) { return g_rng.rnd(lo, hi); }

// A valid snapshot workspace the hostile cases mutate one field at a time.
struct Snapshot {
  int32_t n = 0, n_adapters = 0;
  std::vector<int32_t> waiting, prefill, n_active, max_active;
  std::vector<double> kv_usage;
  std::vector<int64_t> kv_free, kv_capacity;
  std::vector<uint8_t> avoid, noisy, placed_tiers, placed_any;
  std::vector<int32_t> res_offsets, res_ids, placed_offsets, placed_ids;
  uint8_t policy = 0, fairness = 0, placement = 0;

  void build(int32_t pods, int32_t adapters, bool extreme) {
    n = pods;
    n_adapters = adapters;
    waiting.assign(n, 0);
    prefill.assign(n, 0);
    n_active.assign(n, 0);
    max_active.assign(n, 4);
    kv_usage.assign(n, 0.0);
    kv_free.assign(n, 1 << 20);
    kv_capacity.assign(n, 1 << 20);
    avoid.assign(n, 0);
    for (int32_t i = 0; i < n; ++i) {
      if (extreme) {
        // Hostile replica metrics: INT32 extremes exercise the int64
        // bucket math (the signed-overflow UB this PR fixed).
        waiting[i] = static_cast<int32_t>(
            rnd(0, 1) ? rnd(INT32_MAX - 4, INT32_MAX)
                      : rnd(INT32_MIN, INT32_MIN + 4));
        prefill[i] = waiting[i];
        kv_usage[i] = rnd(0, 1) ? 1e300 : -1e300;
        kv_free[i] = rnd(0, 1) ? INT64_MAX : INT64_MIN;
        kv_capacity[i] = rnd(0, 1) ? INT64_MAX : 0;
      } else {
        waiting[i] = static_cast<int32_t>(rnd(0, 20));
        prefill[i] = static_cast<int32_t>(rnd(0, 10));
        kv_usage[i] = static_cast<double>(rnd(0, 100)) / 100.0;
        n_active[i] = static_cast<int32_t>(rnd(0, 4));
      }
      avoid[i] = static_cast<uint8_t>(rnd(0, 4) == 0);
    }
    res_offsets.assign(n + 1, 0);
    res_ids.clear();
    for (int32_t i = 0; i < n; ++i) {
      res_offsets[i] = static_cast<int32_t>(res_ids.size());
      const int32_t k = n_adapters > 0
                            ? static_cast<int32_t>(rnd(0, 2))
                            : 0;
      for (int32_t j = 0; j < k; ++j)
        res_ids.push_back(static_cast<int32_t>(rnd(0, n_adapters - 1)));
    }
    res_offsets[n] = static_cast<int32_t>(res_ids.size());
    noisy.assign(n_adapters > 0 ? n_adapters : 1, 0);
    for (auto& b : noisy) b = static_cast<uint8_t>(rnd(0, 3) == 0);
    placed_offsets = res_offsets;
    placed_ids = res_ids;
    placed_tiers.assign(placed_ids.size() ? placed_ids.size() : 1, 0);
    for (auto& t : placed_tiers) t = static_cast<uint8_t>(rnd(1, 2));
    placed_any.assign(n_adapters > 0 ? n_adapters : 1, 0);
    for (auto& b : placed_any) b = static_cast<uint8_t>(rnd(0, 1));
    policy = static_cast<uint8_t>(rnd(0, 2));
    fairness = static_cast<uint8_t>(rnd(0, 1));
    placement = static_cast<uint8_t>(rnd(0, 1));
  }

  int32_t update(void* h) const {
    return lig_state_update(
        h, n, waiting.data(), prefill.data(), kv_usage.data(),
        kv_free.data(), kv_capacity.data(), n_active.data(),
        max_active.data(), avoid.data(), n_adapters, res_offsets.data(),
        res_ids.data(), static_cast<int32_t>(res_ids.size()), noisy.data(),
        placed_offsets.data(), placed_ids.data(),
        static_cast<int32_t>(placed_ids.size()), placed_tiers.data(),
        placed_any.data(), 0.8, 5, 50, 1.2, 4, 1, 1, policy, fairness,
        placement);
  }
};

void picks_against(void* h, const Snapshot& s, int rounds) {
  std::vector<int32_t> out(s.n > 0 ? s.n : 1);
  for (int r = 0; r < rounds; ++r) {
    uint8_t flags = 0;
    // Out-of-range adapter ids (negative and huge) must behave like
    // "no affinity anywhere", never index the bitmap.
    const int32_t aid = static_cast<int32_t>(rnd(-3, s.n_adapters + 3));
    const int64_t toks = rnd(0, 2) == 0 ? rnd(INT64_MAX - 2, INT64_MAX)
                                        : rnd(0, 4096);
    const int32_t rc =
        lig_pick(h, aid, static_cast<uint8_t>(rnd(0, 1)),
                 static_cast<uint8_t>(rnd(0, 1)), toks, out.data(), &flags);
    CHECK(rc >= -3 && rc <= s.n, "lig_pick result out of contract range");
  }
}

void fuzz_valid_load() {
  void* h = lig_state_new();
  CHECK(h != nullptr, "lig_state_new");
  for (int iter = 0; iter < 400; ++iter) {
    Snapshot s;
    s.build(static_cast<int32_t>(rnd(1, 24)),
            static_cast<int32_t>(rnd(0, 8)), iter % 5 == 0);
    CHECK(s.update(h) == 0, "valid snapshot rejected");
    picks_against(h, s, 16);
    // Batched crossing over the same state.
    const int32_t n_reqs = static_cast<int32_t>(rnd(1, 32));
    std::vector<int32_t> aids(n_reqs), counts(n_reqs);
    std::vector<uint8_t> crit(n_reqs), noisy_req(n_reqs), flags(n_reqs);
    std::vector<int64_t> toks(n_reqs);
    std::vector<int32_t> cands(static_cast<size_t>(n_reqs) * s.n);
    for (int32_t i = 0; i < n_reqs; ++i) {
      aids[i] = static_cast<int32_t>(rnd(-2, s.n_adapters + 2));
      crit[i] = static_cast<uint8_t>(rnd(0, 1));
      noisy_req[i] = static_cast<uint8_t>(rnd(0, 1));
      toks[i] = rnd(0, 1 << 14);
    }
    CHECK(lig_pick_many(h, n_reqs, aids.data(), crit.data(),
                        noisy_req.data(), toks.data(), counts.data(),
                        cands.data(), flags.data()) == 0,
          "valid pick_many rejected");
    for (int32_t i = 0; i < n_reqs; ++i)
      CHECK(counts[i] >= -3 && counts[i] <= s.n,
            "pick_many count out of contract range");
  }
  lig_state_free(h);
}

void fuzz_hostile_shapes() {
  void* h = lig_state_new();
  CHECK(h != nullptr, "lig_state_new");
  Snapshot good;
  good.build(6, 4, false);
  CHECK(good.update(h) == 0, "baseline snapshot rejected");

  {  // Truncated CSR: offsets claim more ids than the buffer holds.
    Snapshot s = good;
    s.res_offsets[s.n] = static_cast<int32_t>(s.res_ids.size()) + 8;
    CHECK(s.update(h) == kError, "truncated resident CSR accepted");
    CHECK(lig_pick(h, 0, 1, 0, 16, nullptr, nullptr) == kError,
          "pick against a failed (not-ready) update did not error");
    CHECK(good.update(h) == 0, "state did not recover after bad update");
  }
  {  // Oversized id buffer (offsets end early): also a shape lie.
    Snapshot s = good;
    s.res_ids.push_back(0);
    CHECK(s.update(h) == kError, "oversized resident id buffer accepted");
  }
  {  // Non-monotonic offsets.
    Snapshot s = good;
    if (s.n >= 2) {
      s.res_offsets[1] = s.res_offsets[s.n] + 1;
      CHECK(s.update(h) == kError, "non-monotonic CSR offsets accepted");
    }
  }
  {  // Non-zero-based offsets.
    Snapshot s = good;
    for (auto& o : s.res_offsets) o += 1;
    CHECK(s.update(h) == kError, "non-zero-based CSR offsets accepted");
  }
  {  // Out-of-range adapter ids inside the payload.
    Snapshot s = good;
    if (!s.res_ids.empty()) {
      s.res_ids[0] = s.n_adapters + 7;
      CHECK(s.update(h) == kError, "out-of-range adapter id accepted");
      s.res_ids[0] = -1;
      CHECK(s.update(h) == kError, "negative adapter id accepted");
    }
  }
  {  // Hostile placement CSR (validated only when the mode is on).
    Snapshot s = good;
    s.placement = 1;
    s.placed_offsets[s.n] = static_cast<int32_t>(s.placed_ids.size()) + 3;
    CHECK(s.update(h) == kError, "truncated placement CSR accepted");
  }
  {  // Zero- and negative-pod pools.
    Snapshot s = good;
    s.n = 0;
    CHECK(lig_state_update(h, 0, nullptr, nullptr, nullptr, nullptr,
                           nullptr, nullptr, nullptr, nullptr, 0, nullptr,
                           nullptr, 0, nullptr, nullptr, nullptr, 0,
                           nullptr, nullptr, 0.8, 5, 50, 1.2, 4, 1, 1, 0,
                           0, 0) == kError,
          "zero-pod pool accepted");
    CHECK(lig_state_update(h, -4, good.waiting.data(),
                           good.prefill.data(), good.kv_usage.data(),
                           good.kv_free.data(), good.kv_capacity.data(),
                           good.n_active.data(), good.max_active.data(),
                           good.avoid.data(), good.n_adapters,
                           good.res_offsets.data(), good.res_ids.data(),
                           static_cast<int32_t>(good.res_ids.size()),
                           good.noisy.data(), nullptr, nullptr, 0, nullptr,
                           nullptr, 0.8, 5, 50, 1.2, 4, 1, 1, 0, 0,
                           0) == kError,
          "negative-pod pool accepted");
  }
  {  // Stale-ABI shape: a v3-arity caller leaves the new slots null/0.
    Snapshot s = good;
    CHECK(lig_state_update(
              h, s.n, s.waiting.data(), s.prefill.data(),
              s.kv_usage.data(), s.kv_free.data(), s.kv_capacity.data(),
              s.n_active.data(), s.max_active.data(), s.avoid.data(),
              s.n_adapters, s.res_offsets.data(), nullptr,
              static_cast<int32_t>(s.res_ids.size()), s.noisy.data(),
              nullptr, nullptr, 0, nullptr, nullptr, 0.8, 5, 50, 1.2, 4,
              1, 1, 0, 0, 0) == kError,
          "null id buffer with nonzero claimed length accepted");
  }
  {  // NaN/Inf config doubles: the extern "C" surface does not validate
     // them, so the token-headroom clamp must route NaN away from the
     // float->int cast (UB) — both clamp comparisons are false for NaN.
    const double bad_doubles[] = {
        std::numeric_limits<double>::quiet_NaN(),
        std::numeric_limits<double>::infinity(),
        -std::numeric_limits<double>::infinity()};
    std::vector<int32_t> out(good.n);
    for (double factor : bad_doubles) {
      CHECK(lig_state_update(
                h, good.n, good.waiting.data(), good.prefill.data(),
                good.kv_usage.data(), good.kv_free.data(),
                good.kv_capacity.data(), good.n_active.data(),
                good.max_active.data(), good.avoid.data(),
                good.n_adapters, good.res_offsets.data(),
                good.res_ids.data(),
                static_cast<int32_t>(good.res_ids.size()),
                good.noisy.data(), good.placed_offsets.data(),
                good.placed_ids.data(),
                static_cast<int32_t>(good.placed_ids.size()),
                good.placed_tiers.data(), good.placed_any.data(), 0.8, 5,
                50, factor, 4, 1, 1, 0, 0, 0) == 0,
            "non-finite headroom factor rejected (should marshal)");
      uint8_t flags = 0;
      const int32_t rc = lig_pick(h, 0, 1, 0, INT64_MAX, out.data(),
                                  &flags);
      CHECK(rc >= -3 && rc <= good.n,
            "pick under non-finite headroom factor out of range");
    }
    CHECK(good.update(h) == 0, "baseline re-update after NaN configs");
  }
  {  // Never-updated state + null outputs.
    void* fresh = lig_state_new();
    int32_t out[8];
    uint8_t flags = 0;
    CHECK(lig_pick(fresh, 0, 1, 0, 16, out, &flags) == kError,
          "pick against a never-updated state did not error");
    lig_state_free(fresh);
    CHECK(lig_pick(nullptr, 0, 1, 0, 16, out, &flags) == kError,
          "pick against a null state did not error");
    CHECK(good.update(h) == 0, "baseline re-update failed");
    CHECK(lig_pick(h, 0, 1, 0, 16, nullptr, &flags) == kError,
          "pick with a null out buffer did not error");
    int32_t counts[1];
    int64_t toks[1] = {16};
    int32_t aids[1] = {0};
    uint8_t crit[1] = {1}, noisyr[1] = {0}, oflags[1];
    int32_t cands[8];
    CHECK(lig_pick_many(h, 0, aids, crit, noisyr, toks, counts, cands,
                        oflags) == kError,
          "pick_many with n_reqs=0 did not error");
    CHECK(lig_pick_many(h, -1, aids, crit, noisyr, toks, counts, cands,
                        oflags) == kError,
          "pick_many with negative n_reqs did not error");
    CHECK(lig_pick_many(h, 1, nullptr, crit, noisyr, toks, counts, cands,
                        oflags) == kError,
          "pick_many with null adapter ids did not error");
  }
  lig_state_free(h);
  lig_state_free(nullptr);  // must be a no-op, not a crash
}

// ---- threaded stages (the make native-tsan tentpole) ----------------------

constexpr int32_t kMaxThreadedPods = 16;

// Picker threads racing an updater's snapshot swaps on ONE handle, every
// call serialized by a mutex mirroring the gateway's _call_lock protocol.
// TSan verifies the protocol suffices against the library's real memory
// accesses; the contract CHECKs verify picks stay in range across swaps.
void fuzz_threaded_protocol() {
  void* h = lig_state_new();
  CHECK(h != nullptr, "lig_state_new (threaded)");
  std::mutex call_lock;
  Snapshot snaps[2];
  snaps[0].build(8, 4, false);
  snaps[1].build(kMaxThreadedPods, 6, false);
  {
    std::lock_guard<std::mutex> g(call_lock);
    CHECK(snaps[0].update(h) == 0, "threaded baseline snapshot rejected");
  }
  std::atomic<bool> stop{false};
  std::thread updater([&] {
    for (int i = 0; i < 400; ++i) {
      const Snapshot& s = snaps[i & 1];
      std::lock_guard<std::mutex> g(call_lock);
      CHECK(s.update(h) == 0, "threaded snapshot swap rejected");
    }
    stop.store(true);
  });
  std::vector<std::thread> pickers;
  for (int t = 0; t < 4; ++t) {
    pickers.emplace_back([&, t] {
      Rng r(0x1000 + static_cast<uint64_t>(t) * 7919);
      constexpr int32_t kMaxReqs = 8;
      int32_t aids[kMaxReqs], counts[kMaxReqs];
      uint8_t crit[kMaxReqs], noisy_req[kMaxReqs], flags[kMaxReqs];
      int64_t toks[kMaxReqs];
      int32_t cands[kMaxReqs * kMaxThreadedPods];
      while (!stop.load()) {
        const int32_t n_reqs = static_cast<int32_t>(r.rnd(1, kMaxReqs));
        for (int32_t i = 0; i < n_reqs; ++i) {
          aids[i] = static_cast<int32_t>(r.rnd(-2, 8));
          crit[i] = static_cast<uint8_t>(r.rnd(0, 1));
          noisy_req[i] = static_cast<uint8_t>(r.rnd(0, 1));
          toks[i] = r.rnd(0, 1 << 14);
        }
        std::lock_guard<std::mutex> g(call_lock);
        CHECK(lig_pick_many(h, n_reqs, aids, crit, noisy_req, toks,
                            counts, cands, flags) == 0,
              "threaded pick_many rejected under the call lock");
        for (int32_t i = 0; i < n_reqs; ++i)
          CHECK(counts[i] >= -3 && counts[i] <= kMaxThreadedPods,
                "threaded pick_many count out of contract range");
      }
    });
  }
  updater.join();
  for (auto& th : pickers) th.join();
  lig_state_free(h);
}

// Concurrent lock-free picks against an immutable snapshot: the pick path
// is contractually const (reads the snapshot, writes caller buffers only).
// A hidden mutable cache inside State would be a TSan report here — this
// is the property that lets the gateway copy candidates out and run the
// prefix/RNG/note_* seams outside the lock.
void fuzz_concurrent_const_picks() {
  void* h = lig_state_new();
  CHECK(h != nullptr, "lig_state_new (const picks)");
  Snapshot s;
  s.build(12, 5, false);
  CHECK(s.update(h) == 0, "const-pick snapshot rejected");
  std::vector<std::thread> threads;
  for (int t = 0; t < 6; ++t) {
    threads.emplace_back([&, t] {
      Rng r(0x2000 + static_cast<uint64_t>(t) * 104729);
      int32_t out[12];
      int32_t aids[4], counts[4];
      uint8_t crit[4], noisy_req[4], flags[4];
      int64_t toks[4];
      int32_t cands[4 * 12];
      for (int iter = 0; iter < 2000; ++iter) {
        uint8_t f = 0;
        const int32_t rc = lig_pick(
            h, static_cast<int32_t>(r.rnd(-1, 6)),
            static_cast<uint8_t>(r.rnd(0, 1)),
            static_cast<uint8_t>(r.rnd(0, 1)), r.rnd(0, 4096), out, &f);
        CHECK(rc >= -3 && rc <= 12,
              "concurrent lig_pick out of contract range");
        for (int32_t i = 0; i < 4; ++i) {
          aids[i] = static_cast<int32_t>(r.rnd(-1, 6));
          crit[i] = static_cast<uint8_t>(r.rnd(0, 1));
          noisy_req[i] = static_cast<uint8_t>(r.rnd(0, 1));
          toks[i] = r.rnd(0, 4096);
        }
        CHECK(lig_pick_many(h, 4, aids, crit, noisy_req, toks, counts,
                            cands, flags) == 0,
              "concurrent lig_pick_many rejected");
      }
    });
  }
  for (auto& th : threads) th.join();
  lig_state_free(h);
}

}  // namespace

int main() {
  std::printf("libligsched ABI v%d hostile-snapshot fuzz\n",
              lig_abi_version());
  fuzz_valid_load();
  fuzz_hostile_shapes();
  std::printf("threaded fuzz: pick_many vs snapshot swaps under the call "
              "lock, then lock-free const picks\n");
  fuzz_threaded_protocol();
  fuzz_concurrent_const_picks();
  if (g_failures > 0) {
    std::fprintf(stderr, "FUZZ: %d failure(s)\n", g_failures.load());
    return 1;
  }
  std::printf("FUZZ PASS\n");
  return 0;
}
