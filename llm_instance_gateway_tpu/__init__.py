"""TPU-native inference gateway + serving framework.

A brand-new, TPU-first framework with the capabilities of
kubernetes-sigs/llm-instance-gateway (the Gateway API Inference Extension):

- ``gateway``  — the Endpoint Picker: filter-tree scheduler over a live metrics
  plane (KV-cache headroom, prefill/decode queue depths, criticality, LoRA
  affinity), ext-proc-style transport and a standalone reverse proxy.
- ``server``   — the TPU model server the reference delegates to vLLM:
  continuous batching with a prefill/decode split, paged KV cache, OpenAI-style
  API, Prometheus metrics matching the gateway contract, LoRA hot-swap via
  Orbax restore into pre-allocated adapter slots (no XLA recompilation).
- ``models``   — JAX/Flax-free pytree model definitions (Llama-3-style GQA
  decoder, Gemma, Mixtral-MoE) with multi-LoRA slot application.
- ``ops``      — Pallas TPU kernels (flash attention, paged decode attention,
  multi-LoRA bgmv) with XLA fallbacks for CPU tests.
- ``parallel`` — device-mesh shardings (dp/fsdp/tp/sp), ring attention for
  long context, collective helpers.
- ``api``      — InferencePool / InferenceModel declarative config
  (CRD-equivalent) + reconcilers in ``gateway.controllers``.
- ``sim``      — discrete-event simulator of continuous batching + routing,
  recalibrated to TPU latency models.

Reference parity map: see SURVEY.md at the repo root; docstrings throughout
cite /root/reference file:line for the behavior they match.
"""

__version__ = "0.1.0"
