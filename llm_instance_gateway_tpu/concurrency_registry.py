"""Registry of every cross-thread shared field the framework mutates.

PRs 10-11 multiplied the cross-thread surface: the statebus gossip path
calls ``set_remote_noisy`` / ``set_remote_avoid`` / ``set_remote_resident``
into advisor state that concurrent data-path picks read lock-free, per-pool
tick stacks run on the observability thread, the fleet collector and the
step profiler each added their own locks — ~40 ``threading.Lock`` sites
across the tree, each with a hand-maintained discipline that lived in
comments.  This module is the single declarative list (the
``metrics_registry.py`` shape): every class owning cross-thread state
declares its **owning domain**, its **lock attributes**, and — for every
field rebound after construction — the field's **publication discipline**
and the methods allowed to write it.

The concurrency lint (``lint/concurrency.py``; ``make lint``) cross-checks
this against the AST:

- ``ownership``: a class that constructs a lock but is not registered
  fails; a registered class assigning an undeclared field outside
  ``__init__`` fails; a write from a method not in the field's ``writers``
  allowlist fails.  Overlay seams (``set_remote_*``) are the declared
  gossip-domain exceptions, not folklore.
- ``publish-by-swap``: a field declared SWAP_PUBLISHED is read lock-free
  on the pick hot path, so writers must REPLACE the whole object —
  any in-place mutation (``.append``/``.update``/``[k] =``/``+=``) of it
  fails lint.
- ``lock-order``: the interprocedural acquisition graph over the declared
  lock attributes (plus call edges resolved through ``BINDINGS``) must be
  acyclic; the runtime ``lockwitness`` cross-checks the graph's
  completeness from real acquisitions in the interleave harness.

Keep entries grouped by module; ``tests/test_lint.py`` and the clean-tree
lint run are the currency tests — an undeclared shared field fails CI, a
dead entry fails CI.
"""

from __future__ import annotations

from dataclasses import dataclass

# -- owning domains (who mutates the state in steady operation) -------------
DATA_PATH = "data-path"            # request/pick threads (HTTP + gRPC pool)
OBS_TICK = "observability-tick"    # the proxy's control_tick thread
GOSSIP = "gossip"                  # statebus exchange / merged-view apply
ENGINE_STEP = "engine-step"        # the model server's engine loop thread
COLLECTOR = "collector"            # fleet-collector pulls (event loop)
CONTROL = "control"                # config reload / lifecycle (rare writes)

DOMAINS = (DATA_PATH, OBS_TICK, GOSSIP, ENGINE_STEP, COLLECTOR, CONTROL)

# -- publication disciplines ------------------------------------------------
# Reads and writes both happen inside the owning class's lock; lock-free
# readers are bugs (the lint can't see reads, but the witness harness and
# the discipline docs make the contract explicit).
LOCK_GUARDED = "lock-guarded"
# Lock-free reads on the hot path; writers REPLACE the field with a whole
# (effectively immutable) object — the ``_noisy_pods_cache`` tuple-swap
# idiom.  In-place mutation anywhere fails the publish-by-swap rule.
SWAP_PUBLISHED = "publish-by-swap"
# Increment-only numeric state owned by one domain (or bumped under the
# lock); readers tolerate a stale value, never a torn one.
MONOTONIC = "monotonic-counter"
# Touched only from the owning domain's single thread (the engine step
# loop's scratch state); in-place mutation is legal because there are NO
# cross-thread readers — crossing a thread boundary means re-declaring
# under one of the disciplines above.
OWNER_PRIVATE = "owner-private"

DISCIPLINES = (LOCK_GUARDED, SWAP_PUBLISHED, MONOTONIC, OWNER_PRIVATE)


@dataclass(frozen=True)
class SharedField:
    name: str
    discipline: str
    writers: tuple = ()    # methods allowed to rebind it (besides __init__)
    domain: str = ""       # override of the class domain (overlay seams)
    note: str = ""


@dataclass(frozen=True)
class SharedClass:
    module: str            # repo-relative path
    name: str
    domain: str            # dominant owning domain
    lock_attrs: tuple = ("_lock",)
    rlock_attrs: tuple = ()   # reentrant members of lock_attrs
    fields: tuple = ()
    note: str = ""


PKG = "llm_instance_gateway_tpu"

CLASSES = (
    # -- shared infrastructure ---------------------------------------------
    SharedClass(
        f"{PKG}/events.py", "EventJournal", DATA_PATH,
        lock_attrs=("_lock",),
        fields=(
            SharedField("_seq", MONOTONIC, writers=("emit",)),
        ),
        note="ring appends are GIL-atomic; seq/counters bump under the "
             "lock"),
    SharedClass(
        f"{PKG}/gateway/telemetry.py", "GatewayMetrics", DATA_PATH,
        fields=(
            SharedField("lora_affinity_hits", MONOTONIC,
                        writers=("record_pick",)),
        ),
        note="all counter tables mutate in place under the lock"),
    SharedClass(
        f"{PKG}/gateway/provider.py", "Provider", OBS_TICK,
        lock_attrs=("_lock",), rlock_attrs=("_lock",),
        fields=(
            SharedField("version", MONOTONIC,
                        writers=("refresh_metrics_once",
                                 "refresh_pods_once",
                                 "update_pod_metrics")),
        ),
        note="snapshot() hands out (version, pods) pairs; pods lists are "
             "swapped whole per refresh"),
    SharedClass(
        f"{PKG}/gateway/datastore.py", "Datastore", CONTROL,
        lock_attrs=("_lock",), rlock_attrs=("_lock",),
        fields=(
            SharedField("_pool", SWAP_PUBLISHED, writers=("set_pool",)),
        )),

    # -- gateway advisor stack (per pool) ----------------------------------
    SharedClass(
        f"{PKG}/gateway/health.py", "HealthScorer", OBS_TICK,
        fields=(
            SharedField("_non_healthy", SWAP_PUBLISHED,
                        writers=("update",),
                        note="the pick seam's lock-free avoid mark set"),
            SharedField("last_update", MONOTONIC, writers=("update",)),
            SharedField("would_avoid_total", MONOTONIC,
                        writers=("note_pick",), domain=DATA_PATH),
        )),
    SharedClass(
        f"{PKG}/gateway/resilience.py", "CircuitBreaker", DATA_PATH,
        fields=(
            SharedField("_blocked_cache", SWAP_PUBLISHED,
                        writers=("blocked_set",),
                        note="lock-free fast-path read; rebuilt under the "
                             "lock when dirty"),
            SharedField("_cache_expiry", SWAP_PUBLISHED,
                        writers=("blocked_set",)),
            SharedField("_cache_dirty", LOCK_GUARDED,
                        writers=("blocked_set", "note_pick", "prune",
                                 "_transition", "_maybe_half_open")),
        )),
    SharedClass(
        f"{PKG}/gateway/resilience.py", "RetryBudget", DATA_PATH,
        fields=(
            SharedField("_tokens", LOCK_GUARDED,
                        writers=("note_request", "try_spend")),
            SharedField("spent_total", MONOTONIC, writers=("try_spend",)),
            SharedField("denied_total", MONOTONIC, writers=("try_spend",)),
        )),
    SharedClass(
        f"{PKG}/gateway/resilience.py", "ResiliencePlane", DATA_PATH,
        fields=(
            SharedField("escape_hatch_total", MONOTONIC,
                        writers=("note_escape_hatch",)),
            SharedField("_remote_avoid", SWAP_PUBLISHED,
                        writers=("set_remote_avoid",), domain=GOSSIP,
                        note="statebus overlay; avoid_set() unions it "
                             "lock-free per pick"),
        )),
    SharedClass(
        f"{PKG}/gateway/usage.py", "UsageRollup", OBS_TICK,
        fields=(
            SharedField("_noisy_models", SWAP_PUBLISHED,
                        writers=("tick", "seed_noisy",
                                 "set_remote_noisy"),
                        note="noisy() serves it lock-free per pick"),
            SharedField("_noisy_key_of", SWAP_PUBLISHED,
                        writers=("tick", "seed_noisy"),
                        note="note_pick reads it lock-free; every writer "
                             "(seed_noisy included) rebuilds and swaps "
                             "the dict whole"),
            SharedField("_remote_noisy", SWAP_PUBLISHED,
                        writers=("set_remote_noisy",), domain=GOSSIP,
                        note="statebus overlay; note_pick falls back to "
                             "it lock-free"),
            SharedField("_totals", LOCK_GUARDED, writers=("tick",)),
            SharedField("_pool_waste", LOCK_GUARDED, writers=("tick",)),
            SharedField("_prev_requests", LOCK_GUARDED, writers=("tick",)),
            SharedField("last_tick", MONOTONIC,
                        writers=("tick",),
                        note="maybe_tick reads it lock-free (float "
                             "rebind)"),
            SharedField("ticks", MONOTONIC, writers=("tick",)),
            SharedField("would_deprioritize_total", MONOTONIC,
                        writers=("note_pick",), domain=DATA_PATH),
        )),
    SharedClass(
        f"{PKG}/gateway/kvobs.py", "KvObsRollup", OBS_TICK,
        fields=(
            SharedField("_remote_tables", SWAP_PUBLISHED,
                        writers=("set_remote_tables",), domain=GOSSIP,
                        note="peer-gateway residency overlay; tick() reads "
                             "it lock-free before joining, so writers swap "
                             "the whole dict"),
            SharedField("_pods", LOCK_GUARDED, writers=("tick",)),
            SharedField("_dup_rows", LOCK_GUARDED, writers=("tick",)),
            SharedField("_dup_totals", LOCK_GUARDED, writers=("tick",)),
            SharedField("_dup_prefixes", LOCK_GUARDED, writers=("tick",)),
            SharedField("last_tick", MONOTONIC, writers=("tick",),
                        note="maybe_tick reads it lock-free (float "
                             "rebind)"),
            SharedField("ticks", MONOTONIC, writers=("tick",)),
        ),
        note="EMA/delta tables (_prev_*, _*_rate) mutate in place under "
             "the lock; the kv_duplication journal emit runs after "
             "release (no nested acquisition)"),
    SharedClass(
        f"{PKG}/gateway/capacity.py", "CapacityPlanner", OBS_TICK,
        fields=(
            SharedField("_pods", LOCK_GUARDED,
                        writers=("_derive_saturation",),
                        note="derived lazily at render/debug time from "
                             "the stashed rows, under the same lock"),
            SharedField("_pool_saturation", LOCK_GUARDED,
                        writers=("_derive_saturation",)),
            SharedField("_prev", LOCK_GUARDED,
                        writers=("_fold_windows",),
                        note="rebuilt and swapped whole each fold (pod "
                             "membership churn prunes via the swap)"),
            SharedField("_rows_old", LOCK_GUARDED,
                        writers=("_fold_windows",)),
            SharedField("_sat_dt", LOCK_GUARDED,
                        writers=("_fold_windows",)),
            SharedField("_sat_ticks", LOCK_GUARDED,
                        writers=("_fold_windows", "_derive_saturation"),
                        note="fold invalidates, derive stamps — both "
                             "under the tick lock"),
            SharedField("_model", LOCK_GUARDED,
                        writers=("_load_artifact", "_refit"),
                        note="_load_artifact runs at construction; _refit "
                             "under the tick lock"),
            SharedField("_model_info", LOCK_GUARDED,
                        writers=("_load_artifact", "_refit")),
            SharedField("_forecast", LOCK_GUARDED,
                        writers=("_update_forecast",)),
            SharedField("_drift_state", LOCK_GUARDED,
                        writers=("_update_drift",)),
            SharedField("_drift_over", LOCK_GUARDED,
                        writers=("_update_drift",)),
            SharedField("_drift_under", LOCK_GUARDED,
                        writers=("_update_drift",)),
            SharedField("last_tick", MONOTONIC, writers=("tick",),
                        note="maybe_tick reads it lock-free (float "
                             "rebind)"),
            SharedField("ticks", MONOTONIC, writers=("tick",)),
        ),
        note="EMA tables (_windows, _mix, _rate_hist, _drift) mutate in "
             "place under the lock; the _fold/_refit/_update helpers all "
             "run from tick() inside it; journal emits run after release "
             "(kvobs discipline)"),
    SharedClass(
        f"{PKG}/gateway/pickledger.py", "PickLedger", OBS_TICK,
        fields=(
            SharedField("_rollup", SWAP_PUBLISHED, writers=("tick",),
                        note="seam_rollup() serves statebus/fleet/loadgen "
                             "readers lock-free; tick() rebuilds and "
                             "swaps the dict whole"),
            SharedField("_ring", LOCK_GUARDED, writers=("charge",)),
            SharedField("_seq", LOCK_GUARDED, writers=("charge",)),
            SharedField("_samples", LOCK_GUARDED, writers=("charge",)),
            SharedField("_stage_survivors", LOCK_GUARDED,
                        writers=("charge",)),
            SharedField("_stage_removed", LOCK_GUARDED,
                        writers=("charge",)),
            SharedField("_steered", LOCK_GUARDED, writers=("charge",)),
            SharedField("_decisive", LOCK_GUARDED, writers=("charge",)),
            SharedField("_escapes", LOCK_GUARDED, writers=("charge",)),
            SharedField("_steered_away", LOCK_GUARDED,
                        writers=("charge",)),
            SharedField("_shadow_mismatch", LOCK_GUARDED,
                        writers=("charge",)),
            SharedField("_picks_seen", MONOTONIC, writers=("sampled",),
                        domain=DATA_PATH,
                        note="per-pick int rebind next to the GIL-atomic "
                             "itertools.count bump; readers tolerate "
                             "one-pick staleness"),
            SharedField("last_tick", MONOTONIC, writers=("tick",),
                        note="maybe_tick reads it lock-free (float "
                             "rebind)"),
            SharedField("ticks", MONOTONIC, writers=("tick",)),
        ),
        note="charge() runs the counterfactual replays and builds the "
             "record BEFORE taking the lock; journal emits run after "
             "release (kvobs discipline — no nested acquisition)"),
    SharedClass(
        f"{PKG}/gateway/fairness.py", "FairnessPolicy", OBS_TICK,
        fields=(
            SharedField("_noisy_pods_cache", SWAP_PUBLISHED,
                        writers=("noisy_pods",), domain=DATA_PATH,
                        note="the checked tuple-swap idiom: (noisy-set "
                             "identity, frozenset) swapped whole; a "
                             "mid-pick swap can never tear "
                             "(tests/test_concurrency.py)"),
            SharedField("_fair_shares", LOCK_GUARDED, writers=("tick",)),
            SharedField("_shares", LOCK_GUARDED, writers=("tick",)),
            SharedField("_costs", LOCK_GUARDED, writers=("tick",)),
            SharedField("_throttled", LOCK_GUARDED, writers=("tick",)),
            SharedField("cfg", SWAP_PUBLISHED,
                        writers=("update_config",), domain=CONTROL,
                        note="whole FairnessConfig dataclass swapped on "
                             "hot reload"),
            SharedField("quota_scale", SWAP_PUBLISHED,
                        writers=("set_quota_scale",), domain=GOSSIP),
            SharedField("escape_total", MONOTONIC,
                        writers=("note_fairness_escape",),
                        domain=DATA_PATH),
            SharedField("ticks", MONOTONIC, writers=("tick",)),
        )),
    SharedClass(
        f"{PKG}/gateway/placement.py", "PlacementPlanner", OBS_TICK,
        fields=(
            SharedField("_resident_pods", SWAP_PUBLISHED,
                        writers=("_rebuild_merged_locked",),
                        note="note_pick/resident_pods read it lock-free"),
            SharedField("_tier_pods", SWAP_PUBLISHED,
                        writers=("_rebuild_merged_locked",),
                        note="identity doubles as the native marshal's "
                             "staleness signal"),
            SharedField("_have_residency", SWAP_PUBLISHED,
                        writers=("_rebuild_merged_locked",)),
            SharedField("_have_local_residency", SWAP_PUBLISHED,
                        writers=("tick",)),
            SharedField("_local_tier_pods", SWAP_PUBLISHED,
                        writers=("tick",),
                        note="statebus publishes it; swapped whole per "
                             "tick"),
            SharedField("_remote_tier_pods", SWAP_PUBLISHED,
                        writers=("set_remote_resident",), domain=GOSSIP),
            SharedField("_decisions", SWAP_PUBLISHED, writers=("tick",)),
            SharedField("_residency", LOCK_GUARDED, writers=("tick",)),
            SharedField("_idle", LOCK_GUARDED, writers=("tick",)),
            SharedField("_model_of", LOCK_GUARDED, writers=("tick",)),
            SharedField("cfg", SWAP_PUBLISHED, writers=("update_config",),
                        domain=CONTROL),
            SharedField("would_steer_total", MONOTONIC,
                        writers=("note_pick",), domain=DATA_PATH),
            SharedField("wrong_tier_total", MONOTONIC,
                        writers=("note_pick",), domain=DATA_PATH),
            SharedField("escape_total", MONOTONIC,
                        writers=("note_placement_escape",),
                        domain=DATA_PATH),
            SharedField("last_tick", MONOTONIC, writers=("tick",)),
            SharedField("ticks", MONOTONIC, writers=("tick",)),
        )),
    SharedClass(
        f"{PKG}/gateway/slo.py", "SLOEngine", OBS_TICK,
        fields=(
            SharedField("last_tick", MONOTONIC, writers=("tick",)),
        )),
    SharedClass(
        f"{PKG}/gateway/statebus.py", "StateBus", GOSSIP,
        fields=(
            SharedField("_seq", MONOTONIC, writers=("snapshot",),
                        domain=OBS_TICK),
            SharedField("_ever_saw_peer", SWAP_PUBLISHED,
                        writers=("merge",),
                        note="latching bool; set-once rebind"),
            SharedField("_stale", SWAP_PUBLISHED, writers=("apply",),
                        domain=OBS_TICK),
            SharedField("last_apply_scale", SWAP_PUBLISHED,
                        writers=("apply",), domain=OBS_TICK),
            SharedField("stale_fallbacks_total", MONOTONIC,
                        writers=("apply",), domain=OBS_TICK),
            SharedField("exchanges", MONOTONIC,
                        note="per-outcome counters mutated in place by "
                             "the exchange event loop only; render() "
                             "copies under the lock"),
        )),
    SharedClass(
        f"{PKG}/gateway/fleetobs.py", "FleetCollector", COLLECTOR,
        fields=(
            SharedField("last_sources", SWAP_PUBLISHED,
                        writers=("_collect_locked",)),
            SharedField("last_stitched", SWAP_PUBLISHED,
                        writers=("_collect_locked",)),
        )),

    # -- scheduling ----------------------------------------------------------
    SharedClass(
        f"{PKG}/gateway/scheduling/native.py", "NativeScheduler",
        DATA_PATH, lock_attrs=("_call_lock",),
        fields=(
            SharedField("_role_cache", SWAP_PUBLISHED,
                        writers=("_routable_pods",),
                        note="(version, pods, eff-version) tuple swapped "
                             "whole; racing writers compute identical "
                             "values for one snapshot version"),
            SharedField("cfg", SWAP_PUBLISHED, writers=("update_config",),
                        domain=CONTROL),
            SharedField("_decode_tree", SWAP_PUBLISHED,
                        writers=("update_config",), domain=CONTROL),
            SharedField("_oracle_tree", SWAP_PUBLISHED,
                        writers=("update_config",), domain=CONTROL,
                        note="the pick ledger's shadow-replay filter tree; "
                             "rebuilt and swapped whole on hot reload like "
                             "_decode_tree"),
            SharedField("_cfg_gen", MONOTONIC,
                        writers=("update_config",), domain=CONTROL),
        ),
        note="the native State handle + persistent buffers live entirely "
             "under _call_lock; the finish seams (prefix hash, RNG, "
             "note_*) run outside it by PR-6 contract (lock-discipline "
             "rule)"),
    SharedClass(
        f"{PKG}/gateway/scheduling/admission.py", "AdmissionController",
        DATA_PATH,
        fields=(
            SharedField("_cfg", SWAP_PUBLISHED, writers=("update_config",),
                        domain=CONTROL),
            SharedField("_queues", SWAP_PUBLISHED,
                        writers=("update_config",), domain=CONTROL),
            SharedField("_park_budget", SWAP_PUBLISHED,
                        writers=("set_park_budget",), domain=CONTROL),
            SharedField("_drain_scheduler", SWAP_PUBLISHED,
                        writers=("_arm",), domain=CONTROL),
            SharedField("_running", SWAP_PUBLISHED,
                        writers=("_arm", "stop"), domain=CONTROL),
            SharedField("_thread", SWAP_PUBLISHED, writers=("_arm",),
                        domain=CONTROL),
        )),
    SharedClass(
        f"{PKG}/gateway/scheduling/prefix_affinity.py", "PrefixIndex",
        DATA_PATH,
        note="holder map mutates in place under the lock; no post-init "
             "rebinds"),

    # -- controllers / transports -------------------------------------------
    SharedClass(
        f"{PKG}/gateway/controllers/filewatch.py", "MembershipAggregator",
        CONTROL),
    SharedClass(
        f"{PKG}/gateway/controllers/k8swatch.py", "KubeSource", CONTROL,
        lock_attrs=("_slices_lock",)),
    SharedClass(
        f"{PKG}/gateway/extproc/service.py", "HealthService", CONTROL,
        lock_attrs=("_watchers_lock",),
        fields=(
            SharedField("_watchers", LOCK_GUARDED, writers=("watch",),
                        note="admission counter inc/dec under the lock"),
        )),

    # -- model server --------------------------------------------------------
    SharedClass(
        f"{PKG}/server/usage.py", "UsageTracker", ENGINE_STEP,
        fields=(
            SharedField("_kv_holdings", LOCK_GUARDED,
                        writers=("sync_kv",)),
            SharedField("_kv_t", LOCK_GUARDED, writers=("sync_kv",)),
            SharedField("idle_slot_seconds", MONOTONIC,
                        writers=("charge_decode",)),
            SharedField("padding_tokens", MONOTONIC,
                        writers=("charge_padding",)),
        )),
    SharedClass(
        f"{PKG}/server/profiler.py", "StepProfiler", ENGINE_STEP,
        fields=(
            SharedField("_seq", MONOTONIC, writers=("note_dispatch",)),
            SharedField("_last_end", OWNER_PRIVATE,
                        writers=("note_dispatch",)),
            SharedField("_idle_pending", OWNER_PRIVATE,
                        writers=("note_dispatch", "note_idle")),
            SharedField("_foreign_wall", OWNER_PRIVATE,
                        writers=("note_dispatch",)),
            SharedField("_prev_active", OWNER_PRIVATE,
                        writers=("note_dispatch",)),
            SharedField("padding_tokens", MONOTONIC,
                        writers=("note_padding",)),
        )),
    SharedClass(
        f"{PKG}/server/kv_ledger.py", "KvLedger", ENGINE_STEP,
        fields=(
            SharedField("_states", LOCK_GUARDED, writers=("sync_states",),
                        note="recounted whole from allocator ground truth "
                             "per sync; snapshot() copies under the lock"),
            SharedField("_parked_tokens", LOCK_GUARDED,
                        writers=("sync_states",)),
            SharedField("_free_view", SWAP_PUBLISHED,
                        writers=("sync_states",),
                        note="immutable tuple of the free list, swapped "
                             "whole; the scrape-rate fragmentation "
                             "histogram reads it without re-walking the "
                             "allocator"),
            SharedField("_syncs", MONOTONIC, writers=("sync_states",)),
            SharedField("prefix_table_evictions", MONOTONIC,
                        writers=("_touch",)),
        ),
        note="event counters / prefix LRU / ring mutate in place under "
             "the lock (the GatewayMetrics shape); every note_* is "
             "engine-thread, snapshot() is the scrape thread"),
    SharedClass(
        f"{PKG}/server/lora_manager.py", "LoRAManager", ENGINE_STEP,
        lock_attrs=("_lock", "_mutate_lock"),
        fields=(
            SharedField("buffers", SWAP_PUBLISHED,
                        writers=("load", "demote", "unload"),
                        note="device buffer pytree swapped whole per "
                             "residency verb"),
        )),
    SharedClass(
        f"{PKG}/server/engine.py", "Engine", ENGINE_STEP,
        fields=(
            SharedField("_running", SWAP_PUBLISHED,
                        writers=("start", "stop"), domain=CONTROL),
            SharedField("_thread", SWAP_PUBLISHED, writers=("start",),
                        domain=CONTROL),
            SharedField("_draining", SWAP_PUBLISHED, writers=("drain",),
                        domain=CONTROL),
            SharedField("_admitting", LOCK_GUARDED,
                        writers=("_admit_and_insert",
                                 "_drain_decode_wait")),
            SharedField("_pending", LOCK_GUARDED,
                        writers=("_admit_and_insert", "_collect_followers",
                                 "_start_stream", "stop")),
            SharedField("_streams", LOCK_GUARDED,
                        note="chunk-stream lane list (engine-thread "
                             "append/remove in place; the scrape thread "
                             "iterates a list() copy like decode_wait)"),
            SharedField("_stream_rr", OWNER_PRIVATE,
                        writers=("_stream_step",),
                        note="fair-interleave round-robin cursor"),
            SharedField("_stops_active", OWNER_PRIVATE,
                        writers=("_clear_slot", "_program_stop_lanes"),
                        note="rows with programmed device stop lanes; "
                             "gates the history rebuild and excludes "
                             "speculative dispatch"),
            SharedField("decode_wait", LOCK_GUARDED,
                        writers=("_sweep_decode_wait",)),
            SharedField("_parked_kv_tokens", LOCK_GUARDED,
                        writers=("_do_attach", "_drain_decode_wait",
                                 "_park_waiting", "_sweep_decode_wait",
                                 "stop")),
            SharedField("cache", SWAP_PUBLISHED,
                        writers=("_insert_prompt_kv", "_sync_tables"),
                        note="KV pytree swapped whole by the engine "
                             "thread"),
            SharedField("draft_cache", OWNER_PRIVATE,
                        writers=("_draft_admit",)),
            SharedField("_tables_dirty", OWNER_PRIVATE,
                        writers=("_paged_ensure", "_paged_free_row",
                                 "_prefix_match_and_map", "_sync_tables")),
            SharedField("_kv_evicts_pending", OWNER_PRIVATE,
                        writers=("_paged_alloc_block", "_kv_ledger_sync"),
                        note="eviction tally drained into ONE aggregated "
                             "kv_evict journal event per ledger sync"),
            SharedField("_dev_counts", OWNER_PRIVATE,
                        writers=("_count_first_token", "_counts",
                                 "_dispatch_block", "_do_decode_step",
                                 "_register_slot")),
            SharedField("_dev_tokens", OWNER_PRIVATE,
                        writers=("_activate_slot_pipelined",
                                 "_dispatch_block", "_dispatch_spec_block",
                                 "_loop_pipelined")),
            SharedField("_dev_positions", OWNER_PRIVATE,
                        writers=("_activate_slot_pipelined",
                                 "_dispatch_block", "_dispatch_spec_block",
                                 "_loop_pipelined")),
            SharedField("_dev_remaining", OWNER_PRIVATE,
                        writers=("_activate_slot_pipelined",
                                 "_dispatch_block", "_dispatch_spec_block",
                                 "_loop_pipelined")),
            SharedField("_dev_stop_hist", OWNER_PRIVATE,
                        writers=("_activate_slot_pipelined",
                                 "_dispatch_block", "_loop_pipelined"),
                        note="stop-automaton history carry (device-"
                             "resident twin of _slot_stop_hist)"),
            SharedField("_dev_has_extra", OWNER_PRIVATE,
                        writers=("_activate_slot_pipelined",
                                 "_dispatch_spec_block", "_draft_admit",
                                 "_loop_pipelined")),
            SharedField("_dev_extra_pos", OWNER_PRIVATE,
                        writers=("_dispatch_spec_block",
                                 "_loop_pipelined")),
            SharedField("_dev_extra_tok", OWNER_PRIVATE,
                        writers=("_dispatch_spec_block",
                                 "_loop_pipelined")),
            SharedField("_pending_budget_zero", OWNER_PRIVATE,
                        writers=("_activate_slot_pipelined",
                                 "_loop_pipelined")),
            SharedField("_prev_dispatch_steps", OWNER_PRIVATE,
                        writers=("_loop_pipelined",
                                 "_paged_ensure_decode")),
            SharedField("decode_tps_ema", SWAP_PUBLISHED,
                        writers=("_do_decode_step", "_do_spec_step",
                                 "_process_block"),
                        note="float rebind; the scrape thread reads it "
                             "lock-free"),
            SharedField("prefix_reused_tokens", MONOTONIC,
                        writers=("_prefix_bucket_prefill",
                                 "_prefix_match_and_map")),
            SharedField("spec_cycles", MONOTONIC,
                        writers=("_dispatch_spec_block", "_do_spec_step")),
            SharedField("spec_emitted", MONOTONIC,
                        writers=("_do_spec_step", "_process_block")),
            SharedField("total_generated", MONOTONIC,
                        writers=("_do_decode_step", "_do_spec_step",
                                 "_emit_first_token", "_process_block")),
            SharedField("total_requests", MONOTONIC,
                        writers=("attach_prefilled", "submit"),
                        domain=DATA_PATH),
        ),
        note="device-array fields are engine-thread-owned and rebound "
             "whole; queue/park accounting shares the lock with the HTTP "
             "submit path"),
)

# Attribute-name -> registered class, for the lock-order rule's
# interprocedural call resolution (``self.usage.note_pick()`` resolves to
# ``UsageRollup.note_pick`` through this map).  One name, one class,
# repo-wide — keep attribute naming unambiguous or the analyzer (and the
# reader) loses the thread.
BINDINGS = {
    "journal": "EventJournal",
    "metrics": "GatewayMetrics",
    "provider": "Provider",
    "datastore": "Datastore",
    "health": "HealthScorer",
    "breaker": "CircuitBreaker",
    "retry_budget": "RetryBudget",
    "resilience": "ResiliencePlane",
    "health_advisor": "ResiliencePlane",
    "usage": "UsageRollup",
    "kvobs": "KvObsRollup",
    "capacity": "CapacityPlanner",
    "pickledger": "PickLedger",
    "pick_ledger": "PickLedger",
    "fairness": "FairnessPolicy",
    "usage_advisor": "FairnessPolicy",
    "placement": "PlacementPlanner",
    "placement_advisor": "PlacementPlanner",
    "slo": "SLOEngine",
    "statebus": "StateBus",
    "bus": "StateBus",
    "fleet": "FleetCollector",
    "prefix_index": "PrefixIndex",
    "admission": "AdmissionController",
    "tracker": "UsageTracker",
    "kv_ledger": "KvLedger",
    "profiler": "StepProfiler",
    "lora": "LoRAManager",
    "engine": "Engine",
}


def all_classes() -> tuple[SharedClass, ...]:
    return CLASSES


def by_name() -> dict[str, SharedClass]:
    return {c.name: c for c in CLASSES}


def render_markdown() -> str:
    """Domain/discipline catalogue for ARCHITECTURE.md §3m (generated on
    demand by docs tooling; the source of truth stays here)."""
    out = ["| class | module | domain | lock(s) | shared fields "
           "(discipline) |", "|---|---|---|---|---|"]
    for c in CLASSES:
        fields = ", ".join(
            f"`{f.name}` ({f.discipline}"
            + (f", {f.domain}" if f.domain and f.domain != c.domain else "")
            + ")"
            for f in c.fields) or "—"
        locks = ", ".join(f"`{a}`" for a in c.lock_attrs) or "—"
        out.append(f"| `{c.name}` | `{c.module.split('/', 1)[1]}` "
                   f"| {c.domain} | {locks} | {fields} |")
    return "\n".join(out)
