"""Minimal Prometheus text-exposition-format parser.

The reference uses ``expfmt.TextParser.TextToMetricFamilies``
(``pkg/ext-proc/backend/vllm/metrics.go:62-67``) to parse model-server
/metrics scrapes.  This is the Python equivalent: a small, dependency-free
parser producing ``{family_name: [Sample]}`` where each sample carries labels,
value, and optional timestamp (timestamps are how the reference selects the
*latest* LoRA info series, metrics.go:135-150).

Only the subset of the format the gateway consumes is supported: counters and
gauges with optional labels and timestamps; HELP/TYPE comments are skipped;
histogram/summary series parse as plain samples of their component families.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Sample:
    name: str
    labels: dict[str, str] = field(default_factory=dict)
    value: float = 0.0
    timestamp_ms: int | None = None


def _parse_labels(s: str) -> dict[str, str]:
    labels: dict[str, str] = {}
    i = 0
    n = len(s)
    while i < n:
        # key
        j = s.find("=", i)
        if j < 0:
            break
        key = s[i:j].strip().strip(",").strip()
        # value: quoted string with escapes
        k = s.find('"', j)
        if k < 0:
            break
        out = []
        k += 1
        while k < n:
            c = s[k]
            if c == "\\" and k + 1 < n:
                nxt = s[k + 1]
                out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                k += 2
                continue
            if c == '"':
                break
            out.append(c)
            k += 1
        labels[key] = "".join(out)
        i = k + 1
    return labels


def parse_text(text: str) -> dict[str, list[Sample]]:
    """Parse exposition text into families keyed by metric name."""
    families: dict[str, list[Sample]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        # name{labels} value [timestamp]
        labels: dict[str, str] = {}
        if "{" in line:
            brace = line.index("{")
            end = line.rfind("}")
            if end < brace:
                continue  # malformed line: unbalanced braces — skip, don't raise
            name = line[:brace].strip()
            labels = _parse_labels(line[brace + 1 : end])
            rest = line[end + 1 :].split()
        else:
            parts = line.split()
            name, rest = parts[0], parts[1:]
        if not rest:
            continue
        try:
            value = float(rest[0])
        except ValueError:
            continue
        ts = None
        if len(rest) > 1:
            try:
                ts = int(float(rest[1]))
            except ValueError:
                ts = None
        families.setdefault(name, []).append(
            Sample(name=name, labels=labels, value=value, timestamp_ms=ts)
        )
    return families


def latest_sample(samples: list[Sample]) -> Sample | None:
    """Latest-by-timestamp selection (metrics.go:135-150 getLatestLoraMetric).

    Samples without timestamps compare as oldest; with no timestamps at all the
    last sample in exposition order wins.
    """
    if not samples:
        return None
    best = samples[0]
    for s in samples[1:]:
        bt = best.timestamp_ms if best.timestamp_ms is not None else -1
        st = s.timestamp_ms if s.timestamp_ms is not None else -1
        if st >= bt:
            best = s
    return best
