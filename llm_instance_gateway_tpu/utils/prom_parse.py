"""Minimal Prometheus text-exposition-format parser.

The reference uses ``expfmt.TextParser.TextToMetricFamilies``
(``pkg/ext-proc/backend/vllm/metrics.go:62-67``) to parse model-server
/metrics scrapes.  This is the Python equivalent: a small, dependency-free
parser producing ``{family_name: [Sample]}`` where each sample carries labels,
value, and optional timestamp (timestamps are how the reference selects the
*latest* LoRA info series, metrics.go:135-150).

Only the subset of the format the gateway consumes is supported: counters and
gauges with optional labels and timestamps; HELP/TYPE comments are skipped;
histogram/summary series parse as plain samples of their component families.
"""

from __future__ import annotations

import ctypes
import threading


class Sample:
    """One exposition sample.  ``labels`` may be LAZY: the native scanner
    hands over the raw label block and it parses on first access — the
    gateway reads labels for exactly one family (lora_requests_info), so
    eagerly unescaping every histogram bucket's ``le=`` would dominate the
    scrape cost it exists to cut."""

    __slots__ = ("name", "value", "timestamp_ms", "_labels", "_raw_labels")

    def __init__(self, name: str, labels: dict[str, str] | None = None,
                 value: float = 0.0, timestamp_ms: int | None = None,
                 raw_labels: str | None = None):
        self.name = name
        self.value = value
        self.timestamp_ms = timestamp_ms
        self._labels = {} if labels is None and raw_labels is None else labels
        self._raw_labels = raw_labels

    @property
    def labels(self) -> dict[str, str]:
        if self._labels is None:
            self._labels = _parse_labels(self._raw_labels or "")
        return self._labels

    def __eq__(self, other):
        return (isinstance(other, Sample) and self.name == other.name
                and self.value == other.value
                and self.timestamp_ms == other.timestamp_ms
                and self.labels == other.labels)

    def __repr__(self):
        return (f"Sample(name={self.name!r}, labels={self.labels!r}, "
                f"value={self.value!r}, timestamp_ms={self.timestamp_ms!r})")


def _parse_labels(s: str) -> dict[str, str]:
    labels: dict[str, str] = {}
    i = 0
    n = len(s)
    while i < n:
        # key
        j = s.find("=", i)
        if j < 0:
            break
        key = s[i:j].strip().strip(",").strip()
        # value: quoted string with escapes
        k = s.find('"', j)
        if k < 0:
            break
        out = []
        k += 1
        while k < n:
            c = s[k]
            if c == "\\" and k + 1 < n:
                nxt = s[k + 1]
                out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                k += 2
                continue
            if c == '"':
                break
            out.append(c)
            k += 1
        labels[key] = "".join(out)
        i = k + 1
    return labels


def parse_text(text: str) -> dict[str, list[Sample]]:
    """Parse exposition text into families keyed by metric name."""
    families: dict[str, list[Sample]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        # name{labels} value [timestamp]
        labels: dict[str, str] = {}
        if "{" in line:
            brace = line.index("{")
            end = line.rfind("}")
            if end < brace:
                continue  # malformed line: unbalanced braces — skip, don't raise
            name = line[:brace].strip()
            labels = _parse_labels(line[brace + 1 : end])
            rest = line[end + 1 :].split()
        else:
            parts = line.split()
            name, rest = parts[0], parts[1:]
        if not rest:
            continue
        try:
            value = float(rest[0])
        except ValueError:
            continue
        ts = None
        if len(rest) > 1:
            try:
                ts = int(float(rest[1]))
            except (ValueError, OverflowError):  # junk / +-Inf
                ts = None
            else:
                # Timestamps are int64 epoch-millis on the wire; values a
                # 64-bit consumer can't hold are garbage, not data (and the
                # native scanner's int64 field could not represent them).
                # Exclusive lower bound: INT64_MIN is the scanner's
                # absent-timestamp sentinel, so it can't be a value either.
                if ts is not None and not (-(2 ** 63) < ts < 2 ** 63):
                    ts = None
        families.setdefault(name, []).append(
            Sample(name=name, labels=labels, value=value, timestamp_ms=ts)
        )
    return families


# ---------------------------------------------------------------------------
# Native fast path: the provider scrapes every pod every 50ms; at the 200-pod
# loadgen scale the pure-Python line loop costs ~33% of the tick budget on
# one core.  native/prom_parse.cc does the per-line scan and value parsing in
# C and returns byte offsets into the scrape body; Python materializes
# Sample objects from real samples only and unescapes labels in Python (so
# the escape semantics stay identical).  parse_text_fast auto-dispatches and
# falls back to the pure parser if the library can't build/load.
# ---------------------------------------------------------------------------

_native_lib = None
_native_tried = False
_native_lock = threading.Lock()


class _NativeSample(ctypes.Structure):
    _fields_ = [
        ("name_off", ctypes.c_int32),
        ("name_len", ctypes.c_int32),
        ("labels_off", ctypes.c_int32),
        ("labels_len", ctypes.c_int32),
        ("value", ctypes.c_double),
        ("ts_ms", ctypes.c_int64),
    ]


_TS_NONE = -(2 ** 63)


def _load_native():
    global _native_lib, _native_tried
    if _native_tried:
        return _native_lib
    with _native_lock:
        return _load_native_locked()


def _load_native_locked():
    # First scrapes fan out across the provider's thread pool: without the
    # lock, concurrent `make` runs could race a CDLL of a half-written .so
    # and permanently pin the fast path off.
    global _native_lib, _native_tried
    if _native_tried:
        return _native_lib
    _native_tried = True
    import logging
    import os
    import subprocess

    from llm_instance_gateway_tpu.utils.native_build import ensure_native_lib

    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
    try:
        lib_path = ensure_native_lib(native_dir, "libligprom.so",
                                     "prom_parse.cc")
        if lib_path is None:
            _native_lib = None
            return None
        lib = ctypes.CDLL(lib_path)
        lib.lig_prom_parse.restype = ctypes.c_int32
        lib.lig_prom_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(_NativeSample), ctypes.c_int32,
        ]
        _native_lib = lib
    except (OSError, subprocess.SubprocessError) as e:
        logging.getLogger(__name__).warning(
            "native prom parser unavailable (%s); using pure Python", e)
        _native_lib = None
    return _native_lib


_SAMPLE_DTYPE = None  # numpy view of LigPromSample; lazy (numpy import)


def parse_text_native(text: str) -> dict[str, list[Sample]]:
    """Native-scanner parse; semantics identical to ``parse_text``
    (fuzz-pinned).  Raises RuntimeError if the library is unavailable —
    call ``parse_text_fast`` for the auto-dispatching entry."""
    global _SAMPLE_DTYPE
    lib = _load_native()
    if lib is None:
        raise RuntimeError("native prom parser unavailable")
    import numpy as np

    if _SAMPLE_DTYPE is None:
        _SAMPLE_DTYPE = np.dtype([
            ("name_off", "<i4"), ("name_len", "<i4"),
            ("labels_off", "<i4"), ("labels_len", "<i4"),
            ("value", "<f8"), ("ts_ms", "<i8")])
        assert _SAMPLE_DTYPE.itemsize == ctypes.sizeof(_NativeSample)
    data = text.encode("utf-8", "surrogatepass")
    # Upper bound on samples: the shortest producible line ("a 1") is 3
    # bytes + a 1-byte break — counting only b"\n" would truncate bodies
    # using the exotic splitlines() separators.
    cap = len(data) // 4 + 2
    buf = (_NativeSample * cap)()
    n = lib.lig_prom_parse(data, len(data), buf, cap)
    # Per-field ctypes attribute access is slower than the Python parser it
    # replaces; one structured-array .tolist() materializes every field in C.
    rows = np.frombuffer(buf, dtype=_SAMPLE_DTYPE, count=n).tolist()
    families: dict[str, list[Sample]] = {}
    names: dict[bytes, str] = {}  # series names repeat heavily
    for name_off, name_len, labels_off, labels_len, value, ts in rows:
        # surrogatepass both ways: the body was ENCODED with surrogatepass,
        # so decoding the same bytes with it round-trips the original text
        # exactly — 'replace' here would diverge from parse_text on input
        # containing lone surrogates (an undocumented parity gap otherwise).
        nb = data[name_off:name_off + name_len]
        name = names.get(nb)
        if name is None:
            name = names.setdefault(nb, nb.decode("utf-8", "surrogatepass"))
        raw = (data[labels_off:labels_off + labels_len].decode(
                   "utf-8", "surrogatepass") if labels_len > 0 else None)
        families.setdefault(name, []).append(Sample(
            name=name, labels=None if raw else {}, raw_labels=raw,
            value=value, timestamp_ms=None if ts == _TS_NONE else ts))
    return families


# Below this size the native path's fixed costs (encode, ctypes call,
# result view) exceed the scan it saves; the tpu:* contract scrape is a few
# hundred bytes, while vLLM-style pages (histogram buckets) run tens of KB
# and win ~1.6x (more when labels stay lazily unparsed).
_NATIVE_MIN_BYTES = 4096


def parse_text_fast(text: str) -> dict[str, list[Sample]]:
    """C scanner for production-sized scrapes, pure Python otherwise."""
    if len(text) >= _NATIVE_MIN_BYTES and _load_native() is not None:
        return parse_text_native(text)
    return parse_text(text)


def latest_sample(samples: list[Sample]) -> Sample | None:
    """Latest-by-timestamp selection (metrics.go:135-150 getLatestLoraMetric).

    Samples without timestamps compare as oldest; with no timestamps at all the
    last sample in exposition order wins.
    """
    if not samples:
        return None
    best = samples[0]
    for s in samples[1:]:
        bt = best.timestamp_ms if best.timestamp_ms is not None else -1
        st = s.timestamp_ms if s.timestamp_ms is not None else -1
        if st >= bt:
            best = s
    return best
