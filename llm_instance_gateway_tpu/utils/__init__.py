"""Shared utilities: Prometheus text parsing, logging helpers."""
