"""Shared build-and-load protocol for the C++ hot-path libraries.

One implementation of the stale-check / cross-process-lock / make dance
used by the scheduler (`gateway/scheduling/native.py`) and the prom
scanner (`utils/prom_parse.py`) — the protocol must not drift between
them.

Ordering matters: the stale check is READ-ONLY and happens first, so a
read-only install with a fresh prebuilt .so never needs the lock file and
loads fine; the flock is taken only when a build is actually required
(two processes racing `make` could hand one of them a torn .so), and the
staleness is re-checked under the lock so the loser of the race skips
straight past the winner's fresh build.
"""

from __future__ import annotations

import logging
import os
import subprocess

logger = logging.getLogger(__name__)


def _stale(lib_path: str, src_path: str) -> bool:
    return (not os.path.exists(lib_path)
            or os.path.getmtime(lib_path) < os.path.getmtime(src_path))


def ensure_native_lib(native_dir: str, target: str, src: str,
                      timeout_s: float = 60.0) -> str | None:
    """Return the path of an up-to-date ``target`` .so in ``native_dir``,
    building it (serialized across processes) if stale.  None = can't be
    made current (caller falls back to its pure-Python path)."""
    lib_path = os.path.join(native_dir, target)
    src_path = os.path.join(native_dir, src)
    try:
        if not _stale(lib_path, src_path):
            return lib_path  # fresh prebuilt: no lock, no writes
        import fcntl

        with open(os.path.join(native_dir, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            if _stale(lib_path, src_path):  # loser of the race skips this
                subprocess.run(
                    ["make", "-C", native_dir, "-s", target, "-B"],
                    check=True, capture_output=True, timeout=timeout_s)
        return lib_path
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build of %s failed: %s", target, e)
        return None
