"""End-to-end request tracing: span recorder + shared histogram exposition.

PR 1 split a request's life across up to three processes (gateway proxy ->
prefill engine -> KV handoff -> decode engine); the only exported signals were
aggregate counters, so "where did this slow request spend its time?" had no
answer (the reference EPP acknowledges the same export gap,
``backend/provider.go:140``; SURVEY.md §5).  This module is the shared,
dependency-free substrate both halves use:

- **Trace propagation**: every request gets a trace id at the proxy, carried
  in the ``x-lig-trace-id`` header through ``/v1/completions``, the two-hop
  ``/v1/prefill`` -> ``/v1/attach`` relay, and the ext-proc handlers, and
  echoed in every response (success headers AND error bodies) so clients and
  the loadgen can correlate.
- **Span recorder** (``Tracer``): named wall-clock spans buffered in a
  bounded per-process ring, exported as JSON by the ``/debug/traces``
  endpoints on the proxy and ``api_http``.  Model servers additionally
  return their spans in a compact ``x-lig-spans`` response header so the
  proxy can merge a request's cross-process timeline into ONE trace.
- **Histogram + exposition helper**: the one Prometheus histogram
  implementation (``_bucket``/``le`` lines, cumulative counts, ``+Inf``)
  shared by the gateway families (``gateway_ttft_seconds``,
  ``gateway_tpot_seconds``, ``gateway_e2e_seconds``,
  ``gateway_pick_latency_seconds``) and the server families
  (``tpu:prefill_seconds``, ``tpu:handoff_seconds``,
  ``tpu:decode_step_seconds``).

Sampling is deterministic on the trace id (one blake2b over 16 hex chars),
so a trace is either recorded by EVERY process on its path or by none —
there are no half-assembled timelines.  The default records everything; the
ring bounds memory either way.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import random

# Header names (lowercase; transports do case-insensitive lookups).
TRACE_HEADER = "x-lig-trace-id"
SPANS_HEADER = "x-lig-spans"

# Second-scale phase latencies (TTFT, prefill, e2e): wider than the
# microsecond-scale pick-latency buckets below.
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# Scheduler-pick-scale buckets (the gateway's historical default).
PICK_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                0.05, 0.1, 0.25, 0.5, 1.0)


# Trace ids need uniqueness, not cryptographic strength: uuid4 costs ~25µs
# on kernels with slow urandom (measured in the bench image — os.urandom is
# a real syscall there, and so is os.getpid), which would alone bust the
# <5% pick-overhead budget.  A urandom-seeded PRNG mints in ~1µs;
# register_at_fork reseeds children so they can't replay the parent's
# sequence without paying a per-call getpid syscall.
_rng = random.Random(int.from_bytes(os.urandom(8), "big"))


def _reseed() -> None:
    global _rng
    _rng = random.Random(int.from_bytes(os.urandom(8), "big") ^ os.getpid())


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed)


def new_trace_id() -> str:
    return f"{_rng.getrandbits(64):016x}"


def header_trace_id(headers) -> str | None:
    """Case-insensitive ``x-lig-trace-id`` lookup over any mapping."""
    get = getattr(headers, "get", None)
    if get is not None:
        v = get(TRACE_HEADER) or get(TRACE_HEADER.title())
        if v:
            return str(v)
    for k in headers:
        if str(k).lower() == TRACE_HEADER:
            return str(headers[k])
    return None


def escape_label(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline).

    One hostile label value must not poison a whole exposition page; every
    render path label goes through here.
    """
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


# ---------------------------------------------------------------------------
# Histogram (+ Prometheus histogram exposition)
# ---------------------------------------------------------------------------


class Histogram:
    """Fixed-bucket latency histogram: observe() is a few list ops, cheap
    enough for the request path.  Exposed either as quantile estimates
    (``quantile``) or true Prometheus histogram series
    (``render_histogram``)."""

    __slots__ = ("buckets", "counts", "total", "n")

    def __init__(self, buckets: tuple[float, ...] = PICK_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += v
        self.n += 1

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return 0.0
        target = q * self.n
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")

    def state(self) -> dict:
        """Copy-out snapshot (cross-thread export: metrics_snapshot)."""
        return {"buckets": list(self.buckets), "counts": list(self.counts),
                "sum": self.total, "count": self.n}


def _fmt(v: float) -> str:
    # %g gives "0.001" / "2.5" / "5e-05": all parse back as the same float.
    return format(v, "g")


def render_keyed_family(name: str, table: dict, labels: tuple,
                        kind: str = "counter", fmt: str = "%s") -> list[str]:
    """One multi-label family over tuple keys: ``# TYPE`` line, an
    unlabeled 0 fallback when the table is empty (counters only — a gauge
    family with no series simply renders nothing past its TYPE line), keys
    sorted and every label value escaped.  The tuple-key sibling of
    ``render_counter`` — the fairness/usage planes key everything by
    ``(model, adapter)``."""
    lines = [f"# TYPE {name} {kind}"]
    if not table:
        if kind == "counter":
            lines.append(f"{name} 0")
        return lines
    for key in sorted(table):
        label_str = ",".join(
            f'{label}="{escape_label(str(part))}"'
            for label, part in zip(labels, key))
        lines.append(f"{name}{{{label_str}}} {fmt % (table[key],)}")
    return lines


def render_counter(name: str, table: dict, label: str) -> list[str]:
    """One labeled counter family: ``# TYPE`` line, a ``None`` key (or an
    empty table) rendered as the unlabeled fallback line, remaining keys
    sorted and escaped.  The ONE implementation behind every counter
    family the gateway, the event journal, and the health scorer expose —
    exposition-format fixes land here once."""
    lines = [f"# TYPE {name} counter"]
    if not table:
        lines.append(f"{name} 0")
    # None sorts first: stable output, fallback line leads.
    for key in sorted(table, key=lambda k: (k is not None, k or "")):
        if key is None:
            lines.append(f"{name} {table[key]}")
        else:
            lines.append(
                f'{name}{{{label}="{escape_label(key)}"}} {table[key]}')
    return lines


def render_histogram(name: str, hist, labels: dict[str, str] | None = None,
                     type_line: bool = True) -> list[str]:
    """Prometheus histogram exposition lines for one series.

    ``hist`` is a ``Histogram`` or its ``state()`` dict.  ``labels`` are
    escaped here.  ``type_line=False`` lets a caller emitting several label
    sets of the same family write the ``# TYPE`` comment once.
    """
    if isinstance(hist, Histogram):
        hist = hist.state()
    base = "".join(
        f'{k}="{escape_label(v)}",' for k, v in (labels or {}).items())
    plain = "{" + base.rstrip(",") + "}" if base else ""
    lines = [f"# TYPE {name} histogram"] if type_line else []
    cum = 0
    for b, c in zip(hist["buckets"], hist["counts"]):
        cum += c
        lines.append(f'{name}_bucket{{{base}le="{_fmt(b)}"}} {cum}')
    cum += hist["counts"][len(hist["buckets"])]
    lines.append(f'{name}_bucket{{{base}le="+Inf"}} {cum}')
    lines.append(f"{name}_sum{plain} {hist['sum']}")
    lines.append(f"{name}_count{plain} {hist['count']}")
    return lines


# ---------------------------------------------------------------------------
# Span recorder
# ---------------------------------------------------------------------------


def wire_spans(spans) -> str:
    """Compact JSON for the ``x-lig-spans`` response header:
    ``[[name, start, end], ...]`` (epoch seconds, µs precision)."""
    return json.dumps(
        [[n, round(float(s), 6), round(float(e), 6)] for n, s, e in spans],
        separators=(",", ":"))


def parse_wire(value: str) -> list[tuple[str, float, float]]:
    """Tolerant inverse of ``wire_spans`` — foreign headers must never
    break a response relay."""
    try:
        rows = json.loads(value)
        return [(str(r[0]), float(r[1]), float(r[2]))
                for r in rows if len(r) >= 3]
    except (ValueError, TypeError, KeyError, IndexError):
        return []


# Annotation marker inside the flat ring: record[1] is a span name for
# spans, or this sentinel for (model, path, status) trace metadata.
_META = None


class Tracer:
    """Bounded per-process trace recorder.

    The HOT PATH is a single ``deque.append`` of a tuple onto a flat,
    maxlen-bounded ring (GIL-atomic — no lock, no per-trace dict, no
    eviction bookkeeping): record() sits on the proxy's per-request path
    and is budgeted at <5% of a scheduler pick (bench.py enforces it).
    Grouping spans into per-trace JSON happens at EXPORT (/debug/traces),
    which is a debug endpoint and can afford the O(ring) walk.

    ``capacity`` counts traces; the span ring holds ``capacity * 16``
    records, so old traces age out naturally.  Sampling is decided per
    TRACE (deterministic hash of the id), so multi-process traces are
    complete or absent, never partial.

    Every record carries a monotonic sequence number (the events.py
    journal convention), so ``/debug/traces?since=<seq>`` serves
    incremental reads — the fleet collector and ``--watch`` tooling poll
    deltas instead of re-shipping the whole ring.  The bump is a plain
    attribute increment, not a lock: recorders run under the GIL and a
    rare duplicate seq under thread races costs a poller one re-shipped
    span, never a lost one.
    """

    def __init__(self, capacity: int | None = None,
                 sample: float | None = None, enabled: bool | None = None):
        if capacity is None:
            capacity = int(os.environ.get("LIG_TRACE_CAPACITY", "256"))
        if sample is None:
            sample = float(os.environ.get("LIG_TRACE_SAMPLE", "1.0"))
        if enabled is None:
            enabled = os.environ.get("LIG_TRACE", "1") not in ("0", "false")
        self.capacity = max(1, capacity)
        self.sample = sample
        self.enabled = enabled
        self._seq = 0
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity * 16)

    @property
    def seq(self) -> int:
        """Head sequence number (the newest record's seq)."""
        return self._seq

    def sampled(self, trace_id: str) -> bool:
        if not self.enabled:
            return False
        if self.sample >= 1.0:
            return True  # default: no hash on the hot path
        if self.sample <= 0.0:
            return False
        h = hashlib.blake2b(trace_id.encode(), digest_size=8).digest()
        return int.from_bytes(h, "big") / 2.0 ** 64 < self.sample

    def record(self, trace_id: str, name: str, start: float, end: float,
               **attrs) -> None:
        if not trace_id or not self.sampled(trace_id):
            return
        self._seq = seq = self._seq + 1
        self._ring.append(
            (seq, trace_id, name, float(start), float(end), attrs or None))

    def record_wire(self, trace_id: str, value: str | None) -> None:
        """Merge spans from a downstream ``x-lig-spans`` header."""
        if not value or not trace_id or not self.sampled(trace_id):
            return
        for n, s, e in parse_wire(value):
            self._seq = seq = self._seq + 1
            self._ring.append((seq, trace_id, n, s, e, None))

    def annotate(self, trace_id: str, model: str | None = None,
                 path: str | None = None, status: str | None = None) -> None:
        if not trace_id or not self.sampled(trace_id):
            return
        self._seq = seq = self._seq + 1
        self._ring.append((seq, trace_id, _META, model, path, status))

    # -- export (the /debug/traces JSON shape) ------------------------------

    def _collect(self, since: int = 0) -> "collections.OrderedDict[str, dict]":
        """Group the flat ring into trace dicts, ordered by last activity.
        ``since`` skips records with seq <= since (the incremental-cursor
        read); each trace carries ``seq`` = its newest included record."""
        traces: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
        for rec in list(self._ring):  # snapshot: appends may race the walk
            seq, tid = rec[0], rec[1]
            if seq <= since:
                continue
            t = traces.get(tid)
            if t is None:
                t = traces[tid] = {"trace_id": tid, "model": "", "path": "",
                                   "status": "", "seq": seq, "_min_seq": seq,
                                   "spans": []}
            else:
                traces.move_to_end(tid)
            t["seq"] = max(t["seq"], seq)
            t["_min_seq"] = min(t["_min_seq"], seq)
            if rec[2] is _META:
                _, _, _, model, path, status = rec
                if model is not None:
                    t["model"] = model
                if path is not None:
                    t["path"] = path
                if status is not None:
                    t["status"] = str(status)
            else:
                _, _, name, s, e, attrs = rec
                t["spans"].append(
                    {"name": name, "start": round(s, 6), "end": round(e, 6),
                     **({"attrs": attrs} if attrs else {})})
        return traces

    @staticmethod
    def _export(t: dict) -> dict:
        spans = sorted(t["spans"], key=lambda x: (x["start"], x["end"]))
        t_created = spans[0]["start"] if spans else 0.0
        t = {k: v for k, v in t.items() if k != "_min_seq"}
        return {**t, "t_created": t_created, "spans": spans}

    def get(self, trace_id: str) -> dict | None:
        t = self._collect().get(trace_id)
        return self._export(t) if t is not None else None

    def recent(self, limit: int = 64) -> list[dict]:
        """Most-recently-active-first trace dicts."""
        traces = self._collect()
        out = [self._export(t) for t in
               list(traces.values())[-max(0, limit):]]
        out.reverse()
        return out

    def since(self, since: int, limit: int = 1024) -> tuple[list[dict], int]:
        """(partial trace dicts holding only records with seq > ``since``,
        next_since cursor).  The page is the ``limit`` traces whose OLDEST
        new record is earliest, and on truncation the cursor retreats to
        just before the first excluded trace's oldest record — resuming
        from it can re-ship a few records of an included trace (the
        stitcher dedups spans) but can never skip one, the events.py
        lossless-paging contract lifted to trace granularity."""
        traces = sorted(self._collect(since).values(),
                        key=lambda t: t["_min_seq"])
        limit = max(0, limit)
        page, excluded = traces[:limit], traces[limit:]
        if excluded:
            next_since = min(t["_min_seq"] for t in excluded) - 1
        else:
            next_since = max((t["seq"] for t in page), default=self._seq)
        return [self._export(t) for t in page], next_since


def debug_traces_payload(tracer: Tracer, query) -> dict:
    """The shared ``/debug/traces`` response body: ``?trace_id=`` exact
    filter, ``?limit=`` count cap (1..1024, default 64), and the
    incremental cursor ``?since=<seq>`` (the /debug/events contract:
    poll with ``since=next_since`` until ``next_since == seq``) returning
    only records newer than the cursor, grouped per trace.  One contract
    for the proxy and api_http endpoints."""
    trace_id = query.get("trace_id")
    if trace_id:
        t = tracer.get(trace_id)
        return {"traces": [t] if t else [], "seq": tracer.seq}
    try:
        limit = max(1, min(int(query.get("limit", "64")), 1024))
    except ValueError:
        limit = 64
    raw_since = query.get("since")
    if raw_since is not None:
        try:
            since = max(0, int(raw_since))
        except ValueError:
            since = 0
        rows, next_since = tracer.since(since, limit)
        return {"traces": rows, "seq": tracer.seq,
                "next_since": next_since}
    return {"traces": tracer.recent(limit), "seq": tracer.seq}
