"""Registry of every metric family the framework exposes.

The exposition surface has grown PR over PR (gateway request counters,
phase histograms, SLO gauges, health scores, event counters; server-side
``tpu:*`` contract families) and nothing kept it discoverable: an operator
had to curl ``/metrics`` and guess semantics.  This module is the single
declarative list — name, type, labels, help, surface — that:

- generates ``docs/METRICS.md`` (``make metrics-docs``;
  ``tests/test_metrics_docs.py`` asserts the file is current), and
- is cross-checked against the REAL rendered expositions by the contract
  suite, so a family added to a render path without a registry entry (or
  vice versa) fails tier-1 instead of silently drifting.

Keep entries in render order per surface; the doc generator preserves it.
"""

from __future__ import annotations

from dataclasses import dataclass

GATEWAY_SURFACE = "gateway /metrics (proxy)"
SERVER_SURFACE = "model server /metrics (api_http)"


@dataclass(frozen=True)
class Family:
    name: str
    kind: str                 # counter | gauge | histogram
    labels: tuple             # label names ("" entries not allowed)
    help: str
    surface: str


GATEWAY_FAMILIES = (
    Family("gateway_requests_total", "counter", ("model",),
           "Requests admitted past body parsing, by model.",
           GATEWAY_SURFACE),
    Family("gateway_scheduled_total", "counter", ("pod",),
           "Scheduler picks, by target pod.", GATEWAY_SURFACE),
    Family("gateway_shed_total", "counter", ("model",),
           "Load-shed drops (429); unlabeled line = pre-admission fallback "
           "(model unknown).", GATEWAY_SURFACE),
    Family("gateway_errors_total", "counter", ("model",),
           "Request failures (admission errors, upstream failures, broken "
           "streams); unlabeled line = pre-admission fallback.",
           GATEWAY_SURFACE),
    Family("gateway_lora_affinity_hits_total", "counter", (),
           "Picks that landed on a pod already serving the requested "
           "adapter.", GATEWAY_SURFACE),
    Family("gateway_retries_total", "counter", ("reason",),
           "Budgeted data-path retries performed, by failure reason "
           "(connect | ttft_timeout | upstream_503 | read | read_timeout; "
           "gateway/resilience.py).", GATEWAY_SURFACE),
    Family("gateway_hedges_total", "counter", ("outcome",),
           "TTFT hedges, by outcome (fired | won | lost | no_candidate | "
           "failed); enabled via --hedge-ttft-s.", GATEWAY_SURFACE),
    Family("gateway_client_disconnects_total", "counter", ("model",),
           "Client-side disconnects of live SSE relays; the partial "
           "request is still observed into the e2e histograms.",
           GATEWAY_SURFACE),
    Family("gateway_upstream_connections_total", "counter", ("pod", "state"),
           "Upstream keepalive-pool connections by pod and state "
           "(created = fresh TCP handshake, reused = served off a pooled "
           "connection).", GATEWAY_SURFACE),
    Family("gateway_upstream_connection_reuse_ratio", "gauge", (),
           "Pool-wide connection reuse: reused / (created + reused); near "
           "0 means every request pays a handshake.", GATEWAY_SURFACE),
    Family("gateway_pick_latency_seconds", "histogram", (),
           "Scheduler pick latency.", GATEWAY_SURFACE),
    Family("gateway_prompt_tokens_total", "counter", ("model",),
           "Prompt tokens accounted from upstream usage, by model.",
           GATEWAY_SURFACE),
    Family("gateway_completion_tokens_total", "counter", ("model",),
           "Completion tokens accounted from upstream usage, by model.",
           GATEWAY_SURFACE),
    Family("gateway_ttft_seconds", "histogram", ("model", "path"),
           "Client-observed time to first token (path = collocated | "
           "disaggregated).", GATEWAY_SURFACE),
    Family("gateway_tpot_seconds", "histogram", ("model", "path"),
           "Client-observed time per output token after the first.",
           GATEWAY_SURFACE),
    Family("gateway_e2e_seconds", "histogram", ("model", "path"),
           "Client-observed end-to-end request latency.", GATEWAY_SURFACE),
    Family("gateway_pool_prefix_reused_tokens_total", "counter", ("pod",),
           "Per-replica tpu:prefix_reused_tokens re-exported at the "
           "gateway (KV-affinity observable).", GATEWAY_SURFACE),
    Family("gateway_slo_compliance_ratio", "gauge", ("model", "objective"),
           "Cumulative fraction of requests meeting the objective "
           "(gateway/slo.py; objectives: ttft, tpot, e2e, error_rate).",
           GATEWAY_SURFACE),
    Family("gateway_slo_burn_rate", "gauge",
           ("model", "objective", "window"),
           "Windowed error-budget burn rate (1.0 = budget consumed exactly "
           "at the sustainable rate; fast-burn pages at 14.4 by default).",
           GATEWAY_SURFACE),
    Family("gateway_pod_health_score", "gauge", ("pod",),
           "Fused 0-1 replica health score (gateway/health.py; freshness, "
           "errors, queue, KV, latency components).", GATEWAY_SURFACE),
    Family("gateway_pod_health_state", "gauge", ("pod", "state"),
           "Hysteresis health state (healthy | degraded | unhealthy); the "
           "labeled series is 1.", GATEWAY_SURFACE),
    Family("gateway_upstream_errors_total", "counter", ("pod",),
           "Upstream connection/stream/5xx failures, by pod.",
           GATEWAY_SURFACE),
    Family("gateway_upstream_timeouts_total", "counter", ("pod",),
           "Upstream timeouts (subset of errors), by pod.", GATEWAY_SURFACE),
    Family("gateway_handoff_failures_total", "counter", ("pod",),
           "Disaggregation hop failures attributed to the refusing/failing "
           "pod.", GATEWAY_SURFACE),
    Family("tpu:health_would_avoid_total", "counter", ("pod",),
           "Picks that landed on a non-healthy replica (always counted; "
           "with health_policy=log_only routing is otherwise unchanged).",
           GATEWAY_SURFACE),
    Family("gateway_circuit_state", "gauge", ("pod",),
           "Per-pod circuit-breaker state (0 closed / 1 open / 2 "
           "half-open; gateway/resilience.py).", GATEWAY_SURFACE),
    Family("gateway_usage_share", "gauge", ("model", "adapter", "resource"),
           "Pool-wide consumption share per {model, adapter} (EMA of "
           "scrape-tick deltas; resource: step_seconds | tokens | "
           "kv_block_seconds; gateway/usage.py).", GATEWAY_SURFACE),
    Family("gateway_noisy_neighbor_score", "gauge", ("model", "adapter"),
           "Step-seconds consumption share over admitted-traffic share "
           "(1.0 = proportional; flags noisy past the configured ratio "
           "with hysteresis).", GATEWAY_SURFACE),
    Family("gateway_usage_would_deprioritize_total", "counter",
           ("model", "adapter"),
           "Picks that served a currently-flagged noisy key, attributed "
           "to the flagged {model, adapter} (with fairness mode log_only "
           "routing is otherwise unchanged).", GATEWAY_SURFACE),
    Family("gateway_quota_throttles_total", "counter", ("model", "adapter"),
           "Admissions that found the tenant's fairness quota bucket "
           "empty (gateway/fairness.py, mode=enforce).", GATEWAY_SURFACE),
    Family("gateway_fairness_demotions_total", "counter",
           ("model", "adapter"),
           "Over-quota requests demoted one criticality tier (Critical -> "
           "Default -> Sheddable; graceful degradation instead of a hard "
           "shed).", GATEWAY_SURFACE),
    Family("gateway_tenant_quota_remaining", "gauge", ("model", "adapter"),
           "Remaining fairness-quota bucket tokens per throttled tenant "
           "(refill --fairness-quota-rps/s, cost scaled by LoRA rank).",
           GATEWAY_SURFACE),
    Family("gateway_adapter_residency", "gauge",
           ("model", "adapter", "pod", "tier"),
           "Adapter residency as the placement planner sees it: one "
           "series per (pod, adapter) with its tier (slot | host); disk-"
           "tier adapters have no series (gateway/placement.py).",
           GATEWAY_SURFACE),
    Family("gateway_placement_decisions_total", "counter", ("action",),
           "Placement-planner decisions emitted, by action (prefetch | "
           "migrate | demote | evict); executed by lora_sidecar "
           "--planner-url over the adapter wire.", GATEWAY_SURFACE),
    Family("gateway_placement_would_steer_total", "counter", (),
           "Picks that landed on a pod without the adapter RAM-resident "
           "while a resident replica existed (placement_mode=log_only "
           "observable; routing unchanged).", GATEWAY_SURFACE),
    Family("gateway_placement_wrong_tier_picks_total", "counter", (),
           "Same condition under placement_mode=prefer_resident — zero "
           "modulo counted escapes (the cold_start_storm chaos bar).",
           GATEWAY_SURFACE),
    Family("gateway_placement_escapes_total", "counter", (),
           "prefer_resident last-resort escapes: the adapter was resident "
           "in the pool but on no candidate, so the full set served.",
           GATEWAY_SURFACE),
    Family("gateway_statebus_peers", "gauge", (),
           "Gateway replicas with a FRESH statebus snapshot (self "
           "excluded); 0 with peers configured means local-only "
           "enforcement fallback (gateway/statebus.py).", GATEWAY_SURFACE),
    Family("gateway_statebus_snapshot_age_seconds", "gauge", ("replica",),
           "Age of each known replica's newest statebus snapshot (local "
           "receive clock; own replica included at ~0).", GATEWAY_SURFACE),
    Family("gateway_statebus_merge_seconds", "histogram", (),
           "Statebus merge latency per received doc batch (gossip fold, "
           "not the network round trip).", GATEWAY_SURFACE),
    Family("gateway_statebus_stale_fallbacks_total", "counter", (),
           "Transitions into local-only enforcement because every peer "
           "snapshot aged past the staleness bound (journaled as "
           "statebus_stale; recovery journals statebus_rejoin).",
           GATEWAY_SURFACE),
    Family("gateway_statebus_exchanges_total", "counter", ("outcome",),
           "Peer push-pull exchange attempts by outcome (ok | error).",
           GATEWAY_SURFACE),
    Family("gateway_fleet_sources", "gauge", ("kind",),
           "Sources the fleet collector reached on its last /debug/fleet "
           "pull, by kind (gateway = statebus peers + self, pod = pool "
           "replicas; gateway/fleetobs.py).", GATEWAY_SURFACE),
    Family("gateway_fleet_stitched_traces", "gauge", (),
           "Cross-replica traces stitched on the last fleet pull.",
           GATEWAY_SURFACE),
    Family("gateway_fleet_collect_errors_total", "counter", ("source",),
           "Fleet-collector pull failures by source (also journaled as "
           "fleet_peer_error); the source's cached view keeps serving.",
           GATEWAY_SURFACE),
    Family("gateway_fleet_collect_seconds", "histogram", (),
           "Wall time of one full fleet pull (all sources concurrent).",
           GATEWAY_SURFACE),
    Family("gateway_kv_reuse_efficiency", "gauge", ("pod",),
           "Per-pod prefix-cache reuse efficiency: reused prompt tokens / "
           "(reused + prefilled) cumulative (gateway/kvobs.py over the "
           "replicas' tpu:kv_* ledger families).", GATEWAY_SURFACE),
    Family("gateway_kv_parked_share", "gauge", ("pod",),
           "Fraction of the pod's KV block budget held by parked "
           "(prefilled-but-unslotted) handoff KV.", GATEWAY_SURFACE),
    Family("gateway_kv_saved_tokens_per_s", "gauge", ("pod",),
           "EMA rate of prefill tokens the pod's prefix cache absorbed "
           "(scrape-tick deltas of tpu:prefix_reused_tokens).",
           GATEWAY_SURFACE),
    Family("gateway_kv_duplicated_prefixes", "gauge", (),
           "Prefixes resident on >= min_replicas pods at the last rollup "
           "— the fleet duplication index's row count.", GATEWAY_SURFACE),
    Family("gateway_kv_duplicated_blocks", "gauge", (),
           "KV blocks caching a prefix some other replica also holds "
           "(sum(holders) - max(holders) per duplicated prefix): HBM "
           "spent caching the same tokens twice.", GATEWAY_SURFACE),
    Family("gateway_kv_dedup_tokens_saved_per_s", "gauge", (),
           "Reuse traffic (tokens/s) currently served by duplicate copies "
           "— what a KV-affinity router or shared KV store could serve "
           "from one copy.", GATEWAY_SURFACE),
    Family("gateway_kv_prefix_replicas", "gauge", ("prefix",),
           "Replica count holding each duplicated prefix (top rows by "
           "duplicated blocks; prefix = content-addressed 16-hex id).",
           GATEWAY_SURFACE),
    Family("gateway_capacity_saturation", "gauge", ("resource",),
           "Pool saturation index per resource, 0..1 (gateway/capacity.py; "
           "max over pods — saturation is a weakest-link property): kv "
           "(1 - free/capacity), decode_slots (batch occupancy window "
           "mean), queue (waiting over waiting+running), prefill_compute "
           "(prefill wall seconds per wall second).", GATEWAY_SURFACE),
    Family("gateway_capacity_pod_saturation", "gauge", ("pod", "resource"),
           "Per-pod per-resource saturation index, 0..1 (the rows behind "
           "gateway_capacity_saturation).", GATEWAY_SURFACE),
    Family("gateway_capacity_offered_rps", "gauge", (),
           "EMA'd offered arrival rate (prefill completions/s summed over "
           "the pool's pods, scrape-tick deltas).", GATEWAY_SURFACE),
    Family("gateway_capacity_knee_rps", "gauge", (),
           "The calibrated twin's knee: offered load where simulated TTFT "
           "p95 crosses the SLO (bisected DES probes at the observed "
           "prompt/output mix, times the pod count).", GATEWAY_SURFACE),
    Family("gateway_capacity_headroom_ratio", "gauge", (),
           "Headroom-at-SLO: (knee - offered) / knee, clamped to 0 "
           "(0 = at or past the knee; 1 = idle).", GATEWAY_SURFACE),
    Family("gateway_capacity_time_to_breach_seconds", "gauge", (),
           "Forecast seconds until the offered-rate trend (least-squares "
           "slope over recent windows) crosses the knee; -1 = no breach "
           "on the current trend; 0 = already past the knee.  Entering "
           "the breach horizon journals a capacity_forecast event.",
           GATEWAY_SURFACE),
    Family("gateway_twin_drift", "gauge", ("observable",),
           "EMA'd relative divergence |predicted - observed| / observed "
           "between the calibrated twin and the live pool, per observable "
           "(prefill_s, decode_step_s, occupancy via Little's law).  "
           "Breaching --twin-drift-threshold journals twin_drift and "
           "untrusts forecasts.", GATEWAY_SURFACE),
    Family("gateway_twin_trusted", "gauge", (),
           "1 while the twin's forecasts are trusted (a model is loaded "
           "or fitted AND drift is below threshold); 0 = capacity "
           "surfaces still export but must not be believed.",
           GATEWAY_SURFACE),
    Family("gateway_pick_sample_total", "counter", (),
           "Picks recorded by the routing decision ledger "
           "(gateway/pickledger.py; deterministic every-Nth sampling — "
           "multiply by the configured sample_every to estimate pick "
           "volume).", GATEWAY_SURFACE),
    Family("gateway_pick_narrowing", "gauge", ("stage",),
           "Mean surviving candidates after each pick stage across "
           "sampled picks (pool -> role_partition -> filter_tree -> "
           "health/circuit -> fairness -> placement -> prefix_affinity "
           "-> rng): the funnel /debug/picks itemizes per record.",
           GATEWAY_SURFACE),
    Family("gateway_pick_steered_total", "counter", ("seam",),
           "Sampled picks whose final survivor set the counterfactual "
           "replay shows this advisor seam changed (disabling the seam "
           "yields a different set) — the 'why pod X' attribution.",
           GATEWAY_SURFACE),
    Family("gateway_events_total", "counter", ("kind",),
           "Flight-recorder events by kind (events.py; the journal itself "
           "is served by /debug/events).", GATEWAY_SURFACE),
)

SERVER_FAMILIES = (
    Family("tpu:prefill_queue_size", "gauge", (),
           "Requests awaiting prefill.", SERVER_SURFACE),
    Family("tpu:decode_queue_size", "gauge", (),
           "Prefilled requests awaiting a decode slot.", SERVER_SURFACE),
    Family("tpu:num_requests_running", "gauge", (),
           "In-flight requests.", SERVER_SURFACE),
    Family("tpu:num_requests_waiting", "gauge", (),
           "Total queued (prefill + decode).", SERVER_SURFACE),
    Family("tpu:kv_cache_usage_perc", "gauge", (),
           "Paged-KV utilization 0..1 (parked KV included).",
           SERVER_SURFACE),
    Family("tpu:kv_tokens_capacity", "gauge", (),
           "Total KV token capacity.", SERVER_SURFACE),
    Family("tpu:kv_tokens_free", "gauge", (),
           "Free KV token headroom.", SERVER_SURFACE),
    Family("tpu:kv_parked_tokens", "gauge", (),
           "Prefilled-but-unslotted KV tokens held outside the cache.",
           SERVER_SURFACE),
    Family("tpu:decode_tokens_per_sec", "gauge", (),
           "Recent decode throughput (EMA).", SERVER_SURFACE),
    Family("tpu:lora_requests_info", "gauge",
           ("running_lora_adapters", "waiting_lora_adapters", "max_lora",
            "adapter_ranks", "resident_tiers"),
           "Adapter-activity info gauge (vLLM semantics: running = "
           "actively decoding, waiting = parked in decode_wait / queued); "
           "adapter_ranks is a name:rank CSV (rank-aware fairness "
           "weighting); resident_tiers is a name:tier CSV over the "
           "slot/host residency ladder; value is a unix timestamp "
           "(latest series wins).", SERVER_SURFACE),
    Family("tpu:adapter_residency_info", "gauge", ("tier", "adapters"),
           "Residency ladder info gauge: one line per tier (slot = "
           "device buffers, host = host-RAM cache) with an adapters CSV; "
           "every adapter appears in exactly one tier per replica "
           "(server/lora_manager.py); value is a unix timestamp.",
           SERVER_SURFACE),
    Family("tpu:adapter_tier_transitions_total", "counter", ("from", "to"),
           "Residency-ladder transitions (load, promote, demote, "
           "prefetch, evict, host-LRU overflow) by from/to tier.",
           SERVER_SURFACE),
    Family("tpu:adapter_load_seconds_total", "counter", ("tier",),
           "Cumulative adapter-load wall seconds by source tier (host = "
           "device put of a cached copy, disk = full Orbax restore); "
           "mean = _total / tpu:adapter_loads_total.", SERVER_SURFACE),
    Family("tpu:adapter_loads_total", "counter", ("tier",),
           "Adapter loads performed, by source tier.", SERVER_SURFACE),
    Family("tpu:pool_role", "gauge", ("role",),
           "Disaggregation role info gauge (collocated | prefill | "
           "decode).", SERVER_SURFACE),
    Family("tpu:prefix_reused_tokens", "counter", (),
           "Cumulative prompt tokens served from the prefix cache.",
           SERVER_SURFACE),
    Family("tpu:spec_cycles", "counter", (),
           "Speculative-decoding verify cycles.", SERVER_SURFACE),
    Family("tpu:spec_tokens_per_cycle", "gauge", (),
           "Accepted tokens per speculative cycle (draft-quality signal).",
           SERVER_SURFACE),
    Family("tpu:stream_lanes", "gauge", (),
           "Configured concurrent chunk-stream lanes (long prompts "
           "streaming into reserved cache lanes at once; "
           "EngineConfig.stream_lanes).", SERVER_SURFACE),
    Family("tpu:stream_lanes_active", "gauge", (),
           "Chunk-stream lanes currently mid-prompt; at the configured "
           "lane count a further long prompt head-of-line waits.",
           SERVER_SURFACE),
    Family("tpu:dispatch_steps", "histogram", (),
           "Fused decode steps per dispatch — the adaptive multi-step "
           "planner's decision record (buckets land on its power-of-two "
           "choices; EngineConfig.adaptive_steps).", SERVER_SURFACE),
    Family("tpu:prefill_seconds", "histogram", ("model", "role"),
           "Prefill compute latency.", SERVER_SURFACE),
    Family("tpu:handoff_seconds", "histogram", ("model", "role"),
           "KV-handoff serialize / deserialize+attach latency.",
           SERVER_SURFACE),
    Family("tpu:decode_step_seconds", "histogram", ("model", "role"),
           "Per-step decode cadence.", SERVER_SURFACE),
    Family("tpu:adapter_step_seconds_total", "counter",
           ("model", "adapter", "phase"),
           "TPU step wall-seconds charged to each adapter (decode "
           "dispatches split evenly across active slots; prefills charged "
           "whole to their owner; adapter=base = no-LoRA rows; "
           "server/usage.py).", SERVER_SURFACE),
    Family("tpu:adapter_tokens_total", "counter",
           ("model", "adapter", "phase"),
           "Tokens attributed per adapter (prompt tokens at prefill, "
           "emitted tokens at decode).", SERVER_SURFACE),
    Family("tpu:adapter_kv_block_seconds_total", "counter",
           ("model", "adapter"),
           "Time-integral of KV blocks held per adapter (parked "
           "decode_wait KV included; token-seconds when the cache is not "
           "paged).", SERVER_SURFACE),
    Family("tpu:step_seconds_total", "counter", ("phase",),
           "Engine wall step-seconds per phase — the conservation "
           "denominator: per-adapter step-seconds sum to this within "
           "epsilon (tests/test_usage.py).", SERVER_SURFACE),
    Family("tpu:idle_slot_seconds_total", "counter", (),
           "Slot-seconds decode dispatches ran with empty rows (pool "
           "waste).", SERVER_SURFACE),
    Family("tpu:prefill_padding_tokens_total", "counter", (),
           "Prompt tokens prefilled as bucket/ring padding and thrown "
           "away (pool waste).", SERVER_SURFACE),
    Family("tpu:decode_batch_occupancy", "histogram", (),
           "Active-slots / total-slots fraction per decode dispatch.",
           SERVER_SURFACE),
    Family("tpu:dispatch_wall_seconds", "histogram", ("phase",),
           "Per-dispatch device program + host-sync wall by phase "
           "(prefill | decode | spec); the step-timeline profiler's "
           "dispatch bucket (server/profiler.py, /debug/profile).",
           SERVER_SURFACE),
    Family("tpu:dispatch_gap_seconds", "histogram", ("kind",),
           "Engine-thread gap between consecutive dispatches (kind=host "
           "= step-loop overhead the ROADMAP item-2 levers amortize; "
           "kind=idle = the gap contained a no-work wait).",
           SERVER_SURFACE),
    Family("tpu:kv_blocks_total", "gauge", (),
           "KV block budget the ledger accounts: pool blocks + parked "
           "block-equivalents (server/kv_ledger.py; paged mode with "
           "EngineConfig.kv_ledger).", SERVER_SURFACE),
    Family("tpu:kv_block_tokens", "gauge", (),
           "Tokens per KV block (the ledger's block size).",
           SERVER_SURFACE),
    Family("tpu:kv_blocks", "gauge", ("state",),
           "Block budget by state (free | active | prefix_resident | "
           "parked); states tile the budget, so the sum equals "
           "tpu:kv_blocks_total — the conservation invariant "
           "tests/test_kv_ledger.py pins.", SERVER_SURFACE),
    Family("tpu:kv_block_events_total", "counter", ("kind",),
           "Block lifecycle events (alloc | evict | reuse_hit | "
           "reuse_unwind | register | release | cache_park | park | "
           "unpark | sweep).", SERVER_SURFACE),
    Family("tpu:kv_prefix_hits_total", "counter", ("prefix",),
           "Prefix-cache hits per content-addressed prefix id (16-hex of "
           "the deepest chained block hash; identical across replicas for "
           "the same prompt prefix — the fleet duplication join key).",
           SERVER_SURFACE),
    Family("tpu:kv_prefix_tokens_saved_total", "counter", ("prefix",),
           "Prompt tokens served from cache per prefix (reuse unwinds "
           "subtracted, so the sum tracks tpu:prefix_reused_tokens).",
           SERVER_SURFACE),
    Family("tpu:kv_prefix_resident_blocks", "gauge", ("prefix",),
           "Cached chain depth (blocks) currently resident per prefix; "
           "decays as LRU eviction consumes the chain.", SERVER_SURFACE),
    Family("tpu:kv_free_run_blocks", "histogram", (),
           "Lengths of maximal runs of consecutive free physical block "
           "ids at the last sync — the fragmentation view (a pool can be "
           "40% free and still lack contiguous headroom).", SERVER_SURFACE),
    Family("tpu:kv_parked_share", "histogram", (),
           "Parked share of the block budget sampled at each ledger sync.",
           SERVER_SURFACE),
    Family("tpu:events_total", "counter", ("kind",),
           "Replica-side flight-recorder events by kind (served by the "
           "replica's /debug/events).", SERVER_SURFACE),
)


def all_families() -> tuple[Family, ...]:
    return GATEWAY_FAMILIES + SERVER_FAMILIES


def registered_names() -> set[str]:
    return {f.name for f in all_families()}


def render_markdown() -> str:
    """The full ``docs/METRICS.md`` content (generated; do not hand-edit)."""
    out = [
        "# Metrics reference",
        "",
        "<!-- GENERATED by `make metrics-docs` from "
        "llm_instance_gateway_tpu/metrics_registry.py — do not edit. -->",
        "",
        "Every Prometheus family the framework exposes, by surface.  "
        "Histogram families expose the usual `_bucket`/`_sum`/`_count` "
        "series.  Counter families keyed by an attribution label render an "
        "unlabeled fallback line when no labeled sample exists yet.",
        "",
    ]
    for surface in (GATEWAY_SURFACE, SERVER_SURFACE):
        out += [f"## {surface}", "",
                "| family | type | labels | help |",
                "|---|---|---|---|"]
        for f in all_families():
            if f.surface != surface:
                continue
            labels = ", ".join(f.labels) if f.labels else "—"
            help_cell = f.help.replace("|", "\\|")  # literal pipes in cells
            out.append(
                f"| `{f.name}` | {f.kind} | {labels} | {help_cell} |")
        out.append("")
    return "\n".join(out)
