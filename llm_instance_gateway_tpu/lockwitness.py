"""Runtime lock-order witness: the dynamic half of the concurrency plane.

The static half (``lint/concurrency.py``) proves the declared lock-
acquisition graph acyclic by reading the AST; this module watches the REAL
acquisitions at runtime and records, per thread, every "acquired B while
holding A" edge.  Two uses:

- **Acyclicity at runtime**: ``WITNESS.assert_acyclic()`` fails a test the
  moment two code paths acquire the same pair of locks in opposite orders
  — the deterministic interleave harness (``tests/test_concurrency.py``)
  drives the cross-thread seams and asserts this at the end, so a lock
  inversion that only manifests under a thread schedule nobody ran still
  fails CI.
- **Static-graph completeness**: the witness's observed edge set must be a
  SUBSET of the edges the AST analysis derived (``cross_check``).  The
  static analyzer skips calls it cannot resolve; an observed edge it
  missed means the analyzer (or the registry's attribute bindings) lost
  track of a seam — the mismatch fails loudly instead of silently
  narrowing the lint's coverage.

Arming: ``witness_lock(name)`` returns a recording wrapper only when
``LIG_LOCK_WITNESS`` is set truthy AT CONSTRUCTION TIME (tests arm it in
``tests/conftest.py``); otherwise it returns a plain ``threading.Lock`` /
``RLock`` — zero overhead in production.  The armed overhead is bounded by
the ``pick_witness_ratio`` microbench (< 1.05 vs plain locks, committed to
``BASELINE_BENCH.json``): per acquisition it costs one thread-local list
append plus, only for a never-seen (held, acquired) pair, one dict insert.

Naming convention: ``"ClassName._lockattr"`` — the SAME identity the
static analyzer assigns (``concurrency_registry`` declares the classes and
lock attributes), so observed and static edges compare directly.
"""

from __future__ import annotations

import os
import threading

ENV = "LIG_LOCK_WITNESS"


def armed() -> bool:
    return os.environ.get(ENV, "") not in ("", "0", "false", "no")


class LockWitness:
    """Process-global acquisition-order recorder (one edge set, per-thread
    hold stacks)."""

    def __init__(self):
        self._mu = threading.Lock()     # guards the edge dict only; never
        #                                 held while acquiring a user lock
        self._edges: dict[tuple[str, str], int] = {}
        self._tls = threading.local()

    # -- recording (called by _WitnessLock with the user lock HELD) ---------
    def thread_stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def note_acquire(self, name: str) -> None:
        stack = self.thread_stack()
        if stack:
            self.note_edge(stack[-1], name)
        stack.append(name)

    def note_edge(self, held: str, name: str) -> None:
        edge = (held, name)
        if edge not in self._edges:   # racy fast-path miss is fine:
            with self._mu:            # the locked insert is idempotent
                self._edges[edge] = self._edges.get(edge, 0) + 1

    def note_release(self, name: str) -> None:
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return
        if stack[-1] == name:         # with-statements release LIFO
            stack.pop()
            return
        # Tolerate out-of-order manual release: newest matching entry.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- inspection ---------------------------------------------------------
    def edges(self) -> frozenset:
        """Every observed (held, then-acquired) pair."""
        with self._mu:
            return frozenset(self._edges)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()

    def find_cycle(self) -> list[str] | None:
        """A lock cycle in the observed order graph, or None."""
        graph: dict[str, set[str]] = {}
        for a, b in self.edges():
            graph.setdefault(a, set()).add(b)
        return find_cycle(graph)

    def assert_acyclic(self) -> None:
        cycle = self.find_cycle()
        if cycle:
            raise AssertionError(
                "lock-order cycle observed at runtime: "
                + " -> ".join(cycle)
                + " (two code paths acquire these locks in opposite "
                  "orders — a thread schedule exists that deadlocks)")


def find_cycle(graph: dict[str, set]) -> list[str] | None:
    """First cycle in a directed graph as [a, b, ..., a], or None.
    Shared by the witness and the static lock-order rule."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    for tgt in graph.values():
        for n in tgt:
            color.setdefault(n, WHITE)
    path: list[str] = []

    def dfs(n: str) -> list[str] | None:
        color[n] = GRAY
        path.append(n)
        for m in sorted(graph.get(n, ())):
            if color[m] == GRAY:
                return path[path.index(m):] + [m]
            if color[m] == WHITE:
                found = dfs(m)
                if found:
                    return found
        path.pop()
        color[n] = BLACK
        return None

    for n in sorted(color):
        if color[n] == WHITE:
            found = dfs(n)
            if found:
                return found
    return None


def cross_check(static_edges: frozenset | set,
                observed: frozenset | set) -> list[tuple[str, str]]:
    """Edges the witness observed that the static analyzer did NOT derive
    (analyzer/registry blind spots).  Empty list = the static graph covers
    everything the runtime actually did."""
    return sorted(set(observed) - set(static_edges))


WITNESS = LockWitness()


class _WitnessLock:
    """``threading.Lock`` wrapper recording acquisition order.  API-
    compatible with the subset the tree uses (with-statement, acquire/
    release, locked).  The with-statement path (``__enter__``/``__exit__``)
    inlines the recording — it brackets every pick-seam acquisition, and
    the ``pick_witness_ratio`` bench bounds its cost at < 5% of a pick."""

    __slots__ = ("_lock", "_name")

    def __init__(self, name: str):
        self._lock = threading.Lock()
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            WITNESS.note_acquire(self._name)
        return ok

    def release(self) -> None:
        WITNESS.note_release(self._name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self._lock.acquire()
        stack = WITNESS.thread_stack()
        if stack:
            WITNESS.note_edge(stack[-1], self._name)
        stack.append(self._name)
        return self

    def __exit__(self, *exc) -> None:
        stack = WITNESS.thread_stack()
        if stack and stack[-1] == self._name:
            stack.pop()
        else:
            WITNESS.note_release(self._name)
        self._lock.release()


class _WitnessRLock:
    """``threading.RLock`` wrapper.  Only the OUTERMOST acquisition records
    (reentrant re-acquisition is not an ordering edge — the lock is already
    held by this thread)."""

    __slots__ = ("_lock", "_name", "_tls")

    def __init__(self, name: str):
        self._lock = threading.RLock()
        self._name = name
        self._tls = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            depth = getattr(self._tls, "depth", 0)
            if depth == 0:
                WITNESS.note_acquire(self._name)
            self._tls.depth = depth + 1
        return ok

    def release(self) -> None:
        depth = getattr(self._tls, "depth", 1) - 1
        self._tls.depth = depth
        if depth == 0:
            WITNESS.note_release(self._name)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def witness_lock(name: str):
    """A lock for the shared-state class field ``name`` ("Class._attr").
    Plain ``threading.Lock`` unless the witness is armed (env, checked at
    construction so tests can arm per-rig)."""
    if armed():
        return _WitnessLock(name)
    return threading.Lock()


def witness_rlock(name: str):
    """Reentrant flavor (Provider/Datastore use RLock)."""
    if armed():
        return _WitnessRLock(name)
    return threading.RLock()
