"""CLI: ``python -m llm_instance_gateway_tpu.lint`` (see package docstring).

Exit status: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys

from llm_instance_gateway_tpu import lint
from llm_instance_gateway_tpu.lint import abi


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m llm_instance_gateway_tpu.lint",
        description="AST-driven repo-invariant checker for the gateway's "
                    "hand-maintained seams.")
    parser.add_argument("--root", default=None,
                        help="tree to lint (default: this checkout)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rule names and exit")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report grandfathered findings too")
    parser.add_argument("--write-abi-baseline", action="store_true",
                        help="refingerprint the native ABI "
                             "(lint/abi_baseline.json) after a deliberate, "
                             "version-bumped signature change")
    args = parser.parse_args(argv)
    root = args.root or lint.repo_root()
    if args.write_abi_baseline:
        path = abi.write_baseline(lint.Tree(root))
        print(f"wrote {path}")
        return 0
    if args.list_rules:
        lint._load_rules()
        for name, fn in lint.RULES:
            print(name)
        return 0
    rules = args.rules.split(",") if args.rules else None
    findings = lint.run(root, rules=rules,
                        apply_baseline=not args.no_baseline)
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} finding(s). Invariant catalogue: "
              f"ARCHITECTURE.md 'correctness tooling'; suppress a line "
              f"with `lig-lint: ignore[rule]`.", file=sys.stderr)
        return 1
    print("lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
