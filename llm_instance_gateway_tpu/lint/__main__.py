"""CLI: ``python -m llm_instance_gateway_tpu.lint`` (see package docstring).

Exit status: 0 clean, 1 findings, 2 usage error.

``--json`` emits one machine-readable document (findings + per-rule wall
milliseconds) for CI log scraping; ``--timings`` prints the per-rule table
in text mode so a slow rule is a number in the build log, not a vibe.
"""

from __future__ import annotations

import argparse
import json
import sys

from llm_instance_gateway_tpu import lint
from llm_instance_gateway_tpu.lint import abi


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m llm_instance_gateway_tpu.lint",
        description="AST-driven repo-invariant checker for the gateway's "
                    "hand-maintained seams.")
    parser.add_argument("--root", default=None,
                        help="tree to lint (default: this checkout)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rule names and exit")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report grandfathered findings too")
    parser.add_argument("--write-abi-baseline", action="store_true",
                        help="refingerprint the native ABI "
                             "(lint/abi_baseline.json) after a deliberate, "
                             "version-bumped signature change")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings + per-rule timings as one "
                             "JSON document (CI log scraping)")
    parser.add_argument("--timings", action="store_true",
                        help="print the per-rule wall-time table after "
                             "the findings")
    args = parser.parse_args(argv)
    root = args.root or lint.repo_root()
    if args.write_abi_baseline:
        path = abi.write_baseline(lint.Tree(root))
        print(f"wrote {path}")
        return 0
    if args.list_rules:
        lint._load_rules()
        for name, fn in lint.RULES:
            print(name)
        return 0
    rules = args.rules.split(",") if args.rules else None
    findings, timings = lint.run_timed(root, rules=rules,
                                       apply_baseline=not args.no_baseline)
    if args.as_json:
        print(json.dumps({
            "clean": not findings,
            "findings": [{"rule": f.rule, "path": f.path, "line": f.line,
                          "message": f.message} for f in findings],
            "timings_ms": {name: round(s * 1000, 2)
                           for name, s in timings.items()},
            "total_ms": round(sum(timings.values()) * 1000, 2),
        }, indent=1))
        return 1 if findings else 0
    for f in findings:
        print(f)
    if args.timings:
        width = max(len(n) for n in timings) if timings else 0
        for name, s in sorted(timings.items(), key=lambda kv: -kv[1]):
            print(f"  {name:<{width}}  {s * 1000:8.1f} ms")
        print(f"  {'TOTAL':<{width}}  "
              f"{sum(timings.values()) * 1000:8.1f} ms")
    if findings:
        print(f"\n{len(findings)} finding(s). Invariant catalogue: "
              f"ARCHITECTURE.md 'correctness tooling'; suppress a line "
              f"with `lig-lint: ignore[rule]`.", file=sys.stderr)
        return 1
    print("lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
