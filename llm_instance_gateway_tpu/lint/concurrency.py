"""Rules ``lock-order``, ``ownership``, ``publish-by-swap``.

The concurrency contract plane (ISSUE 13).  PR 9's lock-discipline rule
polices two narrow patterns (work under the native ``_call_lock``, blocking
calls in proxy coroutines); meanwhile PRs 10-11 multiplied the cross-thread
surface — the statebus gossip path writes advisor state that concurrent
data-path picks read lock-free, and ~40 ``threading.Lock`` sites guard
hand-maintained disciplines that lived in comments.  These rules pin them
against ``concurrency_registry.py`` (the ``metrics_registry.py`` shape):

**ownership** — every class that constructs a lock is registered; every
field a registered class rebinds after ``__init__`` is declared with a
publication discipline and a writer allowlist.  A write from an undeclared
method (or an undeclared field appearing at all) fails lint, so the overlay
seams (``set_remote_*``) are checked exceptions rather than folklore, and
new shared state cannot land undocumented.

**publish-by-swap** — fields declared SWAP_PUBLISHED are read lock-free on
the pick hot path, so the only legal write is replacing the whole object
(the ``_noisy_pods_cache`` tuple-swap idiom).  Any in-place mutation
(``.append`` / ``.update`` / ``[k] =`` / ``del`` / ``+=``) of such a field
is a torn-read factory and fails here.

**lock-order** — an interprocedural lock-acquisition graph: ``with
self._lock`` sites give direct nesting edges; call edges resolve through
the registry's ``BINDINGS`` (attribute name -> owning class) plus
same-class/same-module lookup, and a held lock gains an edge to every lock
its callees may transitively acquire.  A cycle is a potential deadlock —
two code paths that acquire the same pair of locks in opposite orders —
and fails statically, before any thread schedule has to demonstrate it.
Re-entrant acquisition of a lock not declared reentrant fails too
(``threading.Lock`` self-deadlocks).  Unresolvable calls are skipped: the
runtime ``lockwitness`` cross-checks the graph's completeness against the
acquisitions the deterministic interleave harness actually performs
(``tests/test_concurrency.py``), so an analyzer blind spot fails a test
instead of silently narrowing coverage.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from llm_instance_gateway_tpu.lint import PKG, Finding, Tree, rule

REGISTRY = f"{PKG}/concurrency_registry.py"

_LOCK_FACTORIES = {"Lock", "RLock", "witness_lock", "witness_rlock"}
_RLOCK_FACTORIES = {"RLock", "witness_rlock"}

# In-place mutators that tear a swap-published read.
_MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "sort", "reverse",
    "appendleft", "extendleft",
}


# ---------------------------------------------------------------------------
# Registry parsing (AST, not import — fixture trees must work)
# ---------------------------------------------------------------------------


@dataclass
class FieldDecl:
    name: str
    discipline: str
    writers: tuple = ()
    domain: str = ""


@dataclass
class ClassDecl:
    module: str
    name: str
    domain: str
    lock_attrs: tuple = ("_lock",)
    rlock_attrs: tuple = ()
    fields: dict = field(default_factory=dict)   # name -> FieldDecl


@dataclass
class Registry:
    classes: list            # [ClassDecl]
    bindings: dict           # attr name -> class name
    disciplines: set
    domains: set

    def by_key(self) -> dict:
        return {(c.module, c.name): c for c in self.classes}

    def by_name(self) -> dict:
        return {c.name: c for c in self.classes}


def _const_str(node, symbols: dict | None = None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    # f"{PKG}/gateway/usage.py" — the real registry prefixes module paths
    # with the package constant; resolve Name parts via module constants.
    if isinstance(node, ast.JoinedStr) and symbols is not None:
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(
                    part.value, str):
                out.append(part.value)
            elif (isinstance(part, ast.FormattedValue)
                  and isinstance(part.value, ast.Name)
                  and part.value.id in symbols):
                out.append(symbols[part.value.id])
            else:
                return None
        return "".join(out)
    return None


def _const_str_tuple(node) -> tuple:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            s = _const_str(e)
            if s is not None:
                out.append(s)
        return tuple(out)
    return ()


def _name_values(mod: ast.Module, names: set[str]) -> dict[str, str]:
    """Module-level NAME = "str" constants (the discipline/domain
    vocabulary), so registry entries can use the symbolic names."""
    out: dict[str, str] = {}
    for node in mod.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


def _resolve(node, symbols: dict[str, str]) -> str | None:
    s = _const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.Name):
        return symbols.get(node.id)
    return None


def load_registry(tree: Tree) -> tuple[Registry | None, list[Finding]]:
    mod = tree.parse(REGISTRY)
    if mod is None:
        return None, [Finding(
            "ownership", REGISTRY, 0,
            "concurrency_registry.py missing or unparseable — the shared-"
            "state contract has nothing to anchor to")]
    symbols = _name_values(mod, set())
    disciplines = {symbols[n] for n in
                   ("LOCK_GUARDED", "SWAP_PUBLISHED", "MONOTONIC",
                    "OWNER_PRIVATE")
                   if n in symbols}
    domains = {v for k, v in symbols.items()
               if k in ("DATA_PATH", "OBS_TICK", "GOSSIP", "ENGINE_STEP",
                        "COLLECTOR", "CONTROL")}
    classes: list[ClassDecl] = []
    bindings: dict[str, str] = {}
    for node in ast.walk(mod):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "BINDINGS"
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                ks, vs = _const_str(k), _const_str(v)
                if ks and vs:
                    bindings[ks] = vs
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "SharedClass"):
            continue
        args = list(node.args)
        if len(args) < 3:
            continue
        module = _const_str(args[0], symbols)
        name = _const_str(args[1])
        domain = _resolve(args[2], symbols) or ""
        if not module or not name:
            continue
        decl = ClassDecl(module=module, name=name, domain=domain)
        for kw in node.keywords:
            if kw.arg == "lock_attrs":
                decl.lock_attrs = _const_str_tuple(kw.value)
            elif kw.arg == "rlock_attrs":
                decl.rlock_attrs = _const_str_tuple(kw.value)
            elif kw.arg == "fields" and isinstance(
                    kw.value, (ast.Tuple, ast.List)):
                for fe in kw.value.elts:
                    if not (isinstance(fe, ast.Call)
                            and isinstance(fe.func, ast.Name)
                            and fe.func.id == "SharedField"
                            and fe.args):
                        continue
                    fname = _const_str(fe.args[0])
                    fdisc = (_resolve(fe.args[1], symbols)
                             if len(fe.args) > 1 else None) or ""
                    if fname is None:
                        continue
                    fd = FieldDecl(name=fname, discipline=fdisc)
                    for fkw in fe.keywords:
                        if fkw.arg == "writers":
                            fd.writers = _const_str_tuple(fkw.value)
                        elif fkw.arg == "domain":
                            fd.domain = _resolve(fkw.value, symbols) or ""
                    decl.fields[fname] = fd
        classes.append(decl)
    if not classes:
        return None, [Finding(
            "ownership", REGISTRY, 0,
            "no SharedClass(...) declarations found in "
            "concurrency_registry.py — re-anchor the shared-state "
            "contract")]
    return Registry(classes=classes, bindings=bindings,
                    disciplines=disciplines, domains=domains), []


# ---------------------------------------------------------------------------
# Package scanning helpers
# ---------------------------------------------------------------------------


# lockwitness.py IS the lock instrumentation — its internal mutex and
# wrapper classes are the measurement apparatus, excluded the way
# label-hygiene trusts tracing.py's renderer layer.
_TRUSTED = (f"{PKG}/lockwitness.py",)


def _pkg_files(tree: Tree) -> list[str]:
    return [rel for rel in tree.py_files(PKG, exclude=(f"{PKG}/lint/",))
            if rel not in _TRUSTED]


def _class_methods(cls: ast.ClassDef) -> dict[str, ast.AST]:
    return {m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _lock_constructions(cls: ast.ClassDef) -> dict[str, tuple]:
    """{attr: (lineno, is_rlock, witness_name)} for every
    ``self.<attr> = <lock>()``; ``witness_name`` is the string literal
    passed to witness_lock/witness_rlock (None for plain threading
    constructions)."""
    out: dict[str, tuple] = {}
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(meth):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                    and isinstance(node.value, ast.Call)):
                continue
            fn = node.value.func
            if isinstance(fn, ast.Attribute):
                # Only threading locks guard cross-THREAD state; asyncio
                # locks serialize coroutines on one loop.
                if not (isinstance(fn.value, ast.Name)
                        and fn.value.id == "threading"):
                    continue
                name = fn.attr
            else:
                name = getattr(fn, "id", "")
            if name in _LOCK_FACTORIES:
                witness_name = None
                if name.startswith("witness") and node.value.args:
                    witness_name = _const_str(node.value.args[0])
                out[node.targets[0].attr] = (
                    node.lineno, name in _RLOCK_FACTORIES, witness_name)
    return out


def _attr_rebinds(meth: ast.AST) -> list[tuple[str, int, str]]:
    """(field, lineno, kind) for every ``self.<field> = / += ...`` in the
    method (nested defs included: they close over self)."""
    out = []
    for node in ast.walk(meth):
        tgt = None
        kind = "assign"
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out.append((t.attr, node.lineno, "assign"))
            continue
        if isinstance(node, ast.AugAssign):
            tgt, kind = node.target, "augassign"
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt = node.target
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            out.append((tgt.attr, node.lineno, kind))
    return out


_INIT_METHODS = ("__init__", "__post_init__", "__new__")


# ---------------------------------------------------------------------------
# ownership
# ---------------------------------------------------------------------------


@rule("ownership")
def check_ownership(tree: Tree) -> list[Finding]:
    registry, findings = load_registry(tree)
    if registry is None:
        return findings
    by_key = registry.by_key()
    by_name = registry.by_name()

    # Vocabulary sanity: disciplines/domains/binding targets must resolve.
    for c in registry.classes:
        if registry.domains and c.domain not in registry.domains:
            findings.append(Finding(
                "ownership", REGISTRY, 0,
                f"{c.name}: domain {c.domain!r} is not a declared domain"))
        for f in c.fields.values():
            if registry.disciplines and \
                    f.discipline not in registry.disciplines:
                findings.append(Finding(
                    "ownership", REGISTRY, 0,
                    f"{c.name}.{f.name}: discipline {f.discipline!r} is "
                    f"not a declared discipline"))
            if f.domain and registry.domains and \
                    f.domain not in registry.domains:
                findings.append(Finding(
                    "ownership", REGISTRY, 0,
                    f"{c.name}.{f.name}: domain {f.domain!r} is not a "
                    f"declared domain"))
    for attr, cls_name in sorted(registry.bindings.items()):
        if cls_name not in by_name:
            findings.append(Finding(
                "ownership", REGISTRY, 0,
                f"BINDINGS[{attr!r}] names {cls_name!r}, which is not a "
                f"registered SharedClass — the lock-order analyzer "
                f"cannot resolve calls through it"))

    seen_keys: set[tuple[str, str]] = set()
    for rel in _pkg_files(tree):
        if rel == REGISTRY:
            continue
        mod = tree.parse(rel)
        if mod is None:
            continue
        for cls in ast.walk(mod):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_constructions(cls)
            decl = by_key.get((rel, cls.name))
            if decl is None:
                if locks:
                    attr, (lineno, _, _) = sorted(locks.items())[0]
                    findings.append(Finding(
                        "ownership", rel, lineno,
                        f"{cls.name} constructs a lock ({attr}) but is "
                        f"not registered in concurrency_registry.py — "
                        f"declare its owning domain, lock attrs, and "
                        f"shared fields (undeclared shared state is how "
                        f"the next race ships)"))
                continue
            seen_keys.add((rel, cls.name))
            # Lock attrs: constructed vs declared, both directions.
            for attr, (lineno, is_rlock, witness_name) in sorted(
                    locks.items()):
                # The witness name IS the lock's runtime identity; a
                # copy-paste typo would merge two locks into one graph
                # node and corrupt both the cross_check and acyclicity.
                if (witness_name is not None
                        and witness_name != f"{cls.name}.{attr}"):
                    findings.append(Finding(
                        "ownership", rel, lineno,
                        f"witness lock name {witness_name!r} does not "
                        f"match its owner {cls.name}.{attr} — the "
                        f"runtime witness would merge two distinct "
                        f"locks into one graph node (copy-paste?)"))
                if attr not in decl.lock_attrs:
                    findings.append(Finding(
                        "ownership", rel, lineno,
                        f"{cls.name}.{attr} is a constructed lock not in "
                        f"the registry's lock_attrs — the lock-order "
                        f"graph cannot see acquisitions of it"))
                elif is_rlock and attr not in decl.rlock_attrs:
                    findings.append(Finding(
                        "ownership", rel, lineno,
                        f"{cls.name}.{attr} is reentrant but not in "
                        f"rlock_attrs — the lock-order rule would "
                        f"misflag legal re-acquisition"))
                elif not is_rlock and attr in decl.rlock_attrs:
                    findings.append(Finding(
                        "ownership", rel, lineno,
                        f"{cls.name}.{attr} is declared reentrant but "
                        f"constructed as a plain Lock — re-acquisition "
                        f"self-deadlocks"))
            # Field inventory.
            assigned_anywhere: set[str] = set()
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                rebinds = _attr_rebinds(meth)
                assigned_anywhere.update(f for f, _, _ in rebinds)
                if meth.name in _INIT_METHODS:
                    continue
                for fname, lineno, _kind in rebinds:
                    if fname in locks or fname in decl.lock_attrs:
                        continue
                    fd = decl.fields.get(fname)
                    if fd is None:
                        findings.append(Finding(
                            "ownership", rel, lineno,
                            f"{cls.name}.{meth.name} writes undeclared "
                            f"shared field {fname!r} — declare it in "
                            f"concurrency_registry.py with a discipline "
                            f"and writer allowlist (cross-thread state "
                            f"does not get to be folklore)"))
                    elif meth.name not in fd.writers:
                        findings.append(Finding(
                            "ownership", rel, lineno,
                            f"{cls.name}.{meth.name} writes {fname!r} "
                            f"but is not in its declared writers "
                            f"{list(fd.writers)} — either the method "
                            f"joined the owning domain (declare it) or "
                            f"this write races the owner"))
            for fname, fd in sorted(decl.fields.items()):
                if fname not in assigned_anywhere:
                    findings.append(Finding(
                        "ownership", rel, cls.lineno,
                        f"{cls.name}.{fname} is declared in "
                        f"concurrency_registry.py but never assigned in "
                        f"the class — dead registry entry (or the field "
                        f"was renamed)"))
    for (module, name), decl in sorted(by_key.items()):
        if (module, name) not in seen_keys:
            findings.append(Finding(
                "ownership", REGISTRY, 0,
                f"registered class {name} not found in {module} — the "
                f"class moved or was renamed; re-anchor the registry"))
    return findings


# ---------------------------------------------------------------------------
# publish-by-swap
# ---------------------------------------------------------------------------


def _is_self_field(node: ast.AST, fields: dict) -> str | None:
    """field name when ``node`` is ``self.<field>`` for a declared swap
    field (the check scopes to the owning class — attr-name collisions
    across classes in one module must not cross-fire)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self" and node.attr in fields):
        return node.attr
    return None


@rule("publish-by-swap")
def check_publish_by_swap(tree: Tree) -> list[Finding]:
    registry, findings = load_registry(tree)
    if registry is None:
        return []  # ownership already reports the missing registry
    for decl in registry.classes:
        fields = {f.name: f for f in decl.fields.values()
                  if f.discipline == "publish-by-swap"}
        if not fields:
            continue
        mod = tree.parse(decl.module)
        if mod is None:
            continue
        cls = next((n for n in ast.walk(mod)
                    if isinstance(n, ast.ClassDef)
                    and n.name == decl.name), None)
        if cls is None:
            continue  # ownership reports the missing class
        for node in ast.walk(cls):
            # self.field.mutator(...)
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                if node.func.attr in _MUTATOR_METHODS:
                    base = _is_self_field(node.func.value, fields)
                    if base is not None:
                        findings.append(Finding(
                            "publish-by-swap", decl.module, node.lineno,
                            f"in-place .{node.func.attr}() on swap-"
                            f"published field {decl.name}.{base} — "
                            f"lock-free readers can see a half-built "
                            f"value; build a new object and swap it "
                            f"whole (the _noisy_pods_cache idiom)"))
                continue
            # self.field[k] = v   /   del self.field[k]
            if isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                base = _is_self_field(node.value, fields)
                if base is not None:
                    findings.append(Finding(
                        "publish-by-swap", decl.module, node.lineno,
                        f"in-place subscript write to swap-published "
                        f"field {decl.name}.{base} — lock-free "
                        f"readers can see a half-built value; build a "
                        f"new object and swap it whole"))
                continue
            # self.field += ... (read-modify-write: not a swap)
            if isinstance(node, ast.AugAssign):
                base = _is_self_field(node.target, fields)
                if base is not None:
                    findings.append(Finding(
                        "publish-by-swap", decl.module, node.lineno,
                        f"augmented assignment to swap-published field "
                        f"{decl.name}.{base} — a read-modify-write is "
                        f"not an atomic swap; compute the new value, then "
                        f"rebind"))
    return findings


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------


@dataclass
class _FnInfo:
    key: tuple                         # (class name or "", fn name)
    rel: str
    direct_edges: list = field(default_factory=list)  # (L, M, lineno)
    held_calls: list = field(default_factory=list)    # (L, callee key, line)
    acquired: set = field(default_factory=set)        # lock ids touched
    calls: set = field(default_factory=set)           # callee keys
    acquire_sites: dict = field(default_factory=dict)  # lock id -> lineno


def _lock_id_of(ctx: ast.AST, own_class: str | None,
                bindings: dict) -> str | None:
    """Lock identity for a with-item / .acquire() receiver, or None."""
    if not isinstance(ctx, ast.Attribute) or "lock" not in ctx.attr:
        return None
    base = ctx.value
    if isinstance(base, ast.Name):
        if base.id == "self":
            return f"{own_class}.{ctx.attr}" if own_class else None
        cls = bindings.get(base.id)
        return f"{cls}.{ctx.attr}" if cls else None
    if isinstance(base, ast.Attribute):
        cls = bindings.get(base.attr)
        return f"{cls}.{ctx.attr}" if cls else None
    return None


def _callee_key(node: ast.Call, own_class: str | None,
                bindings: dict, module_fns: set) -> tuple | None:
    fn = node.func
    if isinstance(fn, ast.Name):
        return ("", fn.id) if fn.id in module_fns else None
    if not isinstance(fn, ast.Attribute):
        return None
    base = fn.value
    if isinstance(base, ast.Name):
        if base.id == "self":
            return (own_class, fn.attr) if own_class else None
        cls = bindings.get(base.id)
        return (cls, fn.attr) if cls else None
    if isinstance(base, ast.Attribute):
        cls = bindings.get(base.attr)
        return (cls, fn.attr) if cls else None
    return None


def _walk_fn(info: _FnInfo, fn: ast.AST, own_class: str | None,
             bindings: dict, module_fns: set) -> None:
    """Recursive walk tracking the syntactic held-lock stack."""

    def visit(node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            inner = held
            for item in node.items:
                lock = _lock_id_of(item.context_expr, own_class, bindings)
                if lock is not None:
                    info.acquired.add(lock)
                    info.acquire_sites.setdefault(lock, node.lineno)
                    if inner:
                        info.direct_edges.append(
                            (inner[-1], lock, node.lineno))
                    inner = inner + (lock,)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Call):
            # Explicit .acquire() outside a with-statement.
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                lock = _lock_id_of(node.func.value, own_class, bindings)
                if lock is not None:
                    info.acquired.add(lock)
                    info.acquire_sites.setdefault(lock, node.lineno)
                    if held:
                        info.direct_edges.append(
                            (held[-1], lock, node.lineno))
            callee = _callee_key(node, own_class, bindings, module_fns)
            if callee is not None:
                info.calls.add(callee)
                if held:
                    info.held_calls.append((held[-1], callee, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for child in ast.iter_child_nodes(fn):
        visit(child, ())


def _build_fn_table(tree: Tree,
                    bindings: dict) -> dict[tuple, list[_FnInfo]]:
    """(class-or-"" , fn name) -> [_FnInfo] over the whole package.
    Class keys use the bare class name (BINDINGS resolve to names, not
    modules); colliding class names merge conservatively."""
    table: dict[tuple, list[_FnInfo]] = {}
    for rel in _pkg_files(tree):
        if rel == REGISTRY:
            continue
        mod = tree.parse(rel)
        if mod is None:
            continue
        module_fns = {n.name for n in mod.body
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        for node in mod.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FnInfo(key=("", node.name), rel=rel)
                _walk_fn(info, node, None, bindings, module_fns)
                table.setdefault(info.key, []).append(info)
            elif isinstance(node, ast.ClassDef):
                for meth in node.body:
                    if not isinstance(meth, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    info = _FnInfo(key=(node.name, meth.name), rel=rel)
                    _walk_fn(info, meth, node.name, bindings, module_fns)
                    table.setdefault(info.key, []).append(info)
    return table


def _transitive_acquired(table: dict) -> dict[tuple, set]:
    """Fixpoint: every lock a function may acquire, directly or through
    resolved callees."""
    acq = {key: set().union(*(i.acquired for i in infos))
           for key, infos in table.items()}
    changed = True
    while changed:
        changed = False
        for key, infos in table.items():
            cur = acq[key]
            before = len(cur)
            for info in infos:
                for callee in info.calls:
                    if callee in acq:
                        cur |= acq[callee]
            if len(cur) != before:
                changed = True
    return acq


def static_lock_graph(tree: Tree) -> tuple[dict[str, set], dict, list]:
    """(edges graph, witness sites, findings) — the interprocedural
    acquisition-order graph.  ``witness sites``: edge -> (rel, lineno,
    description) for reporting; the edge set is also what
    ``lockwitness.cross_check`` compares observed runtime edges against.
    """
    graph, sites, findings, _ = _lock_graph_full(tree)
    return graph, sites, findings


def _lock_graph_full(tree: Tree):
    registry, findings = load_registry(tree)
    bindings = dict(registry.bindings) if registry else {}
    rlocks: set[str] = set()
    if registry:
        for c in registry.classes:
            for attr in c.rlock_attrs:
                rlocks.add(f"{c.name}.{attr}")
    table = _build_fn_table(tree, bindings)
    acq = _transitive_acquired(table)
    n_acquire_sites = sum(len(i.acquire_sites)
                          for infos in table.values() for i in infos)
    graph: dict[str, set] = {}
    sites: dict[tuple, tuple] = {}
    for key, infos in table.items():
        for info in infos:
            for held, lock, lineno in info.direct_edges:
                graph.setdefault(held, set()).add(lock)
                sites.setdefault((held, lock), (
                    info.rel, lineno,
                    f"{'.'.join(k for k in key if k)} nests the "
                    f"acquisition directly"))
            for held, callee, lineno in info.held_calls:
                for lock in acq.get(callee, ()):
                    graph.setdefault(held, set()).add(lock)
                    sites.setdefault((held, lock), (
                        info.rel, lineno,
                        f"{'.'.join(k for k in key if k)} calls "
                        f"{'.'.join(c for c in callee if c)} while "
                        f"holding {held}"))
    # Reentrancy self-edges are legal for declared rlocks.
    for lock in rlocks:
        if lock in graph:
            graph[lock].discard(lock)
    return graph, sites, findings, n_acquire_sites


@rule("lock-order")
def check_lock_order(tree: Tree) -> list[Finding]:
    from llm_instance_gateway_tpu.lockwitness import find_cycle

    graph, sites, reg_findings, n_sites = _lock_graph_full(tree)
    if reg_findings:
        return []  # the registry problem is ownership's finding
    findings: list[Finding] = []
    if n_sites == 0:
        return [Finding(
            "lock-order", REGISTRY, 0,
            "no lock acquisitions found anywhere in the package — the "
            "locking moved; re-anchor this rule")]
    # Self-edges on non-reentrant locks: guaranteed self-deadlock.
    for lock in sorted(graph):
        if lock in graph.get(lock, ()):
            rel, lineno, why = sites.get((lock, lock),
                                         (REGISTRY, 0, "unknown site"))
            findings.append(Finding(
                "lock-order", rel, lineno,
                f"re-entrant acquisition of non-reentrant lock {lock} "
                f"({why}) — threading.Lock self-deadlocks; hoist the "
                f"inner acquisition out or declare the lock reentrant "
                f"in the registry"))
    # Cycles among distinct locks: a deadlock-capable ordering exists.
    pruned = {a: {b for b in tgts if b != a} for a, tgts in graph.items()}
    seen_cycles: set[tuple] = set()
    while True:
        cycle = find_cycle(pruned)
        if cycle is None:
            break
        canon = tuple(sorted(set(cycle)))
        if canon in seen_cycles:
            break
        seen_cycles.add(canon)
        a, b = cycle[0], cycle[1]
        rel, lineno, why = sites.get((a, b), (REGISTRY, 0, "unknown site"))
        findings.append(Finding(
            "lock-order", rel, lineno,
            f"lock-order cycle: {' -> '.join(cycle)} — two code paths "
            f"acquire these locks in opposite orders ({why}); a thread "
            f"schedule exists that deadlocks.  Break the cycle by "
            f"snapshotting state before crossing objects"))
        # Remove one edge of the reported cycle and keep looking so
        # independent cycles each get a finding.
        pruned[a].discard(b)
    return findings
