"""Rules ``flag-docs`` and ``usage-conservation``.

**flag-docs**: every ``add_argument("--flag", ...)`` the gateway bootstrap
exposes must appear in README.md or ARCHITECTURE.md.  The bootstrap is the
operator surface — five PRs added flags (resilience, fairness, placement)
and the only discovery path was reading argparse source.  An undocumented
flag is a feature nobody can deploy.

**usage-conservation** (PR 5's invariant): the capacity-attribution plane
rests on Σ per-adapter step-seconds == engine-wall step-seconds (the
conservation test pins it within 1% at runtime).  That only holds because
every site that charges a per-adapter share also charges the engine-wall
denominator in the same function, and because nothing outside
``server/usage.py`` writes the accumulator tables directly.  This rule pins
both statically: a new charge path that forgets the denominator (or an
engine call site that pokes ``tracker.step_seconds`` behind the API) fails
here instead of skewing every noisy-neighbor score derived downstream.
"""

from __future__ import annotations

import ast

from llm_instance_gateway_tpu.lint import PKG, Finding, Tree, rule

BOOTSTRAP = f"{PKG}/gateway/bootstrap.py"
USAGE = f"{PKG}/server/usage.py"
DOCS = ("README.md", "ARCHITECTURE.md")


@rule("flag-docs")
def check_flag_docs(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    mod = tree.parse(BOOTSTRAP)
    if mod is None:
        return [Finding("flag-docs", BOOTSTRAP, 0,
                        "gateway/bootstrap.py missing or unparseable")]
    docs = "\n".join(tree.read(d) or "" for d in DOCS)
    if not docs.strip():
        return [Finding("flag-docs", "README.md", 0,
                        "README.md / ARCHITECTURE.md missing — flags have "
                        "nowhere to be documented")]
    flags: list[tuple[str, int]] = []
    for node in ast.walk(mod):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str) and arg.value.startswith("--"):
                flags.append((arg.value, node.lineno))
    if not flags:
        findings.append(Finding(
            "flag-docs", BOOTSTRAP, 0,
            "no add_argument flags found in bootstrap.py — the operator "
            "surface moved; re-anchor this rule"))
        return findings
    for flag, lineno in flags:
        if flag not in docs:
            findings.append(Finding(
                "flag-docs", BOOTSTRAP, lineno,
                f"flag {flag} is not documented in README.md or "
                f"ARCHITECTURE.md — an undocumented flag is a feature "
                f"nobody can deploy"))
    return findings


def _subscript_attr_store(node: ast.AST) -> str | None:
    """'attr' when ``node`` stores into ``<obj>.attr[...]``."""
    if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Store) and isinstance(node.value, ast.Attribute):
        return node.value.attr
    return None


# Distinctive accumulator names only ("tokens" is too generic to claim).
_TABLES = ("step_seconds", "engine_step_seconds", "kv_block_seconds")


@rule("usage-conservation")
def check_usage_conservation(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    mod = tree.parse(USAGE)
    if mod is None:
        return [Finding("usage-conservation", USAGE, 0,
                        "server/usage.py missing or unparseable")]
    charge_fns = 0
    for fn in ast.walk(mod):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stores = set()
        for node in ast.walk(fn):
            attr = _subscript_attr_store(node)
            if attr:
                stores.add(attr)
        if "step_seconds" in stores:
            charge_fns += 1
            if "engine_step_seconds" not in stores:
                findings.append(Finding(
                    "usage-conservation", USAGE, fn.lineno,
                    f"{fn.name}: charges per-adapter step_seconds without "
                    f"charging the engine-wall engine_step_seconds "
                    f"denominator at the same site — the conservation "
                    f"invariant (Σ per-adapter == total, PR 5) breaks and "
                    f"every usage share downstream skews"))
    if charge_fns == 0:
        findings.append(Finding(
            "usage-conservation", USAGE, 0,
            "no per-adapter charge sites found in server/usage.py — the "
            "attribution plane moved; re-anchor this rule"))

    # Accumulator tables are written ONLY through the UsageTracker API.
    for rel in tree.py_files(PKG, exclude=(f"{PKG}/lint/",)):
        if rel == USAGE:
            continue
        other = tree.parse(rel)
        if other is None:
            continue
        for node in ast.walk(other):
            attr = _subscript_attr_store(node)
            if attr in _TABLES:
                findings.append(Finding(
                    "usage-conservation", rel, node.lineno,
                    f"direct write into a UsageTracker accumulator "
                    f"(.{attr}[...]) outside server/usage.py — charge "
                    f"through charge_step/charge_decode so the "
                    f"conservation denominator moves with it"))
    return findings
