"""Rules ``seam-order`` and ``lock-discipline``.

**seam-order** (established by PR 4, extended by PRs 7/8): in BOTH scheduler
paths (``scheduling/scheduler.py`` and ``scheduling/native.py``) the three
advisor filters must run in canonical order —

    filter_by_policy -> filter_by_fairness -> filter_by_placement

— and all of them BEFORE any prefix-affinity tie-break or RNG draw.  The
ordering is load-bearing twice over: ``log_only`` stays routing-byte-identical
only because the filters see the un-drawn survivor set (the same-RNG diff
tests pin the bytes, this rule pins the shape), and an enforcing health
policy must narrow the set before the prefix tie-break can pin a request to
an avoided holder.  Breaking it costs silent routing skew that only shows up
as a diff-test failure hundreds of picks into a seed.

**lock-discipline** (established by PR 6): the native scheduler's
``_call_lock`` exists to guard the resident state handle and its persistent
buffers across threaded gRPC transports — nothing else.  Prefix hashing
(the lazy blake2b chain behind ``req.prefix_hashes``), RNG draws, and the
``note_*`` advisor callbacks can each cost more than the pick itself;
serializing them collapses the threaded transport to single-thread hash
speed (the regression PR 6 removed).  So inside ``with self._call_lock:``
blocks: no hashing, no RNG, no ``note_*`` / prefix-index calls, no
``req.prefix_hashes`` reads, no blocking I/O.  The same blocking-work ban
applies to ``async def`` bodies in ``gateway/proxy.py``: one ``time.sleep``
or sync-HTTP call stalls the whole event loop, not one request.
"""

from __future__ import annotations

import ast

from llm_instance_gateway_tpu.lint import Finding, Tree, rule

SCHED = "llm_instance_gateway_tpu/gateway/scheduling/scheduler.py"
NATIVE = "llm_instance_gateway_tpu/gateway/scheduling/native.py"
PROXY = "llm_instance_gateway_tpu/gateway/proxy.py"

# Canonical advisor-filter order (PR 4 health, PR 7 fairness, PR 8 placement).
FILTER_ORDER = ("filter_by_policy", "filter_by_fairness",
                "filter_by_placement")

# Attribute calls that consume randomness or the prefix tie-break seam.
_RNG_ATTRS = {"randrange", "random", "choice", "shuffle", "getrandbits",
              "randint", "sample"}
_PREFIX_ATTRS = {"prefer"}  # prefix_index.prefer(...) is the tie-break entry


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _functions(mod: ast.Module):
    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _direct_body_walk(fn: ast.AST):
    """Walk a function's own body, not nested function definitions."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@rule("seam-order")
def check_seam_order(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    anchored = 0
    for rel in (SCHED, NATIVE):
        mod = tree.parse(rel)
        if mod is None:
            findings.append(Finding(
                "seam-order", rel, 0,
                "scheduler module missing or unparseable — the advisor-seam "
                "invariant has nothing to anchor to"))
            continue
        for fn in _functions(mod):
            filter_calls: list[tuple[int, int, str]] = []  # (line, col, name)
            consume_calls: list[tuple[int, str]] = []      # (line, what)
            for node in _direct_body_walk(fn):
                if isinstance(node, ast.Call):
                    name = _call_name(node)
                    if name in FILTER_ORDER:
                        filter_calls.append(
                            (node.lineno, node.col_offset, name))
                    elif name in _RNG_ATTRS:
                        consume_calls.append((node.lineno, "RNG draw"))
                    elif (name in _PREFIX_ATTRS
                          and isinstance(node.func, ast.Attribute)
                          and "prefix" in ast.dump(node.func.value)):
                        consume_calls.append(
                            (node.lineno, "prefix tie-break"))
            if not filter_calls:
                continue
            anchored += 1
            filter_calls.sort()
            seen = [name for _, _, name in filter_calls]
            want = [n for n in FILTER_ORDER if n in seen]
            if seen != want:
                findings.append(Finding(
                    "seam-order", rel, filter_calls[0][0],
                    f"{fn.name}: advisor filters run as {seen}; canonical "
                    f"order is policy -> fairness -> placement"))
            missing = [n for n in FILTER_ORDER if n not in seen]
            if missing:
                findings.append(Finding(
                    "seam-order", rel, filter_calls[0][0],
                    f"{fn.name}: advisor seam incomplete — calls "
                    f"{seen} but never {missing} (every pick path runs all "
                    f"three advisor filters)"))
            last_filter_line = filter_calls[-1][0]
            for line, what in sorted(consume_calls):
                if line < last_filter_line:
                    findings.append(Finding(
                        "seam-order", rel, line,
                        f"{fn.name}: {what} at line {line} precedes the "
                        f"advisor filter at line {last_filter_line} — "
                        f"filters must narrow the survivor set BEFORE any "
                        f"tie-break or draw"))
    if anchored == 0 and not findings:
        findings.append(Finding(
            "seam-order", SCHED, 0,
            "no function calls the advisor filters anywhere — the seam "
            "moved; re-anchor this rule before trusting it"))
    return findings


# Work forbidden while holding the native scheduler's call lock.
_LOCKED_FORBIDDEN_CALLS = {
    "prefer": "prefix tie-break",
    "record": "prefix-index bookkeeping",
    "blake2b": "hashing", "md5": "hashing", "sha1": "hashing",
    "sha256": "hashing", "sha512": "hashing",
    "sleep": "blocking sleep",
    "urlopen": "sync HTTP",
    "request": "sync HTTP",
}
_LOCKED_FORBIDDEN_ATTRS = {
    "prefix_hashes": ("lazy prefix-hash resolution (the blake2b chain runs "
                      "on first read)"),
}


def _is_call_lock(item: ast.withitem) -> bool:
    ctx = item.context_expr
    return (isinstance(ctx, ast.Attribute) and ctx.attr == "_call_lock")


@rule("lock-discipline")
def check_lock_discipline(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    mod = tree.parse(NATIVE)
    if mod is None:
        findings.append(Finding(
            "lock-discipline", NATIVE, 0,
            "native scheduler module missing or unparseable"))
    else:
        lock_blocks = 0
        for node in ast.walk(mod):
            if not isinstance(node, ast.With):
                continue
            if not any(_is_call_lock(i) for i in node.items):
                continue
            lock_blocks += 1
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call):
                    name = _call_name(inner)
                    if name in _LOCKED_FORBIDDEN_CALLS:
                        findings.append(Finding(
                            "lock-discipline", NATIVE, inner.lineno,
                            f"{_LOCKED_FORBIDDEN_CALLS[name]} "
                            f"({name}) inside the _call_lock block — PR 6 "
                            f"moved this outside the lock; threaded "
                            f"transports serialize on it otherwise"))
                    elif name in _RNG_ATTRS or (
                            name.startswith("note_")):
                        findings.append(Finding(
                            "lock-discipline", NATIVE, inner.lineno,
                            f"{name}() inside the _call_lock block — RNG "
                            f"and advisor note_* seams run unlocked "
                            f"(Scheduler parity + PR 6 lock discipline)"))
                elif isinstance(inner, ast.Attribute):
                    if inner.attr in _LOCKED_FORBIDDEN_ATTRS:
                        findings.append(Finding(
                            "lock-discipline", NATIVE, inner.lineno,
                            f".{inner.attr} read inside the _call_lock "
                            f"block — {_LOCKED_FORBIDDEN_ATTRS[inner.attr]}"))
        if lock_blocks == 0:
            findings.append(Finding(
                "lock-discipline", NATIVE, 0,
                "no `with self._call_lock:` blocks found — the native "
                "scheduler's locking moved; re-anchor this rule"))

    # Proxy coroutines: no synchronous sleeps or sync HTTP on the event loop.
    pmod = tree.parse(PROXY)
    if pmod is None:
        findings.append(Finding(
            "lock-discipline", PROXY, 0,
            "gateway proxy module missing or unparseable"))
        return findings
    for fn in _functions(pmod):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in _direct_body_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)):
                base, attr = f.value.id, f.attr
                if base == "time" and attr == "sleep":
                    findings.append(Finding(
                        "lock-discipline", PROXY, node.lineno,
                        f"{fn.name}: time.sleep() inside a coroutine "
                        f"stalls the whole event loop — use "
                        f"asyncio.sleep()"))
                elif base in ("urllib", "requests") or (
                        base == "request" and attr == "urlopen"):
                    findings.append(Finding(
                        "lock-discipline", PROXY, node.lineno,
                        f"{fn.name}: synchronous HTTP ({base}.{attr}) "
                        f"inside a coroutine — use the aiohttp session"))
            elif isinstance(f, ast.Attribute) and f.attr == "urlopen":
                findings.append(Finding(
                    "lock-discipline", PROXY, node.lineno,
                    f"{fn.name}: synchronous urlopen inside a coroutine — "
                    f"use the aiohttp session"))
    return findings
