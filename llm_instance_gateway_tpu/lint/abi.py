"""Rule ``abi-drift``: the native FFI contract can't move silently.

The ABI between ``native/scheduler.cc`` and the ctypes marshals in
``scheduling/native.py`` drifted once already: PR 7 changed
``lig_state_update``/``lig_pick`` arity and review had to retrofit the
``lig_abi_version()`` handshake because a stale prebuilt ``.so`` would have
scrambled arguments in the routing hot path (wrong pods picked, or a
segfault, depending on register luck).  The handshake protects RUNTIME
loads; this rule protects the SOURCE TREE:

1. the ``_ABI_VERSION`` constant in native.py equals the literal returned
   by ``lig_abi_version()`` in scheduler.cc;
2. every ``extern "C"`` function's parameter list (count AND types) matches
   the ctypes ``argtypes``/``restype`` marshal for it; and
3. the exported signature set matches the checked-in fingerprint
   (``lint/abi_baseline.json``).  Changing a signature without bumping the
   version is the exact failure mode PR 7 shipped — it fails here, in the
   tree, before any .so exists.  A legitimate ABI change bumps the version
   in BOTH sources and regenerates the baseline
   (``python -m llm_instance_gateway_tpu.lint --write-abi-baseline``) in
   the same commit.
"""

from __future__ import annotations

import ast
import json
import re

from llm_instance_gateway_tpu.lint import Finding, Tree, rule

CC = "llm_instance_gateway_tpu/native/scheduler.cc"
PY = "llm_instance_gateway_tpu/gateway/scheduling/native.py"
BASELINE = "llm_instance_gateway_tpu/lint/abi_baseline.json"

_VERSION_RE = re.compile(
    r"lig_abi_version\s*\(\s*void\s*\)\s*\{\s*return\s+(\d+)\s*;")
_FN_RE = re.compile(
    r"\b([A-Za-z_][A-Za-z0-9_]*)\s*(\*)?\s*\b(lig_[a-z0-9_]+)\s*"
    r"\(([^)]*)\)\s*\{", re.S)
_PARAM_RE = re.compile(
    r"^(?:const\s+)?([A-Za-z_][A-Za-z0-9_]*)\s*(\*)?\s*"
    r"(?:[A-Za-z_][A-Za-z0-9_]*)?$")

# ctypes expression -> normalized C type.
_CTYPE_MAP = {
    "c_void_p": "void*", "c_char_p": "char*",
    "c_int32": "int32_t", "c_int64": "int64_t",
    "c_uint8": "uint8_t", "c_uint32": "uint32_t",
    "c_double": "double", "c_float": "float",
    "c_int": "int", "c_size_t": "size_t",
}


def _strip_comments(src: str) -> str:
    src = re.sub(r"/\*.*?\*/", " ", src, flags=re.S)
    return re.sub(r"//[^\n]*", " ", src)


def cc_signatures(tree: Tree) -> tuple[int | None, dict, list[Finding]]:
    """(abi_version, {fn: {"ret": t, "args": [t...]}}, parse findings)."""
    findings: list[Finding] = []
    src = tree.read(CC)
    if src is None:
        return None, {}, [Finding("abi-drift", CC, 0,
                                  "native/scheduler.cc missing")]
    src = _strip_comments(src)
    m = _VERSION_RE.search(src)
    version = int(m.group(1)) if m else None
    if version is None:
        findings.append(Finding(
            "abi-drift", CC, 0,
            "lig_abi_version() not found — the runtime handshake has "
            "nothing to return"))
    sigs: dict[str, dict] = {}
    for ret, ret_ptr, name, params in _FN_RE.findall(src):
        args: list[str] = []
        bad = False
        params = params.strip()
        if params and params != "void":
            for raw in params.split(","):
                pm = _PARAM_RE.match(" ".join(raw.split()))
                if pm is None:
                    findings.append(Finding(
                        "abi-drift", CC, 0,
                        f"{name}: unparseable parameter {raw.strip()!r} — "
                        f"keep extern \"C\" params to plain scalar/pointer "
                        f"types so the marshal stays checkable"))
                    bad = True
                    break
                args.append(pm.group(1) + ("*" if pm.group(2) else ""))
        if bad:
            continue
        sigs[name] = {"ret": ret + ("*" if ret_ptr else ""), "args": args}
    if not sigs:
        findings.append(Finding(
            "abi-drift", CC, 0,
            "no extern \"C\" lig_* definitions found in scheduler.cc"))
    return version, sigs, findings


def _ctype_of(node: ast.expr, aliases: dict[str, str]) -> str | None:
    if isinstance(node, ast.Attribute):
        return _CTYPE_MAP.get(node.attr)
    if isinstance(node, ast.Name):
        if node.id in aliases:
            return aliases[node.id]
        return _CTYPE_MAP.get(node.id)
    if isinstance(node, ast.Constant) and node.value is None:
        return "void"
    if isinstance(node, ast.Call):
        # ctypes.POINTER(ctypes.c_X) inline
        fn = node.func
        fn_name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
            fn, "id", "")
        if fn_name == "POINTER" and node.args:
            inner = _ctype_of(node.args[0], aliases)
            return inner + "*" if inner else None
    return None


def py_marshals(tree: Tree) -> tuple[int | None, dict, list[Finding]]:
    """(_ABI_VERSION, {fn: {"ret": t|None, "args": [t...]|None}}, findings)."""
    findings: list[Finding] = []
    mod = tree.parse(PY)
    if mod is None:
        return None, {}, [Finding("abi-drift", PY, 0,
                                  "scheduling/native.py missing or "
                                  "unparseable")]
    version: int | None = None
    aliases: dict[str, str] = {}
    for node in mod.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            if tname == "_ABI_VERSION" and isinstance(
                    node.value, ast.Constant):
                version = int(node.value.value)
            else:
                t = _ctype_of(node.value, aliases)
                if t is not None:
                    aliases[tname] = t
    marshals: dict[str, dict] = {}
    for node in ast.walk(mod):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        if not (isinstance(target, ast.Attribute)
                and target.attr in ("argtypes", "restype")
                and isinstance(target.value, ast.Attribute)):
            continue
        fn_name = target.value.attr
        if not fn_name.startswith("lig_"):
            continue
        entry = marshals.setdefault(fn_name, {"ret": None, "args": None})
        if target.attr == "restype":
            entry["ret"] = _ctype_of(node.value, aliases)
        else:
            if not isinstance(node.value, (ast.List, ast.Tuple)):
                findings.append(Finding(
                    "abi-drift", PY, node.lineno,
                    f"{fn_name}.argtypes is not a literal list — the "
                    f"marshal must stay statically checkable"))
                continue
            args = []
            for el in node.value.elts:
                t = _ctype_of(el, aliases)
                if t is None:
                    findings.append(Finding(
                        "abi-drift", PY, el.lineno,
                        f"{fn_name}.argtypes element not resolvable to a "
                        f"C type ({ast.dump(el)[:60]})"))
                    t = "?"
                args.append(t)
            entry["args"] = args
    if version is None:
        findings.append(Finding(
            "abi-drift", PY, 0,
            "_ABI_VERSION constant not found in scheduling/native.py"))
    return version, marshals, findings


def _compatible(cc_t: str, py_t: str) -> bool:
    if cc_t == py_t:
        return True
    # Any data pointer marshals as c_void_p; int is int32 on our targets.
    if py_t == "void*" and cc_t.endswith("*"):
        return True
    return False


@rule("abi-drift")
def check_abi(tree: Tree) -> list[Finding]:
    cc_version, sigs, findings = cc_signatures(tree)
    py_version, marshals, py_findings = py_marshals(tree)
    findings += py_findings
    if cc_version is not None and py_version is not None \
            and cc_version != py_version:
        findings.append(Finding(
            "abi-drift", PY, 0,
            f"_ABI_VERSION={py_version} but scheduler.cc "
            f"lig_abi_version() returns {cc_version} — the handshake "
            f"will refuse every build"))
    for fn_name, marshal in sorted(marshals.items()):
        sig = sigs.get(fn_name)
        if sig is None:
            findings.append(Finding(
                "abi-drift", PY, 0,
                f"ctypes marshals {fn_name} but scheduler.cc does not "
                f"define it"))
            continue
        if marshal["args"] is not None:
            if len(marshal["args"]) != len(sig["args"]):
                findings.append(Finding(
                    "abi-drift", PY, 0,
                    f"{fn_name}: arity mismatch — scheduler.cc takes "
                    f"{len(sig['args'])} parameters, argtypes marshals "
                    f"{len(marshal['args'])} (a stale .so would scramble "
                    f"arguments; fix the marshal AND bump the ABI "
                    f"version)"))
            else:
                for i, (cc_t, py_t) in enumerate(
                        zip(sig["args"], marshal["args"])):
                    if not _compatible(cc_t, py_t):
                        findings.append(Finding(
                            "abi-drift", PY, 0,
                            f"{fn_name}: parameter {i} type mismatch — "
                            f"scheduler.cc declares {cc_t}, argtypes "
                            f"marshals {py_t}"))
        if marshal["ret"] is not None and not _compatible(
                sig["ret"], marshal["ret"]):
            findings.append(Finding(
                "abi-drift", PY, 0,
                f"{fn_name}: return type mismatch — scheduler.cc returns "
                f"{sig['ret']}, restype is {marshal['ret']}"))

    # Fingerprint: signature changes require a version bump + regenerated
    # baseline in the SAME commit.
    raw = tree.read(BASELINE)
    if raw is None:
        findings.append(Finding(
            "abi-drift", BASELINE, 0,
            "ABI baseline missing — run `python -m "
            "llm_instance_gateway_tpu.lint --write-abi-baseline`"))
        return findings
    try:
        baseline = json.loads(raw)
    except ValueError:
        findings.append(Finding(
            "abi-drift", BASELINE, 0, "ABI baseline is not valid JSON"))
        return findings
    base_version = baseline.get("abi_version")
    base_sigs = baseline.get("signatures", {})
    if sigs != base_sigs:
        changed = sorted(
            set(sigs) ^ set(base_sigs)
            | {n for n in set(sigs) & set(base_sigs)
               if sigs[n] != base_sigs[n]})
        if cc_version == base_version:
            findings.append(Finding(
                "abi-drift", CC, 0,
                f"exported ABI changed ({', '.join(changed)}) without a "
                f"lig_abi_version() bump — a prebuilt .so from the old "
                f"tree would pass the handshake and scramble arguments"))
        else:
            findings.append(Finding(
                "abi-drift", BASELINE, 0,
                f"ABI baseline stale for {', '.join(changed)} — version "
                f"bumped to {cc_version}; regenerate with `python -m "
                f"llm_instance_gateway_tpu.lint --write-abi-baseline`"))
    elif cc_version != base_version:
        findings.append(Finding(
            "abi-drift", BASELINE, 0,
            f"baseline records abi_version={base_version} but "
            f"scheduler.cc returns {cc_version} — regenerate the "
            f"baseline"))
    return findings


def write_baseline(tree: Tree) -> str:
    """Regenerate lint/abi_baseline.json from scheduler.cc; returns path."""
    version, sigs, findings = cc_signatures(tree)
    if findings:
        raise SystemExit("cannot fingerprint ABI:\n" + "\n".join(
            str(f) for f in findings))
    path = tree.path(BASELINE)
    import os

    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"abi_version": version, "signatures": sigs}, fh,
                  indent=2, sort_keys=True)
        fh.write("\n")
    return path
