"""Rules ``metric-currency``, ``event-kinds``, ``label-hygiene``.

**metric-currency** (PR 3's registry, superset of the runtime docs test):
every metric-family name string used in a render path — a ``# TYPE`` line,
a ``render_counter``/``render_keyed_family``/``render_histogram``/
``render_prom`` call, or an exposition sample line like
``tpu:adapter_step_seconds_total{...}`` — must be declared in
``metrics_registry.py``, and every registered family must still appear
somewhere in code.  The runtime exposition-contract test catches the same
drift but only for surfaces a test actually renders; this rule catches it
without running servers, including families behind feature flags.

**event-kinds** (PR 3's journal): every kind passed to ``journal.emit(...)``
or an ``event_sink(...)`` — whether a string literal or an
``events_mod.NAME`` attribute — must be a constant declared in
``events.py``.  A typo'd kind silently creates a new counter series and
breaks ``tools/blackbox_report.py``'s narration.

**label-hygiene** (PR 2's hardening): exposition lines assembled by
f-string or %-format must escape label VALUES through ``escape_label`` —
one hostile model/adapter name (embedded quote, newline) poisons the whole
/metrics page for every scraper otherwise.  The rule scopes itself to
modules that render exposition text (any module containing a ``# TYPE ``
literal), treats ``tracing.py`` itself as the trusted renderer layer, and
accepts values that are escape_label calls, locals assigned from
escape_label, numeric %-conversions, or constant-only expressions.
"""

from __future__ import annotations

import ast
import re

from llm_instance_gateway_tpu.lint import PKG, Finding, Tree, rule

REGISTRY = f"{PKG}/metrics_registry.py"
EVENTS = f"{PKG}/events.py"
TRUSTED_RENDERERS = (f"{PKG}/tracing.py",)

_TYPE_RE = re.compile(r"# TYPE ([A-Za-z_:][A-Za-z0-9_:]*)")
_FAMILY_PREFIX_RE = re.compile(
    r"^(gateway_[A-Za-z0-9_]+|tpu:[A-Za-z0-9_:]+)\{")
_HIST_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")
_RENDER_HELPERS = {"render_counter", "render_keyed_family",
                   "render_histogram", "render_prom", "_counter_lines"}


def _pkg_files(tree: Tree) -> list[str]:
    return tree.py_files(PKG, exclude=(f"{PKG}/lint/",))


def registered_families(tree: Tree) -> tuple[set[str], list[Finding]]:
    """Family names declared via ``Family("name", ...)`` in the registry
    (parsed, not imported, so fixture trees work)."""
    mod = tree.parse(REGISTRY)
    if mod is None:
        return set(), [Finding("metric-currency", REGISTRY, 0,
                               "metrics_registry.py missing or "
                               "unparseable")]
    names: set[str] = set()
    for node in ast.walk(mod):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Family" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            names.add(node.args[0].value)
    if not names:
        return set(), [Finding("metric-currency", REGISTRY, 0,
                               "no Family(...) declarations found in the "
                               "registry")]
    return names, []


def _string_constants(mod: ast.Module):
    """(value, lineno) for every string constant, including f-string
    literal fragments."""
    for node in ast.walk(mod):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno


def _family_of(text: str) -> str | None:
    m = _FAMILY_PREFIX_RE.match(text)
    if not m:
        return None
    return _HIST_SUFFIX_RE.sub("", m.group(1))


@rule("metric-currency")
def check_metric_currency(tree: Tree) -> list[Finding]:
    registered, findings = registered_families(tree)
    if not registered:
        return findings
    used: dict[str, tuple[str, int]] = {}   # family -> first render site
    mentioned: set[str] = set()             # any literal equal to a family
    for rel in _pkg_files(tree):
        if rel == REGISTRY:
            continue
        mod = tree.parse(rel)
        if mod is None:
            continue
        for value, lineno in _string_constants(mod):
            if value in registered:
                mentioned.add(value)
            for name in _TYPE_RE.findall(value):
                used.setdefault(name, (rel, lineno))
            fam = _family_of(value)
            if fam is not None:
                used.setdefault(fam, (rel, lineno))
        for node in ast.walk(mod):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
                fn, "id", "")
            if name in _RENDER_HELPERS and node.args and isinstance(
                    node.args[0], ast.Constant) and isinstance(
                    node.args[0].value, str):
                used.setdefault(node.args[0].value, (rel, node.lineno))
    for name in sorted(set(used) - registered):
        rel, lineno = used[name]
        findings.append(Finding(
            "metric-currency", rel, lineno,
            f"family {name!r} is rendered here but not declared in "
            f"metrics_registry.py — add a Family entry (and regenerate "
            f"docs/METRICS.md) or the family is invisible to operators"))
    for name in sorted(registered - mentioned - set(used)):
        findings.append(Finding(
            "metric-currency", REGISTRY, 0,
            f"family {name!r} is registered but appears nowhere in code — "
            f"dead registry entry (or the render path renamed it)"))
    return findings


def declared_kinds(tree: Tree) -> tuple[dict[str, str], list[Finding]]:
    """{CONSTANT_NAME: "kind-string"} declared at events.py module level."""
    mod = tree.parse(EVENTS)
    if mod is None:
        return {}, [Finding("event-kinds", EVENTS, 0,
                            "events.py missing or unparseable")]
    kinds: dict[str, str] = {}
    for node in mod.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.isupper()
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            kinds[node.targets[0].id] = node.value.value
    if not kinds:
        return {}, [Finding("event-kinds", EVENTS, 0,
                            "no event-kind constants declared in "
                            "events.py")]
    return kinds, []


@rule("event-kinds")
def check_event_kinds(tree: Tree) -> list[Finding]:
    kinds, findings = declared_kinds(tree)
    if not kinds:
        return findings
    values = set(kinds.values())
    for rel in _pkg_files(tree):
        if rel == EVENTS:
            continue
        mod = tree.parse(rel)
        if mod is None:
            continue
        for node in ast.walk(mod):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
                fn, "id", "")
            if name not in ("emit", "event_sink"):
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.Constant) and isinstance(
                    arg0.value, str):
                if arg0.value not in values:
                    findings.append(Finding(
                        "event-kinds", rel, node.lineno,
                        f"event kind {arg0.value!r} is not declared in "
                        f"events.py — declare a constant (blackbox_report "
                        f"narration and the *_events_total contract key "
                        f"off the declared set)"))
            elif isinstance(arg0, ast.Attribute):
                if arg0.attr.isupper() and arg0.attr not in kinds:
                    findings.append(Finding(
                        "event-kinds", rel, node.lineno,
                        f"event kind constant {arg0.attr} is not declared "
                        f"in events.py"))
    return findings


# -- label hygiene ----------------------------------------------------------

_PCT_CONV_RE = re.compile(r"%(?:\([^)]*\))?[-#0 +]*(?:\d+|\*)?(?:\.\d+)?"
                          r"([sdifgGeEfFxXor%])")


def _contains_escape_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            fn = n.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
                fn, "id", "")
            if name == "escape_label":
                return True
    return False


def _safe_names(fn: ast.AST) -> set[str]:
    """Local names assigned from escape_label(...) or constant-only
    expressions anywhere in the function."""
    safe: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            if _expr_safe(node.value, safe):
                safe.add(node.targets[0].id)
    return safe


def _expr_safe(node: ast.AST, safe_names: set[str]) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in safe_names
    if isinstance(node, ast.IfExp):
        return (_expr_safe(node.body, safe_names)
                and _expr_safe(node.orelse, safe_names))
    if isinstance(node, ast.BoolOp):
        return all(_expr_safe(v, safe_names) for v in node.values)
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
            fn, "id", "")
        if name in ("escape_label", "int", "float", "len"):
            return True
    return _contains_escape_call(node)


def _render_modules(tree: Tree) -> list[str]:
    out = []
    for rel in _pkg_files(tree):
        if rel in TRUSTED_RENDERERS:
            continue
        mod = tree.parse(rel)
        if mod is None:
            continue
        if any("# TYPE " in v for v, _ in _string_constants(mod)):
            out.append(rel)
    return out


@rule("label-hygiene")
def check_label_hygiene(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    modules = _render_modules(tree)
    if not modules:
        findings.append(Finding(
            "label-hygiene", f"{PKG}/tracing.py", 0,
            "no exposition-rendering modules found (every render path "
            "moved?) — re-anchor this rule"))
        return findings
    for rel in modules:
        mod = tree.parse(rel)
        for fn in ast.walk(mod):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            safe = _safe_names(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.JoinedStr):
                    findings += _check_fstring(rel, node, safe)
                elif isinstance(node, ast.BinOp) and isinstance(
                        node.op, ast.Mod) and isinstance(
                        node.left, ast.Constant) and isinstance(
                        node.left.value, str):
                    findings += _check_percent(rel, node, safe)
    return findings


def _check_fstring(rel: str, node: ast.JoinedStr,
                   safe: set[str]) -> list[Finding]:
    findings = []
    values = node.values
    for i, part in enumerate(values):
        if not (isinstance(part, ast.Constant)
                and isinstance(part.value, str)
                and part.value.endswith('="')):
            continue
        if i + 1 >= len(values):
            continue
        nxt = values[i + 1]
        if not isinstance(nxt, ast.FormattedValue):
            continue
        # A numeric format spec (:.6f, :d, :g) cannot smuggle quotes.
        spec = nxt.format_spec
        if spec is not None and any(
                isinstance(v, ast.Constant) and str(v.value)[-1:] in
                "dfgGeExXo" for v in spec.values):
            continue
        if not _expr_safe(nxt.value, safe):
            findings.append(Finding(
                "label-hygiene", rel, node.lineno,
                "f-string label value interpolated without escape_label — "
                "a hostile label (embedded quote/newline) poisons the "
                "whole exposition page"))
    return findings


def _check_percent(rel: str, node: ast.BinOp,
                   safe: set[str]) -> list[Finding]:
    findings = []
    literal = node.left.value
    convs = list(_PCT_CONV_RE.finditer(literal))
    if not convs:
        return findings
    right = node.right
    elts = list(right.elts) if isinstance(right, ast.Tuple) else [right]
    arg_i = 0
    for m in convs:
        conv = m.group(1)
        if conv == "%":
            continue
        label_pos = literal[max(0, m.start() - 2):m.start()] == '="'
        if label_pos and conv in ("s", "r"):
            if arg_i < len(elts) and not _expr_safe(elts[arg_i], safe):
                findings.append(Finding(
                    "label-hygiene", rel, node.lineno,
                    "%-format label value interpolated without "
                    "escape_label — a hostile label poisons the whole "
                    "exposition page"))
        arg_i += 1
    return findings
