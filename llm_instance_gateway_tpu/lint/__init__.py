"""Repo-invariant linter: machine-checked contracts for hand-maintained seams.

Eight PRs of growth left the gateway held together by conventions that only
same-RNG diff tests and reviewer memory enforced: the advisor-seam ordering
in both scheduler paths, the native FFI ABI (which drifted once already —
PR 7 retrofitted the ``lig_abi_version()`` handshake after the fact), the
lock discipline PR 6 established by moving RNG/note_pick outside the call
lock, and the metric-family/event-kind/exposition contracts threaded across
five files per family.  This package pins those invariants mechanically so
the next refactor can move freely without re-breaking PRs 1-8.

Run: ``python -m llm_instance_gateway_tpu.lint`` (or ``make lint``).
Exit 0 = clean; every finding prints as ``path:line: [rule] message``.

Rules (each module documents its invariant, the PR that established it,
and what breaking it costs — see ARCHITECTURE.md "correctness tooling"):

========================  ===================================================
``seam-order``            advisor filters run policy -> fairness -> placement,
                          all BEFORE prefix tie-break / RNG (PR 4/7/8)
``lock-discipline``       no hashing/RNG/note_*/blocking work inside the
                          native scheduler's call lock; no sync sleep/HTTP in
                          proxy coroutines (PR 6)
``abi-drift``             scheduler.cc extern "C" signatures == the ctypes
                          marshals, and any signature change bumps
                          ``lig_abi_version()`` + the checked-in baseline
``metric-currency``       every family name in a render path is registered in
                          metrics_registry.py and vice versa (PR 3)
``event-kinds``           every journal/emit kind literal is declared in
                          events.py (PR 3)
``label-hygiene``         exposition lines built by f-string/%-format escape
                          label values via ``escape_label`` (PR 2)
``flag-docs``             every bootstrap.py ``add_argument`` flag is
                          documented in README.md or ARCHITECTURE.md
``usage-conservation``    per-adapter step-second charges always charge the
                          engine-wall denominator at the same site, and only
                          server/usage.py writes the accumulator tables (PR 5)
``ownership``             every lock-constructing class and every post-init
                          shared-field rebind is declared in
                          concurrency_registry.py with a discipline and a
                          writer allowlist (ISSUE 13)
``publish-by-swap``       fields read lock-free on the pick hot path are only
                          ever REPLACED whole, never mutated in place (the
                          _noisy_pods_cache tuple-swap idiom, checked)
``lock-order``            the interprocedural lock-acquisition graph (with
                          blocks + call edges via the registry BINDINGS) is
                          acyclic and never re-enters a non-reentrant lock;
                          completeness cross-checked at runtime by
                          lockwitness.py
``mech-*``                mechanical layer (ruff-equivalent fallback): unused
                          imports, mutable default arguments
========================  ===================================================

Suppression: a source line containing ``lig-lint: ignore`` (optionally
``lig-lint: ignore[rule-a,rule-b]``) suppresses findings anchored to that
line.  Grandfathered findings live in ``lint-baseline.json`` at the repo
root; ``tests/test_lint.py`` asserts the baseline never grows (it is empty
at HEAD — every rule went in clean).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Callable, Iterable

# The package under lint.  Rules resolve every path relative to a Tree root
# so the test suite can point them at fixture mini-repos.
PKG = "llm_instance_gateway_tpu"

_IGNORE_RE = re.compile(r"lig-lint:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str      # repo-relative, forward slashes
    line: int      # 1-indexed; 0 = file-level
    message: str

    def fingerprint(self) -> str:
        """Line-number-free identity (baseline entries survive edits that
        only shift lines)."""
        return f"{self.rule}|{self.path}|{self.message}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Tree:
    """A lint target rooted at a directory, with cached sources and ASTs."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._src: dict[str, str | None] = {}
        self._ast: dict[str, ast.Module | None] = {}

    def path(self, rel: str) -> str:
        return os.path.join(self.root, *rel.split("/"))

    def exists(self, rel: str) -> bool:
        return os.path.exists(self.path(rel))

    def read(self, rel: str) -> str | None:
        if rel not in self._src:
            try:
                with open(self.path(rel), encoding="utf-8") as fh:
                    self._src[rel] = fh.read()
            except OSError:
                self._src[rel] = None
        return self._src[rel]

    def parse(self, rel: str) -> ast.Module | None:
        if rel not in self._ast:
            src = self.read(rel)
            if src is None:
                self._ast[rel] = None
            else:
                try:
                    self._ast[rel] = ast.parse(src, filename=rel)
                except SyntaxError:
                    self._ast[rel] = None
        return self._ast[rel]

    def py_files(self, *rel_dirs: str, exclude: tuple[str, ...] = ()
                 ) -> list[str]:
        """Repo-relative .py paths under ``rel_dirs``, sorted."""
        out: list[str] = []
        for rel_dir in rel_dirs:
            base = self.path(rel_dir)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    rel = os.path.relpath(
                        os.path.join(dirpath, fn), self.root
                    ).replace(os.sep, "/")
                    if any(rel.startswith(e) for e in exclude):
                        continue
                    out.append(rel)
        return sorted(set(out))

    def suppressed(self, finding: Finding) -> bool:
        src = self.read(finding.path)
        if src is None or finding.line <= 0:
            return False
        lines = src.splitlines()
        if finding.line > len(lines):
            return False
        m = _IGNORE_RE.search(lines[finding.line - 1])
        if not m:
            return False
        if m.group(1) is None:
            return True
        rules = {r.strip() for r in m.group(1).split(",")}
        return finding.rule in rules


RuleFn = Callable[[Tree], "list[Finding]"]

# Populated by the rule modules at import time (order = report order).
RULES: list[tuple[str, RuleFn]] = []


def rule(name: str) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        RULES.append((name, fn))
        return fn
    return deco


def _load_rules() -> None:
    # Import for registration side effects; idempotent (modules cache).
    from llm_instance_gateway_tpu.lint import (  # noqa: F401
        abi, concurrency, contracts, exposition, mechanical, seams,
    )


def load_baseline(tree: Tree) -> set[str]:
    src = tree.read("lint-baseline.json")
    if src is None:
        return set()
    try:
        doc = json.loads(src)
    except ValueError:
        return set()
    return set(doc.get("grandfathered", []))


def run(root: str, rules: Iterable[str] | None = None,
        apply_baseline: bool = True) -> list[Finding]:
    """All unsuppressed, unbaselined findings for the tree at ``root``."""
    findings, _ = run_timed(root, rules=rules, apply_baseline=apply_baseline)
    return findings


def run_timed(root: str, rules: Iterable[str] | None = None,
              apply_baseline: bool = True
              ) -> tuple[list[Finding], dict[str, float]]:
    """``run`` plus per-rule wall seconds — CI logs print the table so a
    rule that turns quadratic shows up as a number, not as "lint feels
    slow" (the interprocedural lock-order graph is the obvious suspect to
    watch as the package grows)."""
    import time

    _load_rules()
    tree = Tree(root)
    wanted = set(rules) if rules is not None else None
    baseline = load_baseline(tree) if apply_baseline else set()
    findings: list[Finding] = []
    timings: dict[str, float] = {}
    for name, fn in RULES:
        if wanted is not None and name not in wanted:
            continue
        t0 = time.perf_counter()
        found = fn(tree)
        timings[name] = timings.get(name, 0.0) + time.perf_counter() - t0
        for f in found:
            if f.fingerprint() in baseline:
                continue
            if tree.suppressed(f):
                continue
            findings.append(f)
    return findings, timings


def repo_root() -> str:
    """The checkout root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(
        os.path.dirname(__file__))))
