"""Mechanical layer: the ruff-equivalent checks (``mech-*`` rules).

The committed ``pyproject.toml`` configures ruff (F401 unused imports, F811
redefinitions, F821 undefined names, B006 mutable default arguments) and
``make lint`` runs it when the binary exists.  The container images this
repo grows on do not all ship ruff, so the two highest-value checks are
reimplemented here as a fallback — the invariant linter must not silently
lose its mechanical layer on a machine without the tool:

- ``mech-unused-import``: an import bound but never referenced (module
  ``__init__.py`` re-export files are exempt, matching the ruff per-file
  ignore; ``# noqa`` on the import line is honored).
- ``mech-mutable-default``: a list/dict/set literal (or constructor call)
  as a parameter default — shared across calls, the classic aliasing bug.
"""

from __future__ import annotations

import ast
import re

from llm_instance_gateway_tpu.lint import PKG, Finding, Tree, rule

_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _scan_targets(tree: Tree) -> list[str]:
    # Same scope ruff scans (pyproject.toml): the package, tools/, tests/,
    # bench.py — the two layers must agree on what "clean" means, or a
    # ruff-less host certifies a tree a ruff-ful CI then rejects.
    files = [f for f in tree.py_files(PKG, "tools", "tests",
                                      exclude=(f"{PKG}/lint/",))
             if not f.endswith("_pb2.py")          # generated protobuf
             and not f.endswith("_pb2_grpc.py")]
    if tree.exists("bench.py"):
        files.append("bench.py")
    return files


def _noqa_lines(src: str) -> set[int]:
    return {i for i, line in enumerate(src.splitlines(), 1)
            if "# noqa" in line}


def _annotation_idents(mod: ast.Module) -> set[str]:
    """Identifiers inside string annotations (``from __future__ import
    annotations`` keeps real names as AST, but quoted forward refs are
    plain strings)."""
    idents: set[str] = set()

    def from_node(node: ast.AST | None) -> None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            idents.update(_IDENT_RE.findall(node.value))

    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for arg in (list(node.args.args) + list(node.args.posonlyargs)
                        + list(node.args.kwonlyargs)
                        + [node.args.vararg, node.args.kwarg]):
                if arg is not None:
                    from_node(arg.annotation)
            from_node(node.returns)
        elif isinstance(node, ast.AnnAssign):
            from_node(node.annotation)
    return idents


@rule("mech-unused-import")
def check_unused_imports(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    for rel in _scan_targets(tree):
        if rel.endswith("__init__.py"):
            continue  # re-export surface (ruff: per-file-ignores F401)
        src = tree.read(rel)
        mod = tree.parse(rel)
        if src is None or mod is None:
            continue
        noqa = _noqa_lines(src)
        bound: list[tuple[str, int]] = []  # (name, lineno)
        for node in ast.walk(mod):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    bound.append((name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound.append((alias.asname or alias.name, node.lineno))
        if not bound:
            continue
        used: set[str] = set()
        for node in ast.walk(mod):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # base resolves through a Name node anyway
        used |= _annotation_idents(mod)
        # __all__ entries count as use (re-export).
        for node in ast.walk(mod):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in node.targets)
                    and isinstance(node.value, (ast.List, ast.Tuple))):
                for el in node.value.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                            el.value, str):
                        used.add(el.value)
        for name, lineno in bound:
            if name in used or lineno in noqa:
                continue
            findings.append(Finding(
                "mech-unused-import", rel, lineno,
                f"{name!r} imported but unused"))
    return findings


_MUTABLE_CTORS = {"list", "dict", "set"}


@rule("mech-mutable-default")
def check_mutable_defaults(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    for rel in _scan_targets(tree):
        src = tree.read(rel)
        mod = tree.parse(rel)
        if src is None or mod is None:
            continue
        noqa = _noqa_lines(src)
        for fn in ast.walk(mod):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            defaults = list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None]
            for d in defaults:
                mutable = isinstance(d, (ast.List, ast.Dict, ast.Set,
                                         ast.ListComp, ast.DictComp,
                                         ast.SetComp)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in _MUTABLE_CTORS)
                if mutable and d.lineno not in noqa:
                    findings.append(Finding(
                        "mech-mutable-default", rel, d.lineno,
                        f"{fn.name}: mutable default argument (shared "
                        f"across calls) — default to None and build "
                        f"inside"))
    return findings
