"""Attention ops: GQA prefill (causal) and cached decode, XLA reference path.

Shapes follow the grouped-query layout throughout: queries [.., n_kv, q_per_kv,
head_dim] so the KV heads never need materialized repetition (a bf16
``jnp.repeat`` of KV to 32 heads would burn HBM bandwidth for nothing — the
einsum contracts directly against the grouped axis and XLA tiles it onto the
MXU).

Softmax runs in f32 with max-subtraction.  The Pallas flash/paged kernels in
``ops.pallas`` are drop-in replacements for long context on real TPU; these
XLA versions are the correctness reference and the CPU test path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax NaN-free


def xla_chunk_attention(
    q: jax.Array,        # [B, C, n_heads, hd] — chunk at contiguous positions
    k_cache: jax.Array,  # [B, S_max, n_kv, hd] — incl. the chunk's own KV
    v_cache: jax.Array,
    start,               # scalar int32: global position of chunk token 0
) -> jax.Array:
    """Chunk-vs-cache attention reference: chunk token i (global position
    start+i) attends cache positions <= start+i.  The chunk-stream prefill
    hot op; ``pallas_attention.chunk_attention`` auto-dispatches between
    this and the flash-style kernel.  Returns [B, C, n_heads, hd]."""
    b, c, n_heads, hd = q.shape
    s_max, n_kv = k_cache.shape[1], k_cache.shape[2]
    g = n_heads // n_kv
    qg = q.reshape(b, c, n_kv, g, hd)
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    logits = jnp.einsum("bikgh,bjkh->bkgij", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    q_pos = jnp.asarray(start, jnp.int32) + jnp.arange(c)
    mask = jnp.arange(s_max)[None, :] <= q_pos[:, None]  # [C, S]
    logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgij,bjkh->bikgh", probs, v_cache)
    return out.reshape(b, c, n_heads, hd)


def gather_pool_rows(pool: jax.Array, tables: jax.Array) -> jax.Array:
    """Paged-pool gather: ``[n_blocks+1, P, ...] x [B, M] -> [B, M*P, ...]``
    — each table row's physical blocks concatenated into the contiguous
    lane view.  Rank-generic (scale pools ``[n_blocks+1, P, K]`` included).
    The ONE definition of the pool->lane read; models/paged.py, the paged
    kernel's fallback, the on-chip check, and the tests all share it."""
    g = pool[tables]  # [B, M, P, ...]
    return g.reshape(g.shape[0], g.shape[1] * g.shape[2], *g.shape[3:])


def _grouped(q: jax.Array, n_kv_heads: int) -> jax.Array:
    """[.., n_heads, hd] -> [.., n_kv, q_per_kv, hd]."""
    *lead, n_heads, hd = q.shape
    return q.reshape(*lead, n_kv_heads, n_heads // n_kv_heads, hd)


def prefill_attention(
    q: jax.Array,  # [B, S, n_heads, hd]
    k: jax.Array,  # [B, S, n_kv, hd]
    v: jax.Array,  # [B, S, n_kv, hd]
    positions: jax.Array | None = None,  # [B, S] for packed/padded masking
) -> jax.Array:
    """Causal self-attention over a full prompt.  Returns [B, S, n_heads, hd].

    With ``positions`` given, token i attends to j iff positions[j] <=
    positions[i] AND j <= i — correct for right-padded and left-packed
    batches alike.
    """
    b, s, n_heads, hd = q.shape
    n_kv = k.shape[2]
    qg = _grouped(q, n_kv)  # [B,S,K,G,hd]
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    # [B,K,G,S,S]
    logits = jnp.einsum("bikgh,bjkh->bkgij", qg, k, preferred_element_type=jnp.float32)
    logits *= scale
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    mask = causal[None, None, None]
    if positions is not None:
        valid = positions[:, None, :] <= positions[:, :, None]  # [B,S_i,S_j]
        mask = mask & valid[:, None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgij,bjkh->bikgh", probs, v)
    return out.reshape(b, s, n_heads, hd)


def decode_attention(
    q: jax.Array,        # [B, n_heads, hd] — one new token per sequence
    k_cache: jax.Array,  # [B, S_max, n_kv, hd]
    v_cache: jax.Array,  # [B, S_max, n_kv, hd]
    lengths: jax.Array,  # [B] valid tokens per sequence (including current)
) -> jax.Array:
    """Single-step cached attention.  Returns [B, n_heads, hd].

    Reads the whole static-shaped cache and masks positions >= lengths —
    no dynamic shapes, so one compilation serves every step.  This read is
    the HBM-bound hot loop of decode; the Pallas paged kernel replaces it
    on TPU for large S_max.
    """
    b, s_max, n_kv, hd = k_cache.shape
    qg = _grouped(q, n_kv)  # [B,K,G,hd]
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    logits = jnp.einsum("bkgh,bskh->bkgs", qg, k_cache, preferred_element_type=jnp.float32)
    logits *= scale
    valid = jnp.arange(s_max)[None] < lengths[:, None]  # [B,S]
    logits = jnp.where(valid[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgs,bskh->bkgh", probs, v_cache)
    return out.reshape(b, n_kv * (q.shape[1] // n_kv), hd)
