"""Pallas attention kernels under GSPMD meshes, via ``jax.shard_map``.

GSPMD cannot partition an opaque ``pallas_call`` — before this module, any
serving mesh with size > 1 silently dropped the flash-prefill and
cached-decode kernels and fell back to XLA attention, exactly where the big
models run (tensor-parallel v5e-8+).  ``shard_map`` is the standard
composition fix: it splits the arrays along the mesh axes OUTSIDE the kernel,
runs the unmodified single-device kernel on each shard's local block, and
lets the surrounding jitted program keep its GSPMD shardings.

Both attention ops are embarrassingly parallel over (batch, kv-head-group):

- flash prefill   [B, S, H, hd] x [B, S, K, hd]: every (batch row, head)
  pair is independent — shard batch over ``data`` and heads over ``tensor``.
- cached decode   [B, H, hd] x [B, S_max, K, hd]: same, with the cache's
  kv-head axis sharded to match (the engine already lays the cache out this
  way, ``parallel/sharding.cache_specs``).

so the shard-local call IS the global computation restricted to the local
heads/rows: no collectives are needed inside the kernel, and the psum that
tensor parallelism requires stays where it always was — in the ``wo``
projection AFTER attention (Megatron pattern, ``parallel/sharding.py``).

GQA head split: shard_map splits the head axis into equal contiguous blocks,
so sharding is group-aligned iff ``tensor`` divides ``n_kv_heads`` (each
device gets whole KV groups) — or ``n_kv_heads == 1`` (MQA: the single KV
head is replicated, every device's local query heads all map to it).
``mesh_supports`` gates on exactly that; unsupported layouts keep the XLA
fallback.

Reference parity note: the reference (kubernetes-sigs/llm-instance-gateway)
delegates all accelerator work to vLLM (SURVEY.md §2); this module is part
of the model-server half this repo owns.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from llm_instance_gateway_tpu.models.configs import ModelConfig

# Test hook: force the Pallas kernels in interpret mode even off-TPU, so the
# virtual-CPU-mesh suite certifies the KERNEL path (not the XLA fallback the
# auto-dispatch would pick on CPU).
FORCE_INTERPRET = False


def mesh_supports(cfg: ModelConfig, mesh: Mesh) -> bool:
    """True iff the Pallas kernels can run shard-local on this mesh.

    Requirements (see module docstring): the ``tensor`` split must be
    group-aligned for GQA, and query heads must split evenly.  ``data``
    needs no gate — batch axes that don't divide simply replicate (specs
    are chosen per call-site shape).
    """
    t = mesh.shape.get("tensor", 1)
    if t == 1:
        return True
    if cfg.n_heads % t != 0:
        return False
    return cfg.n_kv_heads == 1 or cfg.n_kv_heads % t == 0


def _batch_axis(b: int, mesh: Mesh) -> str | None:
    """Shard batch over ``data`` when it divides; replicate otherwise
    (single-prompt prefill has B=1 — correct either way, GSPMD-free)."""
    dp = mesh.shape.get("data", 1)
    return "data" if dp > 1 and b % dp == 0 else None


def _head_axes(n_kv: int, mesh: Mesh) -> tuple[str | None, str | None]:
    """(q-head axis, kv-head axis) specs for the tensor split."""
    t = mesh.shape.get("tensor", 1)
    if t == 1:
        return None, None
    # MQA: replicate the single KV head; query heads still split — each
    # device's local heads all belong to group 0.
    return "tensor", ("tensor" if n_kv % t == 0 else None)


def make_flash_prefill(cfg: ModelConfig, mesh: Mesh):
    """Returns ``attention_fn(q, k, v, positions)`` for ``transformer.prefill``.

    Same contract as ``pallas_attention.flash_attention``: causal,
    right-padded batches only (positions are ignored — causality alone keeps
    real positions exact; pad rows are garbage the caller masks).  Inside
    each shard the auto-dispatching entry still falls back to XLA for
    shapes that miss the tiling constraints, so tiny test models remain
    correct under the same code path.
    """
    from llm_instance_gateway_tpu.ops.pallas_attention import flash_attention

    if not mesh_supports(cfg, mesh):
        raise ValueError(
            "mesh tensor split is not group-aligned for "
            f"H={cfg.n_heads}/K={cfg.n_kv_heads} (mesh {dict(mesh.shape)}); "
            "a shard-local kernel would mis-map query heads to KV groups — "
            "gate call sites on mesh_supports()")

    def attention_fn(q, k, v, positions):
        del positions
        db = _batch_axis(q.shape[0], mesh)
        qh, kh = _head_axes(k.shape[2], mesh)
        q_spec = P(db, None, qh, None)     # [B, S, H, hd]
        kv_spec = P(db, None, kh, None)    # [B, S, K, hd]

        def local(q, k, v):
            return flash_attention(q, k, v, interpret=FORCE_INTERPRET)

        return jax.shard_map(
            local, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec),
            out_specs=q_spec, check_vma=False,
        )(q, k, v)

    return attention_fn


def make_cached_decode(cfg: ModelConfig, mesh: Mesh):
    """Returns ``attention_fn(q, k_cache, v_cache, lengths)`` for
    ``transformer.decode_step``.

    The per-layer cache inside the scan is [B, S_max, K, hd] with kv heads
    sharded over ``tensor`` and batch over ``data`` — the same layout
    ``cache_specs`` commits, so shard_map's split is a no-op reshard on the
    hot loop.
    """
    from llm_instance_gateway_tpu.ops.pallas_decode_attention import (
        decode_attention,
    )

    if not mesh_supports(cfg, mesh):
        raise ValueError(
            "mesh tensor split is not group-aligned for "
            f"H={cfg.n_heads}/K={cfg.n_kv_heads} (mesh {dict(mesh.shape)}); "
            "a shard-local kernel would mis-map query heads to KV groups — "
            "gate call sites on mesh_supports()")

    def attention_fn(q, k_cache, v_cache, lengths):
        db = _batch_axis(q.shape[0], mesh)
        qh, kh = _head_axes(k_cache.shape[2], mesh)
        q_spec = P(db, qh, None)             # [B, H, hd]
        kv_spec = P(db, None, kh, None)      # [B, S_max, K, hd]
        len_spec = P(db)                     # [B]

        def local(q, kc, vc, lens):
            return decode_attention(q, kc, vc, lens,
                                    interpret=FORCE_INTERPRET)

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec, len_spec),
            out_specs=q_spec, check_vma=False,
        )(q, k_cache, v_cache, lengths)

    return attention_fn
