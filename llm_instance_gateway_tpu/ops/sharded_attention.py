"""Pallas attention kernels under GSPMD meshes, via ``jax.shard_map``.

GSPMD cannot partition an opaque ``pallas_call`` — before this module, any
serving mesh with size > 1 silently dropped the flash-prefill and
cached-decode kernels and fell back to XLA attention, exactly where the big
models run (tensor-parallel v5e-8+).  ``shard_map`` is the standard
composition fix: it splits the arrays along the mesh axes OUTSIDE the kernel,
runs the unmodified single-device kernel on each shard's local block, and
lets the surrounding jitted program keep its GSPMD shardings.

Both attention ops are embarrassingly parallel over (batch, kv-head-group):

- flash prefill   [B, S, H, hd] x [B, S, K, hd]: every (batch row, head)
  pair is independent — shard batch over ``data`` and heads over ``tensor``.
- cached decode   [B, H, hd] x [B, S_max, K, hd]: same, with the cache's
  kv-head axis sharded to match (the engine already lays the cache out this
  way, ``parallel/sharding.cache_specs``).

so the shard-local call IS the global computation restricted to the local
heads/rows: no collectives are needed inside the kernel, and the psum that
tensor parallelism requires stays where it always was — in the ``wo``
projection AFTER attention (Megatron pattern, ``parallel/sharding.py``).

GQA head split: shard_map splits the head axis into equal contiguous blocks,
so sharding is group-aligned iff ``tensor`` divides ``n_kv_heads`` (each
device gets whole KV groups) — or ``n_kv_heads == 1`` (MQA: the single KV
head is replicated, every device's local query heads all map to it).
``mesh_supports`` gates on exactly that; unsupported layouts keep the XLA
fallback.

Reference parity note: the reference (kubernetes-sigs/llm-instance-gateway)
delegates all accelerator work to vLLM (SURVEY.md §2); this module is part
of the model-server half this repo owns.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from llm_instance_gateway_tpu.models.configs import ModelConfig

# Test hook: force the Pallas kernels in interpret mode even off-TPU, so the
# virtual-CPU-mesh suite certifies the KERNEL path (not the XLA fallback the
# auto-dispatch would pick on CPU).
FORCE_INTERPRET = False


def mesh_supports(cfg: ModelConfig, mesh: Mesh) -> bool:
    """True iff the Pallas kernels can run shard-local on this mesh.

    Requirements (see module docstring): the ``tensor`` split must be
    group-aligned for GQA, and query heads must split evenly.  ``data``
    needs no gate — batch axes that don't divide simply replicate (specs
    are chosen per call-site shape).
    """
    t = mesh.shape.get("tensor", 1)
    if t == 1:
        return True
    if cfg.n_heads % t != 0:
        return False
    return cfg.n_kv_heads == 1 or cfg.n_kv_heads % t == 0


def _batch_axis(b: int, mesh: Mesh) -> str | None:
    """Shard batch over ``data`` when it divides; replicate otherwise
    (single-prompt prefill has B=1 — correct either way, GSPMD-free)."""
    dp = mesh.shape.get("data", 1)
    return "data" if dp > 1 and b % dp == 0 else None


def _head_axes(n_kv: int, mesh: Mesh) -> tuple[str | None, str | None]:
    """(q-head axis, kv-head axis) specs for the tensor split."""
    t = mesh.shape.get("tensor", 1)
    if t == 1:
        return None, None
    # MQA: replicate the single KV head; query heads still split — each
    # device's local heads all belong to group 0.
    return "tensor", ("tensor" if n_kv % t == 0 else None)


def _require_group_aligned(cfg: ModelConfig, mesh: Mesh) -> None:
    if not mesh_supports(cfg, mesh):
        raise ValueError(
            "mesh tensor split is not group-aligned for "
            f"H={cfg.n_heads}/K={cfg.n_kv_heads} (mesh {dict(mesh.shape)}); "
            "a shard-local kernel would mis-map query heads to KV groups — "
            "gate call sites on mesh_supports()")


def make_flash_prefill(cfg: ModelConfig, mesh: Mesh):
    """Returns ``attention_fn(q, k, v, positions)`` for ``transformer.prefill``.

    Same contract as ``pallas_attention.flash_attention``: causal,
    right-padded batches only (positions are ignored — causality alone keeps
    real positions exact; pad rows are garbage the caller masks).  Inside
    each shard the auto-dispatching entry still falls back to XLA for
    shapes that miss the tiling constraints, so tiny test models remain
    correct under the same code path.
    """
    from llm_instance_gateway_tpu.ops.pallas_attention import flash_attention

    _require_group_aligned(cfg, mesh)

    def attention_fn(q, k, v, positions):
        del positions
        db = _batch_axis(q.shape[0], mesh)
        qh, kh = _head_axes(k.shape[2], mesh)
        q_spec = P(db, None, qh, None)     # [B, S, H, hd]
        kv_spec = P(db, None, kh, None)    # [B, S, K, hd]

        def local(q, k, v):
            return flash_attention(q, k, v, interpret=FORCE_INTERPRET)

        return jax.shard_map(
            local, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec),
            out_specs=q_spec, check_vma=False,
        )(q, k, v)

    return attention_fn


def _make_decode_wrapper(cfg: ModelConfig, mesh: Mesh, quant: bool):
    """Shared builder for the two cached-decode wrappers: the spec layout
    and shard_map scaffolding are identical; ``quant`` adds the [B, S, K]
    scale operands and swaps in the int8-aware kernel."""
    from llm_instance_gateway_tpu.ops import pallas_decode_attention as pda

    _require_group_aligned(cfg, mesh)

    def attention_fn(q, k_cache, v_cache, *rest):
        *scales, lengths = rest
        db = _batch_axis(q.shape[0], mesh)
        qh, kh = _head_axes(k_cache.shape[2], mesh)
        q_spec = P(db, qh, None)             # [B, H, hd]
        kv_spec = P(db, None, kh, None)      # [B, S_max, K, hd]
        sc_spec = P(db, None, kh)            # [B, S_max, K] f32 scales
        len_spec = P(db)                     # [B]
        kernel = pda.decode_attention_quant if quant else pda.decode_attention

        def local(q, kc, vc, *rest):
            return kernel(q, kc, vc, *rest, interpret=FORCE_INTERPRET)

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec,
                      *([sc_spec, sc_spec] if quant else []), len_spec),
            out_specs=q_spec, check_vma=False,
        )(q, k_cache, v_cache, *scales, lengths)

    attention_fn.quant_aware = quant
    return attention_fn


def make_cached_decode(cfg: ModelConfig, mesh: Mesh):
    """Returns ``attention_fn(q, k_cache, v_cache, lengths)`` for
    ``transformer.decode_step``.

    The per-layer cache inside the scan is [B, S_max, K, hd] with kv heads
    sharded over ``tensor`` and batch over ``data`` — the same layout
    ``cache_specs`` commits, so shard_map's split is a no-op reshard on the
    hot loop.
    """
    return _make_decode_wrapper(cfg, mesh, quant=False)


def make_cached_decode_quant(cfg: ModelConfig, mesh: Mesh):
    """Quantized ``make_cached_decode``: ``attention_fn(q, k_cache,
    v_cache, k_scale, v_scale, lengths)`` where the shard-local call is
    the int8-aware kernel, so tensor-parallel int8 engines keep the kernel
    win AND the bandwidth win together (VERDICT r4 weak #4) — each shard
    streams its local int8 cache block plus [B, S, K_local] scales and
    dequantizes in VMEM at the MXU feed.  The pre-existing alternative
    (dequantize then hand a bf16 view to an opaque wrapper) would
    materialize a full bf16 cache per layer per step, spending exactly the
    HBM bandwidth int8 exists to save.

    The returned function is tagged ``quant_aware`` so
    ``transformer.decode_step`` routes raw int8 + scales to it instead of
    a dequantized view.
    """
    return _make_decode_wrapper(cfg, mesh, quant=True)
