"""Core numeric ops: norms, rotary embeddings, attention, LoRA deltas.

XLA-level reference implementations live here (the compiler fuses these
aggressively on TPU); hand-written Pallas kernels for the genuinely
bandwidth-bound paths live in ``ops.pallas`` with CPU-safe fallbacks.
"""
