"""Pallas TPU kernel for cached decode attention.

Decode attention reads the whole static KV cache every step — the HBM-bound
inner loop of serving.  The XLA reference (``ops.attention.decode_attention``)
materializes [B, K, G, S] logits between two einsums; this kernel streams the
cache in blocks with the online-softmax recurrence, keeping per-program state
in VMEM: one grid cell per (batch row, KV head) computes that head group's
output for the row's single query token.

Length masking is exact (positions >= length contribute nothing), matching
the engine's garbage-tail cache contract.  ``decode_attention`` is the
auto-dispatching entry with the XLA fallback for unsupported shapes/CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_instance_gateway_tpu.ops.attention import (
    decode_attention as xla_decode,
    gather_pool_rows,
)

NEG_INF = -1e30

# Platforms whose default backend runs Mosaic TPU lowering ("axon" is this
# image's tunneled-TPU plugin).  Anything else falls back to the XLA path.
TPU_BACKENDS = ("tpu", "axon")


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, *refs,
                   block_s: int, scale: float, quant: bool):
    # q_ref: [1, K, G, hd]; k_ref/v_ref: [1, block_s, K*hd] — ALL heads of
    # one S-tile per grid step (head fusion keeps the grid small: per-step
    # overhead, not bandwidth, dominated the per-head variant on chip);
    # len_ref: [B] (SMEM, scalar-prefetched).  The S-block axis is the
    # innermost grid dim with "arbitrary" semantics: online-softmax state
    # rides f32 VMEM scratch across the sweep, like the prefill flash kernel.
    # ``quant``: K/V tiles arrive int8 with per-(position, kv-head) f32
    # scale columns (ks_ref/vs_ref: [1, block_s, n_kv]); dequantization
    # happens in VMEM right before the MXU feed, so HBM streams half the
    # bytes of the bf16 variant — decode's actual bound.
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    n_kv, g, hd = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    bi = pl.program_id(0)
    sb = pl.program_id(1)
    n_sb = pl.num_programs(1)
    length = len_ref[bi]
    start = sb * block_s

    @pl.when(sb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Blocks entirely past `length` do nothing (their DMA is elided too —
    # the index map revisits the last live tile); the straddling block masks.
    @pl.when(start < length)
    def _compute():
        pos = start + jax.lax.broadcasted_iota(jnp.int32, (g, block_s), 1)
        live = pos < length
        for kh in range(n_kv):  # unrolled: static head offsets into the tile
            q = q_ref[0, kh]  # [G, hd]
            k = k_ref[0, :, kh * hd:(kh + 1) * hd]
            v = v_ref[0, :, kh * hd:(kh + 1) * hd]
            if quant:
                k = (k.astype(jnp.float32) * ks_ref[0, :, kh:kh + 1]).astype(q.dtype)
                v = (v.astype(jnp.float32) * vs_ref[0, :, kh:kh + 1]).astype(q.dtype)
            # else: K/V stay in their storage dtype — the MXU consumes bf16
            # directly with f32 accumulation; an explicit astype of every
            # tile was pure VPU overhead (measured on chip).
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, BS] f32
            s = jnp.where(live, s, NEG_INF)
            m_prev = m_scr[kh, :, :1]
            l_prev = l_scr[kh, :, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_scr[kh] = jnp.broadcast_to(
                l_prev * corr + p.sum(axis=-1, keepdims=True),
                l_scr.shape[1:])
            acc_scr[kh] = acc_scr[kh] * corr + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_scr[kh] = jnp.broadcast_to(m_new, m_scr.shape[1:])

    @pl.when(sb == n_sb - 1)
    def _finalize():
        # Rows with length == 0 never accumulate (l stays 0) and emit zeros;
        # the engine treats such slots as garbage either way.
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[:, :, :1], 1e-30)
        ).astype(o_ref.dtype)


def _pick_block(s_max: int) -> int:
    for bs in (512, 256, 128):
        if s_max % bs == 0:
            return bs
    return 0


def _pallas_decode_call(q, k_cache, v_cache, scales, lengths,
                        block_s: int | None, interpret: bool) -> jax.Array:
    """Shared pallas_call builder for the bf16 and int8 variants —
    ``scales`` is None (bf16) or (k_scale, v_scale) [B, S, n_kv] f32."""
    b, n_heads, hd = q.shape
    s_max, n_kv = k_cache.shape[1], k_cache.shape[2]
    g = n_heads // n_kv
    scale = float(1.0 / (hd ** 0.5))
    if block_s is None:
        block_s = _pick_block(s_max)
    qg = q.reshape(b, n_kv, g, hd)
    # Mosaic requires the last two block dims be (8k, 128k)-aligned or full;
    # a [1, S, 1, hd] head slice of the 4-D cache violates that.  View the
    # cache as [B, S, K*hd] instead — contiguous, so the reshape is free —
    # and slice heads as static lane columns inside the kernel.
    k2 = k_cache.reshape(b, s_max, n_kv * hd)
    v2 = v_cache.reshape(b, s_max, n_kv * hd)

    def kv_index(bi, sb, lens, block_s=block_s):
        # Clamp dead S-blocks (start >= length) to the last live tile so
        # Pallas elides their HBM->VMEM copies: short rows in a long cache
        # cost bandwidth proportional to their length, not to S_max.
        last = jnp.maximum(lens[bi] - 1, 0) // block_s
        return (bi, jnp.minimum(sb, last), 0)

    quant = scales is not None
    in_specs = [
        pl.BlockSpec((1, n_kv, g, hd), lambda bi, sb, lens: (bi, 0, 0, 0)),
        pl.BlockSpec((1, block_s, n_kv * hd), kv_index),
        pl.BlockSpec((1, block_s, n_kv * hd), kv_index),
    ]
    operands = [lengths, qg, k2, v2]
    if quant:
        in_specs += [pl.BlockSpec((1, block_s, n_kv), kv_index)] * 2
        operands += list(scales)
    kernel = functools.partial(_decode_kernel, block_s=block_s, scale=scale,
                               quant=quant)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, g, hd), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # lengths: drives masking + DMA clamping
            grid=(b, s_max // block_s),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, n_kv, g, hd),
                                   lambda bi, sb, lens: (bi, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((n_kv, g, 128), jnp.float32),  # m (lane-padded)
                pltpu.VMEM((n_kv, g, 128), jnp.float32),  # l
                pltpu.VMEM((n_kv, g, hd), jnp.float32),   # o accumulator
            ],
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, n_heads, hd)


def decode_attention_pallas(
    q: jax.Array,        # [B, n_heads, hd]
    k_cache: jax.Array,  # [B, S, n_kv, hd]
    v_cache: jax.Array,
    lengths: jax.Array,  # [B] int32
    block_s: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    return _pallas_decode_call(q, k_cache, v_cache, None, lengths,
                               block_s, interpret)


def decode_attention_quant_pallas(
    q: jax.Array,        # [B, n_heads, hd]
    k_cache: jax.Array,  # [B, S, n_kv, hd] int8
    v_cache: jax.Array,
    k_scale: jax.Array,  # [B, S, n_kv] f32
    v_scale: jax.Array,
    lengths: jax.Array,  # [B] int32
    block_s: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    return _pallas_decode_call(q, k_cache, v_cache, (k_scale, v_scale),
                               lengths, block_s, interpret)


def supports(s_max: int, hd: int) -> bool:
    return _pick_block(s_max) != 0 and hd % 128 == 0


# ---------------------------------------------------------------------------
# Direct paged variant: block-table indirection in the index map
# ---------------------------------------------------------------------------


def _paged_kernel(len_ref, tab_ref, *rest, block_s, scale, quant):
    # Same body as the lane kernel — the logical S-block index (grid dim 1)
    # drives masking exactly as before; only the DMA source moved.  The
    # table ref is consumed by the index maps, not the body.
    del tab_ref
    _decode_kernel(len_ref, *rest, block_s=block_s, scale=scale, quant=quant)


def supports_paged(block: int, hd: int, dtype) -> bool:
    """The pool tile is one physical block: [1, block, K*hd].  Sublane dim
    = block, so it must divide the dtype's packed tiling: (8, 128) f32,
    (16, 128) bf16, (32, 128) int8."""
    sublane = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(dtype).itemsize, 32)
    return hd % 128 == 0 and block % sublane == 0


def paged_decode_attention_pallas(
    q: jax.Array,        # [B, n_heads, hd]
    k_pool: jax.Array,   # [n_blocks+1, P, n_kv, hd] (bf16 or int8)
    v_pool: jax.Array,
    tables: jax.Array,   # [B, M] int32 — physical block per logical block
    lengths: jax.Array,  # [B] int32
    k_scale: jax.Array | None = None,  # [n_blocks+1, P, n_kv] f32 (int8 mode)
    v_scale: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention DIRECTLY over the paged pool.

    The engine's original paged read gathered each row's blocks into a
    contiguous [B, S_max, K, hd] array first — materializing a second copy
    of the live cache in HBM every step (gather write + kernel read: ~2x
    the bytes decode is bound by).  Here the BLOCK TABLE rides the scalar
    prefetch (vLLM-PagedAttention's indirection, Pallas-style): the index
    map of each (row, logical-block) grid cell looks up the physical block
    and the DMA streams it straight from the pool, once.  Dead blocks
    (start >= length) clamp to the row's last live LOGICAL block — whose
    physical index the revisited map returns again, so Mosaic elides their
    copies exactly like the lane kernel.  Composes with int8 pools: scale
    columns ride the same indirection.
    """
    b, n_heads, hd = q.shape
    n_kv = k_pool.shape[2]
    block = k_pool.shape[1]
    m = tables.shape[1]
    g = n_heads // n_kv
    scale = float(1.0 / (hd ** 0.5))
    qg = q.reshape(b, n_kv, g, hd)
    k2 = k_pool.reshape(k_pool.shape[0], block, n_kv * hd)
    v2 = v_pool.reshape(v_pool.shape[0], block, n_kv * hd)

    def kv_index(bi, sb, lens, tabs, block=block):
        last = jnp.maximum(lens[bi] - 1, 0) // block
        return (tabs[bi, jnp.minimum(sb, last)], 0, 0)

    quant = k_scale is not None
    in_specs = [
        pl.BlockSpec((1, n_kv, g, hd), lambda bi, sb, lens, tabs: (bi, 0, 0, 0)),
        pl.BlockSpec((1, block, n_kv * hd), kv_index),
        pl.BlockSpec((1, block, n_kv * hd), kv_index),
    ]
    operands = [lengths, tables, qg, k2, v2]
    if quant:
        in_specs += [pl.BlockSpec((1, block, n_kv), kv_index)] * 2
        operands += [k_scale, v_scale]
    kernel = functools.partial(_paged_kernel, block_s=block, scale=scale,
                               quant=quant)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, g, hd), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # lengths (masking) + tables (DMA routing)
            grid=(b, m),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, n_kv, g, hd),
                                   lambda bi, sb, lens, tabs: (bi, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((n_kv, g, 128), jnp.float32),  # m (lane-padded)
                pltpu.VMEM((n_kv, g, 128), jnp.float32),  # l
                pltpu.VMEM((n_kv, g, hd), jnp.float32),   # o accumulator
            ],
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, n_heads, hd)


def paged_decode_attention(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    tables: jax.Array, lengths: jax.Array,
    k_scale: jax.Array | None = None, v_scale: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Auto-dispatch for the direct paged kernel: unsupported block/head
    shapes and non-TPU backends gather the row view (the pre-existing
    read) and take the lane-path dispatchers."""
    block, hd = k_pool.shape[1], k_pool.shape[3]
    quant = k_scale is not None
    if supports_paged(block, hd, k_pool.dtype) and (
        interpret or jax.default_backend() in TPU_BACKENDS
    ):
        return paged_decode_attention_pallas(
            q, k_pool, v_pool, tables, lengths, k_scale, v_scale,
            interpret=interpret)
    rows = functools.partial(gather_pool_rows, tables=tables)
    if quant:
        return decode_attention_quant(q, rows(k_pool), rows(v_pool),
                                      rows(k_scale), rows(v_scale), lengths,
                                      interpret=interpret)
    return decode_attention(q, rows(k_pool), rows(v_pool), lengths,
                            interpret=interpret)


def decode_attention(
    q: jax.Array, k_cache: jax.Array, v_cache: jax.Array, lengths: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """Auto-dispatch: Pallas kernel when shapes allow, XLA reference otherwise."""
    s_max, hd = k_cache.shape[1], k_cache.shape[3]
    if not supports(s_max, hd) or (
        not interpret and jax.default_backend() not in TPU_BACKENDS
    ):
        return xla_decode(q, k_cache, v_cache, lengths)
    return decode_attention_pallas(q, k_cache, v_cache, lengths, interpret=interpret)


def decode_attention_quant(
    q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
    k_scale: jax.Array, v_scale: jax.Array, lengths: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """int8-KV auto-dispatch: the quantized kernel streams half the HBM
    bytes AND skips the logits materialization; unsupported shapes / CPU
    dequantize and take the XLA reference."""
    s_max, hd = k_cache.shape[1], k_cache.shape[3]
    if not supports(s_max, hd) or (
        not interpret and jax.default_backend() not in TPU_BACKENDS
    ):
        deq = k_cache.astype(q.dtype) * k_scale[..., None].astype(q.dtype)
        dev = v_cache.astype(q.dtype) * v_scale[..., None].astype(q.dtype)
        return xla_decode(q, deq, dev, lengths)
    return decode_attention_quant_pallas(
        q, k_cache, v_cache, k_scale, v_scale, lengths, interpret=interpret)
