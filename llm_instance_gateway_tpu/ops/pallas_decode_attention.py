"""Pallas TPU kernel for cached decode attention.

Decode attention reads the whole static KV cache every step — the HBM-bound
inner loop of serving.  The XLA reference (``ops.attention.decode_attention``)
materializes [B, K, G, S] logits between two einsums; this kernel streams the
cache in blocks with the online-softmax recurrence, keeping per-program state
in VMEM: one grid cell per (batch row, KV head) computes that head group's
output for the row's single query token.

Length masking is exact (positions >= length contribute nothing), matching
the engine's garbage-tail cache contract.  ``decode_attention`` is the
auto-dispatching entry with the XLA fallback for unsupported shapes/CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_instance_gateway_tpu.ops.attention import decode_attention as xla_decode

NEG_INF = -1e30

BLOCK_S = 128


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_s: int,
                   scale: float):
    # q_ref: [1, 1, G, hd]; k_ref/v_ref: [1, S, 1, hd]; len_ref: [B] (SMEM,
    # scalar-prefetched — index by this program's batch row).
    g, hd = q_ref.shape[2], q_ref.shape[3]
    length = len_ref[pl.program_id(0)]

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [G, hd]
    m0 = jnp.full((g, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    o0 = jnp.zeros((g, hd), jnp.float32)

    def body(sb, carry):
        m, l, o = carry
        start = sb * block_s
        k = k_ref[0, pl.ds(start, block_s), 0, :].astype(jnp.float32)  # [BS, hd]
        v = v_ref[0, pl.ds(start, block_s), 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [G, BS]
        pos = start + jax.lax.broadcasted_iota(jnp.int32, (g, block_s), 1)
        s = jnp.where(pos < length, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        o_new = o * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    # Only blocks that can contain valid positions (< length) do work.
    n_blocks = (length + block_s - 1) // block_s
    m, l, o = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, o0))
    o_ref[0, 0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def decode_attention_pallas(
    q: jax.Array,        # [B, n_heads, hd]
    k_cache: jax.Array,  # [B, S, n_kv, hd]
    v_cache: jax.Array,
    lengths: jax.Array,  # [B] int32
    block_s: int = BLOCK_S,
    interpret: bool = False,
) -> jax.Array:
    b, n_heads, hd = q.shape
    s_max, n_kv = k_cache.shape[1], k_cache.shape[2]
    g = n_heads // n_kv
    scale = float(1.0 / (hd ** 0.5))
    qg = q.reshape(b, n_kv, g, hd)
    kernel = functools.partial(_decode_kernel, block_s=block_s, scale=scale)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, g, hd), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # lengths: needed for the block count
            grid=(b, n_kv),
            in_specs=[
                pl.BlockSpec((1, 1, g, hd), lambda bi, ki, lens: (bi, ki, 0, 0)),
                pl.BlockSpec((1, s_max, 1, hd), lambda bi, ki, lens: (bi, 0, ki, 0)),
                pl.BlockSpec((1, s_max, 1, hd), lambda bi, ki, lens: (bi, 0, ki, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, g, hd), lambda bi, ki, lens: (bi, ki, 0, 0)),
        ),
        interpret=interpret,
    )(lengths, qg, k_cache, v_cache)
    return out.reshape(b, n_heads, hd)


def supports(s_max: int, hd: int, block_s: int = BLOCK_S) -> bool:
    return s_max % block_s == 0 and hd % 128 == 0


def decode_attention(
    q: jax.Array, k_cache: jax.Array, v_cache: jax.Array, lengths: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """Auto-dispatch: Pallas kernel when shapes allow, XLA reference otherwise."""
    s_max, hd = k_cache.shape[1], k_cache.shape[3]
    if not supports(s_max, hd):
        return xla_decode(q, k_cache, v_cache, lengths)
    return decode_attention_pallas(q, k_cache, v_cache, lengths, interpret=interpret)
