"""Pallas TPU flash attention for prefill.

Why a hand kernel here and nowhere else (yet): prefill attention is the one
op where XLA's default schedule materializes the [S, S] score matrix in HBM
for long sequences — flash tiling keeps scores in VMEM and streams K/V
blocks, turning an O(S^2) HBM traffic pattern into O(S).  Everything
elementwise (norms, RoPE, activations) stays XLA-fused, per the guide's
"don't hand-schedule what the compiler already does".

Kernel shape: grid (B, H, S/BLOCK_Q); each program holds one query block in
VMEM and loops over K/V blocks with the online-softmax recurrence in f32
scratch.  GQA is native: the K/V BlockSpec index-maps query head h to KV
head h // (H/K), so grouped heads share the same streamed K/V block without
materialized repetition.  Causal blocks strictly above the diagonal are
skipped (their programs still run but do no FLOPs via @pl.when).

Use ``flash_attention`` for the auto-dispatching entry: it falls back to the
XLA reference (``ops.attention.prefill_attention``) when shapes don't meet
the tiling constraints (tiny test models) or off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_instance_gateway_tpu.ops.attention import prefill_attention

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    # Blocks keep their leading (batch, head) unit dims:
    # q_ref: [1, 1, BLOCK_Q, hd]; k_ref/v_ref: [1, 1, S, hd].
    qi = pl.program_id(2)
    s_total = k_ref.shape[2]
    n_kblocks = s_total // block_k

    q = q_ref[0, 0].astype(jnp.float32) * scale
    bq = q.shape[0]

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    q_start = qi * bq

    def body(kb, carry):
        m, l, o = carry
        k_start = kb * block_k
        k = k_ref[0, 0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        o_new = o * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, o_new

    # Causal: only blocks up to (and including) the diagonal contribute.
    last_block = (
        jnp.minimum((q_start + bq + block_k - 1) // block_k, n_kblocks)
        if causal else n_kblocks
    )
    m, l, o = jax.lax.fori_loop(0, last_block, body, (m0, l0, o0))
    o_ref[0, 0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention_bhsd(
    q: jax.Array,  # [B, H, S, hd]
    k: jax.Array,  # [B, K, S, hd]
    v: jax.Array,
    causal: bool = True,
    block_q: int = BLOCK_Q,
    block_k: int = BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    b, h, s, hd = q.shape
    n_kv = k.shape[1]
    g = h // n_kv
    scale = float(1.0 / (hd ** 0.5))
    grid = (b, h, s // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, hd),
                             lambda bi, hi, qi: (bi, hi, qi, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, s, hd),
                             lambda bi, hi, qi, g=g: (bi, hi // g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, s, hd),
                             lambda bi, hi, qi, g=g: (bi, hi // g, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, hd),
                                   lambda bi, hi, qi: (bi, hi, qi, 0),
                                   memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(q, k, v)


def supports(s: int, hd: int, block_q: int = BLOCK_Q, block_k: int = BLOCK_K) -> bool:
    """Shape gate for the kernel path (pad upstream or fall back)."""
    return s % block_q == 0 and s % block_k == 0 and hd % 128 == 0


def flash_attention(
    q: jax.Array,  # [B, S, H, hd] (model layout)
    k: jax.Array,  # [B, S, K, hd]
    v: jax.Array,
    causal: bool = True,
    interpret: bool = False,
) -> jax.Array:
    """Auto-dispatch: Pallas kernel when shapes allow, XLA reference otherwise.

    NOTE: the kernel path is purely causal — use it for right-padded batches
    (pad tokens trail real ones, so causality alone keeps real positions
    exact; pad rows are garbage the caller ignores).  Packed batches with
    position-based masks must use the XLA path.
    """
    b, s, h, hd = q.shape
    if not supports(s, hd):
        return prefill_attention(q, k, v)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = flash_attention_bhsd(qt, kt, vt, causal=causal, interpret=interpret)
    return out.transpose(0, 2, 1, 3)
