"""Pallas TPU flash attention for prefill.

Why a hand kernel here and nowhere else (yet): prefill attention is the one
op where XLA's default schedule materializes the [S, S] score matrix in HBM
for long sequences — flash tiling keeps scores in VMEM and streams K/V
blocks, turning an O(S^2) HBM traffic pattern into O(S).  Everything
elementwise (norms, RoPE, activations) stays XLA-fused, per the guide's
"don't hand-schedule what the compiler already does".

Kernel shape: grid (B, H, S/BLOCK_Q, S/BLOCK_K) with the K-block axis
innermost and SEQUENTIAL ("arbitrary" semantics): VMEM holds ONE
[BLOCK_K, hd] K/V tile at a time — long-context ready, VMEM use is O(block)
regardless of S — while the online-softmax state (m, l, o-accumulator)
persists in f32 scratch across the K sweep and the output writes on the
last K block.  GQA is native: the K/V BlockSpec index-maps query head h to
KV head h // (H/K), so grouped heads share the same streamed K/V tile
without materialized repetition.  Causal K blocks strictly above the
diagonal skip their FLOPs via @pl.when.

Use ``flash_attention`` for the auto-dispatching entry: it falls back to the
XLA reference (``ops.attention.prefill_attention``) when shapes don't meet
the tiling constraints (tiny test models) or off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_instance_gateway_tpu.ops import pallas_decode_attention
from llm_instance_gateway_tpu.ops.attention import prefill_attention

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128


def _softmax_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                   q_start, k_start, masked: bool, scale: float):
    """One K/V tile of the online-softmax recurrence — the numerically
    sensitive core shared by the self-attention flash kernel (static
    q_start) and the chunk-attend kernel (dynamic, offset q_start)."""
    def go():
        bq = q_ref.shape[2]
        block_k = k_ref.shape[2]
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = jnp.broadcast_to(
            l_prev * corr + p.sum(axis=-1, keepdims=True), l_scr.shape)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    return go


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  causal: bool, scale: float):
    # Blocks keep their leading (batch, head) unit dims:
    # q_ref: [1, 1, BLOCK_Q, hd]; k_ref/v_ref: [1, 1, BLOCK_K, hd] — one K/V
    # tile per grid step, carried state in scratch (lane-padded to 128).
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    n_kblocks = pl.num_programs(3)
    bq = q_ref.shape[2]
    block_k = k_ref.shape[2]
    q_start = qi * bq
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute(masked: bool):
        return _softmax_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                              q_start, k_start, masked, scale)

    if causal:
        # Exactly one branch runs per step: the diagonal-straddling block
        # pays for the iota mask, interior blocks skip it, and blocks
        # strictly above the diagonal do nothing (their K/V DMA is also
        # elided — the index map revisits the previous tile).
        on_diagonal = (k_start + block_k > q_start) & (k_start < q_start + bq)
        pl.when(on_diagonal)(_compute(masked=True))
        pl.when(k_start + block_k <= q_start)(_compute(masked=False))
    else:
        _compute(masked=False)()

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_scr[...] / jnp.maximum(l_scr[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def flash_attention_bhsd(
    q: jax.Array,  # [B, H, S, hd]
    k: jax.Array,  # [B, K, S, hd]
    v: jax.Array,
    causal: bool = True,
    block_q: int = BLOCK_Q,
    block_k: int = BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    b, h, s, hd = q.shape
    n_kv = k.shape[1]
    g = h // n_kv
    scale = float(1.0 / (hd ** 0.5))
    # K-block axis innermost and sequential: scratch carries the online
    # softmax state across it; the three outer axes parallelize freely.
    grid = (b, h, s // block_q, s // block_k)
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale)

    if causal:
        # Blocks strictly above the diagonal never contribute: clamp their
        # K/V index to the last contributing tile, so Pallas sees the same
        # block as the previous step and elides the dead HBM->VMEM copy
        # (the kernel's @pl.when skips their compute anyway).
        def kv_index(bi, hi, qi, kb, g=g):
            last = (qi * block_q + block_q - 1) // block_k
            return (bi, hi // g, jnp.minimum(kb, last), 0)
    else:
        def kv_index(bi, hi, qi, kb, g=g):
            return (bi, hi // g, kb, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd),
                         lambda bi, hi, qi, kb: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, hd), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, hd), kv_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd),
                               lambda bi, hi, qi, kb: (bi, hi, qi, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (lane-padded)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l
            pltpu.VMEM((block_q, hd), jnp.float32),   # o accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def supports(s: int, hd: int, block_q: int = BLOCK_Q, block_k: int = BLOCK_K) -> bool:
    """Shape gate for the kernel path (pad upstream or fall back)."""
    return s % block_q == 0 and s % block_k == 0 and hd % 128 == 0


# ---------------------------------------------------------------------------
# Chunk attend: a query chunk at a dynamic position offset vs the KV cache
# ---------------------------------------------------------------------------


def _chunk_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                  *, scale: float):
    # Same online-softmax core as _flash_kernel (shared _softmax_block)
    # with ONE difference: query positions are offset by the chunk's
    # dynamic start (off_ref, SMEM) — chunk token i sits at global
    # position off + q_start + i and attends cache positions <= it.
    # K blocks wholly above the chunk's last position skip compute
    # (their DMA is elided by the index-map clamp).
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    n_kblocks = pl.num_programs(3)
    bq = q_ref.shape[2]
    block_k = k_ref.shape[2]
    q_start = off_ref[0] + qi * bq
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute(masked: bool):
        return _softmax_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                              q_start, k_start, masked, scale)

    # Dynamic diagonal (off is a runtime value): exactly one branch fires.
    on_diagonal = (k_start + block_k > q_start) & (k_start < q_start + bq)
    pl.when(on_diagonal)(_compute(masked=True))
    pl.when(k_start + block_k <= q_start)(_compute(masked=False))

    @pl.when(kb == n_kblocks - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_scr[...] / jnp.maximum(l_scr[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def chunk_attention_pallas(
    q: jax.Array,        # [B, C, H, hd] — chunk queries (contiguous positions)
    k_cache: jax.Array,  # [B, S, K, hd] — lane view incl. the chunk's KV
    v_cache: jax.Array,
    start: jax.Array,    # scalar int32: global position of chunk token 0
    block_q: int = BLOCK_Q,
    block_k: int = BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Flash-style chunk attend: chunk token i (global position start+i)
    attends cache positions <= start+i.  Replaces the XLA einsum's [C, S]
    logits materialization on the chunk-stream path — the long-context
    TTFT hot loop — with O(block) VMEM tiles; K blocks past each query
    tile's reach are clamped to the last contributing tile so their HBM
    copies are elided (bandwidth tracks the chunk's position, not S_max)."""
    b, c, h, hd = q.shape
    s_max = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    g = h // n_kv
    scale = float(1.0 / (hd ** 0.5))
    qt = q.transpose(0, 2, 1, 3)          # [B, H, C, hd]
    kt = k_cache.transpose(0, 2, 1, 3)    # [B, K, S, hd]
    vt = v_cache.transpose(0, 2, 1, 3)
    off = jnp.asarray(start, jnp.int32).reshape(1)

    def kv_index(bi, hi, qi, kb, off, g=g):
        last = (off[0] + qi * block_q + block_q - 1) // block_k
        return (bi, hi // g, jnp.minimum(kb, last), 0)

    out = pl.pallas_call(
        functools.partial(_chunk_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # chunk start: masking + DMA clamping
            grid=(b, h, c // block_q, s_max // block_k),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, hd),
                             lambda bi, hi, qi, kb, off: (bi, hi, qi, 0)),
                pl.BlockSpec((1, 1, block_k, hd), kv_index),
                pl.BlockSpec((1, 1, block_k, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, hd),
                                   lambda bi, hi, qi, kb, off: (bi, hi, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((block_q, 128), jnp.float32),  # m (lane-padded)
                pltpu.VMEM((block_q, 128), jnp.float32),  # l
                pltpu.VMEM((block_q, hd), jnp.float32),   # o accumulator
            ],
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(off, qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def supports_chunk(c: int, s_max: int, hd: int) -> bool:
    return c % BLOCK_Q == 0 and s_max % BLOCK_K == 0 and hd % 128 == 0


def chunk_attention(
    q: jax.Array, k_cache: jax.Array, v_cache: jax.Array, start,
    interpret: bool = False,
) -> jax.Array:
    """Auto-dispatch for the chunk attend; XLA reference otherwise."""
    from llm_instance_gateway_tpu.ops.attention import xla_chunk_attention

    b, c, h, hd = q.shape
    if not supports_chunk(c, k_cache.shape[1], hd) or (
        not interpret
        and jax.default_backend() not in pallas_decode_attention.TPU_BACKENDS
    ):
        return xla_chunk_attention(q, k_cache, v_cache, start)
    return chunk_attention_pallas(q, k_cache, v_cache, start,
                                  interpret=interpret)


def flash_attention(
    q: jax.Array,  # [B, S, H, hd] (model layout)
    k: jax.Array,  # [B, S, K, hd]
    v: jax.Array,
    causal: bool = True,
    interpret: bool = False,
) -> jax.Array:
    """Auto-dispatch: Pallas kernel when shapes allow, XLA reference otherwise.

    NOTE: the kernel path is purely causal — use it for right-padded batches
    (pad tokens trail real ones, so causality alone keeps real positions
    exact; pad rows are garbage the caller ignores).  Packed batches with
    position-based masks must use the XLA path.
    """
    b, s, h, hd = q.shape
    if not supports(s, hd) or (
        not interpret
        and jax.default_backend() not in pallas_decode_attention.TPU_BACKENDS
    ):
        return prefill_attention(q, k, v)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = flash_attention_bhsd(qt, kt, vt, causal=causal, interpret=interpret)
    return out.transpose(0, 2, 1, 3)
