"""Elementwise/normalization building blocks (XLA-fused on TPU).

These are deliberately *not* Pallas: RMSNorm and RoPE are elementwise chains
that XLA fuses into the surrounding matmuls for free; a hand kernel would only
forfeit fusion.  Accumulations run in float32 and cast back to the activation
dtype (bfloat16 on TPU), the standard mixed-precision discipline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5,
             plus_one: bool = False) -> jax.Array:
    """RMSNorm in f32.  ``plus_one`` selects the Gemma (1+w) convention."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if plus_one:
        w = 1.0 + w
    return (normed * w).astype(x.dtype)


def rope_frequencies(head_dim: int, theta: float,
                     scaling: tuple | None = None) -> jax.Array:
    """Inverse frequencies for rotary embedding, shape [head_dim//2], f32.

    ``scaling`` = (factor, low_freq_factor, high_freq_factor, original_max)
    applies the Llama-3.1 long-context frequency remapping: wavelengths
    beyond ``original_max/low_freq_factor`` are stretched by ``factor``,
    short wavelengths pass through, and the band between interpolates —
    parity-tested against transformers' llama3 rope_type.
    """
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    freqs = 1.0 / (theta ** exponents)
    if scaling is None:
        return freqs
    factor, low_ff, high_ff, original_max = scaling
    wavelen = 2.0 * jnp.pi / freqs
    low_freq_wavelen = original_max / low_ff
    high_freq_wavelen = original_max / high_ff
    smooth = (original_max / wavelen - low_ff) / (high_ff - low_ff)
    interpolated = (1.0 - smooth) * freqs / factor + smooth * freqs
    scaled = jnp.where(wavelen > low_freq_wavelen, freqs / factor,
                       jnp.where(wavelen < high_freq_wavelen, freqs, interpolated))
    return scaled


def apply_rope(x: jax.Array, positions: jax.Array, theta: float,
               scaling: tuple | None = None) -> jax.Array:
    """Rotary position embedding.

    x: [..., seq, heads, head_dim]; positions: broadcastable to [..., seq].
    Uses the split-halves convention (Llama/NeoX style): pairs (x_i, x_{i+d/2}).
    Computed in f32, cast back — sin/cos precision matters at long context.
    """
    head_dim = x.shape[-1]
    freqs = rope_frequencies(head_dim, theta, scaling)  # [hd/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def swiglu(gate: jax.Array, up: jax.Array, gelu: bool = False) -> jax.Array:
    """Gated MLP activation: SiLU (Llama/Mixtral) or tanh-GeLU (Gemma)."""
    act = jax.nn.gelu(gate, approximate=True) if gelu else jax.nn.silu(gate)
    return act * up
