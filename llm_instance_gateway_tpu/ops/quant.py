"""Weight-only int8 quantization for serving.

Decode throughput on a single chip is bounded by reading the weights from
HBM every step; storing the big projection matrices as int8 with per-output-
channel scales halves that traffic versus bf16.  The matmul runs as
``(x @ w_int8.astype(bf16)) * scale`` — XLA fuses the widening into the MXU
feed, so HBM sees int8 while the MXU still computes in bf16, and the
per-column scale is algebraically exact to apply after the contraction.

Quantized leaves are dicts ``{"q": int8 [..., in, out], "s": f32 [..., out]}``
in place of the dense array; ``models.transformer`` dispatches through
``matmul`` below so dense and quantized checkpoints share one forward.
Symmetric per-channel quantization of ~normal weights keeps relative error
around 0.4% per matmul (validated in tests/test_quant.py).

Scope: the seven per-layer projections (dense [L, in, out] AND MoE expert
stacks [L, E, in, out]) + lm_head.  Embeddings (gather, not matmul), norms,
the MoE router (tiny, drives f32 top-k), and LoRA buffers stay bf16.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

QUANT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w: jax.Array) -> dict[str, jax.Array]:
    """Symmetric per-output-channel int8 quantization (last axis = out)."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / 127.0  # [..., 1, out]
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.squeeze(-2).astype(jnp.float32)}


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def matmul(x: jax.Array, w: Any) -> jax.Array:
    """x @ w for dense arrays or quantized {"q","s"} leaves."""
    if is_quantized(w):
        y = x @ w["q"].astype(x.dtype)
        return y * w["s"].astype(x.dtype)
    return x @ w


def _expert_einsum(spec: str, scale_expand: int | None, x, w):
    """One dequant-einsum for every expert-weight contraction: the
    per-output-channel scale applies after the contraction (exact — the
    scaled axis is never contracted), broadcast to the output rank by
    expanding at ``scale_expand`` when the output carries a capacity axis
    between the expert and channel axes."""
    if is_quantized(w):
        y = jnp.einsum(spec, x, w["q"].astype(x.dtype))
        s = w["s"].astype(x.dtype)
        if scale_expand is not None:
            s = jnp.expand_dims(s, scale_expand)
        return y * s
    return jnp.einsum(spec, x, w)


def expert_matmul(x: jax.Array, w: Any) -> jax.Array:
    """Per-expert tile matmul: [E, C, din] x [E, din, dout] -> [E, C, dout]
    (both grouped-dispatch einsums are this shape, up and down)."""
    return _expert_einsum("ecd,edf->ecf", -2, x, w)  # s [E, out] -> [E,1,out]


def expert_mix(x: jax.Array, w: Any) -> jax.Array:
    """Dense all-experts up-projection: [..., din] x [E, din, f] ->
    [..., E, f]."""
    return _expert_einsum("...d,edf->...ef", None, x, w)


def expert_mix_down(x: jax.Array, w: Any) -> jax.Array:
    """Dense all-experts down-projection: [..., E, f] x [E, f, d] ->
    [..., E, d] (the e axes align)."""
    return _expert_einsum("...ef,efd->...ed", None, x, w)


def quantize_params(params: dict, quantize_lm_head: bool = True) -> dict:
    """Return a params tree with the big projections int8-quantized."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in QUANT_TARGETS:
        w = layers.get(name)
        if w is None or is_quantized(w):
            continue
        # Dense projections [L, in, out] AND MoE expert stacks
        # [L, E, in, out] quantize the same way (per-output-channel over
        # the last axis) — expert weights are exactly where Mixtral's
        # HBM-bound decode spends its weight bandwidth.  The router stays
        # dense (a tiny [d, E] matmul whose f32 logits drive top-k).
        layers[name] = quantize_weight(w)
    out["layers"] = layers
    if quantize_lm_head and "lm_head" in params and not is_quantized(params["lm_head"]):
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out


def quantized_bytes(params: dict) -> tuple[int, int]:
    """(bytes_now, bytes_if_dense_bf16) for the weight tree — memory audit."""
    now = 0
    dense = 0
    for leaf in jax.tree.leaves(params):
        now += leaf.size * leaf.dtype.itemsize
        dense += leaf.size * (2 if leaf.dtype == jnp.int8 else leaf.dtype.itemsize)
    return now, dense
