"""Weight-only int8 quantization for serving.

Decode throughput on a single chip is bounded by reading the weights from
HBM every step; storing the big projection matrices as int8 with per-output-
channel scales halves that traffic versus bf16.  The matmul runs as
``(x @ w_int8.astype(bf16)) * scale`` — XLA fuses the widening into the MXU
feed, so HBM sees int8 while the MXU still computes in bf16, and the
per-column scale is algebraically exact to apply after the contraction.

Quantized leaves are dicts ``{"q": int8 [..., in, out], "s": f32 [..., out]}``
in place of the dense array; ``models.transformer`` dispatches through
``matmul`` below so dense and quantized checkpoints share one forward.
Symmetric per-channel quantization of ~normal weights keeps relative error
around 0.4% per matmul (validated in tests/test_quant.py).

Scope (v1): the seven per-layer projections + lm_head.  Embeddings (gather,
not matmul), norms, MoE expert stacks, and LoRA buffers stay bf16.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

QUANT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w: jax.Array) -> dict[str, jax.Array]:
    """Symmetric per-output-channel int8 quantization (last axis = out)."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / 127.0  # [..., 1, out]
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.squeeze(-2).astype(jnp.float32)}


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def matmul(x: jax.Array, w: Any) -> jax.Array:
    """x @ w for dense arrays or quantized {"q","s"} leaves."""
    if is_quantized(w):
        y = x @ w["q"].astype(x.dtype)
        return y * w["s"].astype(x.dtype)
    return x @ w


def quantize_params(params: dict, quantize_lm_head: bool = True) -> dict:
    """Return a params tree with the big projections int8-quantized."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in QUANT_TARGETS:
        w = layers.get(name)
        if w is None or is_quantized(w):
            continue
        if w.ndim == 4:  # MoE expert stacks: keep dense in v1
            continue
        layers[name] = quantize_weight(w)
    out["layers"] = layers
    if quantize_lm_head and "lm_head" in params and not is_quantized(params["lm_head"]):
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out


def quantized_bytes(params: dict) -> tuple[int, int]:
    """(bytes_now, bytes_if_dense_bf16) for the weight tree — memory audit."""
    now = 0
    dense = 0
    for leaf in jax.tree.leaves(params):
        now += leaf.size * leaf.dtype.itemsize
        dense += leaf.size * (2 if leaf.dtype == jnp.int8 else leaf.dtype.itemsize)
    return now, dense
