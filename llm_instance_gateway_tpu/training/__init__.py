"""Training: LoRA fine-tuning + full-parameter train steps (sharded).

The reference repo serves adapters but doesn't produce them; a complete
TPU-native stack owns that loop too — the adapters the sidecar hot-swaps are
Orbax checkpoints written by ``lora_finetune.save_trained_adapter``.
"""
