"""Sharded train steps: full-parameter and LoRA-only fine-tuning.

GSPMD training recipe: params sharded by ``parallel.sharding.param_specs``
(fsdp + tensor), batch sharded over (data, sequence), optimizer state
mirrors the param sharding, and the whole step — forward, causal-LM loss,
backward, optax update — is one jitted program; XLA inserts the
reduce-scatters/all-gathers over the mesh axes (dp/fsdp/tp/sp, ep for MoE).

``lora_train_step`` freezes the base model and differentiates only the
adapter slot buffers — the loop that produces the Orbax adapters the serving
stack hot-swaps (models/lora.py zero-padding means the gradient is naturally
confined to the real ranks' subspace plus harmless padded lanes).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import ModelConfig


def causal_lm_loss(cfg: ModelConfig, params, tokens, positions, lora_bufs=None,
                   slot_ids=None, logits_fn=None) -> jax.Array:
    """Next-token cross-entropy, masked to real (non-pad) positions.

    Position 0 repeated marks padding (matching the serving convention);
    the mask keeps pad targets out of the mean.  ``logits_fn(params,
    tokens, positions) -> [B, S, V]`` swaps the forward — the pipelined
    trainer routes through ``parallel.pipeline.pipeline_forward`` while the
    shift/mask convention stays defined in exactly one place.
    """
    if logits_fn is None:
        def logits_fn(p, t, pos):
            return transformer.prefill(
                cfg, p, t, pos, lora_bufs=lora_bufs, slot_ids=slot_ids)[0]

    logits = logits_fn(params, tokens[:, :-1], positions[:, :-1])
    targets = tokens[:, 1:]
    mask = (positions[:, 1:] > 0).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -(token_logp * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_optimizer(lr: float = 1e-4, weight_decay: float = 0.0):
    return optax.adamw(lr, weight_decay=weight_decay)


def make_full_train_step(cfg: ModelConfig, optimizer):
    """Full-parameter train step (not jitted; caller applies jit+shardings)."""

    def step(params, opt_state, tokens, positions):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(cfg, p, tokens, positions)
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_lora_train_step(cfg: ModelConfig, optimizer):
    """Adapter-only train step: base params are frozen inputs."""

    def step(params, lora_bufs, opt_state, tokens, positions, slot_ids):
        def loss_fn(bufs):
            return causal_lm_loss(
                cfg, params, tokens, positions, lora_bufs=bufs, slot_ids=slot_ids
            )

        loss, grads = jax.value_and_grad(loss_fn)(lora_bufs)
        # The scale vector is serving metadata (alpha/r), not a trainable.
        grads = {**grads, "scale": jnp.zeros_like(lora_bufs["scale"])}
        updates, opt_state = optimizer.update(grads, opt_state, lora_bufs)
        lora_bufs = optax.apply_updates(lora_bufs, updates)
        return lora_bufs, opt_state, loss

    return step


def extract_adapter(cfg: ModelConfig, lora_bufs, slot: int, rank: int) -> dict:
    """Pull one trained slot back out as a rank-r adapter weight dict
    (inverse of models.lora.load_adapter) for Orbax export."""
    from llm_instance_gateway_tpu.models import lora as lora_lib

    weights: dict[str, Any] = {}
    for t in lora_lib.TARGETS:
        a = jax.device_get(lora_bufs[f"{t}_a"][:, slot, :, :rank])
        b = jax.device_get(lora_bufs[f"{t}_b"][:, slot, :rank, :])
        weights[t] = {"a": a, "b": b}
    return weights


def save_trained_adapter(path: str, cfg: ModelConfig, lora_bufs, slot: int,
                         rank: int, alpha: float) -> None:
    from llm_instance_gateway_tpu.server.lora_manager import save_adapter

    save_adapter(path, extract_adapter(cfg, lora_bufs, slot, rank), alpha, rank)
