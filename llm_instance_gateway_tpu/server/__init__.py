"""TPU model server: continuous-batching engine, KV management, HTTP API.

This is the replica the gateway routes to — the JetStream/MaxText-equivalent
the reference delegates to vLLM (SURVEY.md §2: "the TPU-native framework's
native layer is the model-server side we must supply").
"""
