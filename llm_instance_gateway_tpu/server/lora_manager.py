"""LoRA adapter lifecycle on the serving process: registry + Orbax hot-swap.

This replaces the reference's vLLM-side adapter machinery: where the sidecar
POSTs ``/v1/load_lora_adapter`` and vLLM pulls safetensors into CUDA slots
(``tools/dynamic-lora-sidecar/sidecar/sidecar.py:177-213``), our server
restores an **Orbax checkpoint** directly into the pre-allocated JAX slot
buffers (``models.lora``) — no recompilation, no process restart, and the
swap is one device-buffer write (BASELINE.json north star: "hot-swaps
adapters into a JAX/XLA serving process via Orbax restore").

Checkpoint layout (written by ``save_adapter`` / the training pipeline):
a pytree ``{"meta": {"alpha": f, "rank": r}, "weights": {target: {"a": ...,
"b": ...}}}`` saved with ``orbax.checkpoint.PyTreeCheckpointer``.
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.models import lora as lora_lib

logger = logging.getLogger(__name__)

# Residency ladder tiers (MinT/InfiniLoRA-style disaggregated placement):
# ``slot`` = device buffers, decodable this instant; ``host`` = weights
# parked in host RAM (promotion is one device put, no checkpoint restore);
# ``disk`` = Orbax checkpoint only (cold: restore + device put).  An
# adapter is in EXACTLY ONE tier per replica at any time — the
# conservation invariant tests/test_placement.py lints through the
# rendered exposition.
TIER_SLOT = "slot"
TIER_HOST = "host"
TIER_DISK = "disk"
RESIDENCY_TIERS = (TIER_SLOT, TIER_HOST, TIER_DISK)


class AdapterError(Exception):
    pass


class AdapterBusyError(AdapterError):
    """Adapter has in-flight requests pinned to its slot (HTTP 409).

    Unloading a slot that live decodes still read would silently degrade
    those requests to base-model output — and a subsequent load() into the
    recycled slot would hand them a *different tenant's* weights.  The
    sidecar reconciler simply retries on its next pass once traffic drains.
    """


@dataclass
class AdapterInfo:
    name: str
    slot: int
    rank: int
    alpha: float
    source: str  # checkpoint path or "inline"
    # Checkpoint-shaped host copy of the weights (numpy pytree).  This is
    # what demotion moves into the host tier: extracting weights back out
    # of the padded device buffers would be a device read plus an un-pad;
    # keeping the (few-MB) host reference makes slot->host a pointer move
    # and host->slot one device put.  Excluded from repr (huge).
    weights: dict | None = None

    def __repr__(self):  # keep logs/debug payloads weight-free
        return (f"AdapterInfo(name={self.name!r}, slot={self.slot}, "
                f"rank={self.rank}, alpha={self.alpha}, "
                f"source={self.source!r})")


def save_adapter(path: str, weights: dict, alpha: float, rank: int) -> None:
    """Write an adapter checkpoint (numpy pytree) via Orbax."""
    import orbax.checkpoint as ocp

    tree = {
        "meta": {"alpha": np.float32(alpha), "rank": np.int32(rank)},
        "weights": {
            t: {k: np.asarray(v, np.float32) for k, v in tv.items()}
            for t, tv in weights.items()
        },
    }
    ocp.PyTreeCheckpointer().save(path, tree)


def load_adapter_checkpoint(path: str) -> tuple[dict, float, int]:
    import orbax.checkpoint as ocp

    tree = ocp.PyTreeCheckpointer().restore(path)
    meta = tree["meta"]
    return tree["weights"], float(meta["alpha"]), int(meta["rank"])


class LoRAManager:
    """Thread-safe adapter registry bound to the engine's slot buffers.

    Mirrors the metric semantics of ``vllm:lora_requests_info``
    (``backend/vllm/metrics.go:19-32``): ``running_adapters`` is the set the
    gateway's affinity filter matches against; ``max_slots`` is max_lora.
    """

    def __init__(self, cfg, dtype=jnp.bfloat16, mesh=None,
                 host_cache_slots: int = 8, clock=time.perf_counter):
        self.cfg = cfg
        self._lock = witness_lock("LoRAManager._lock")
        # Serializes whole load/unload operations: the buffer update is a
        # read-modify-write of self.buffers, and concurrent HTTP admin calls
        # run in separate executor threads — without this, the second writer
        # would silently drop the first one's weights.
        self._mutate_lock = witness_lock("LoRAManager._mutate_lock")
        self._adapters: dict[str, AdapterInfo] = {}
        self._active: dict[str, int] = {}  # name -> in-flight request count
        self._free_slots = list(range(cfg.max_lora_slots))
        # Host-RAM tier: name -> (weights numpy pytree, alpha, rank,
        # source), LRU-bounded.  Promotion (host -> slot) skips the Orbax
        # restore entirely — one device put; demotion (slot -> host) copies
        # the checkpoint-shaped weights back to host numpy so a later
        # promote restores bit-identical deltas.
        self.host_cache_slots = max(0, host_cache_slots)
        self._host: "OrderedDict[str, tuple]" = OrderedDict()
        self._clock = clock
        # Residency-plane accounting (rendered by server/metrics.py):
        # tier transitions and per-tier load latency (sum, count).
        self.tier_transitions: dict[tuple[str, str], int] = {}
        self.load_seconds: dict[str, list] = {
            t: [0.0, 0] for t in (TIER_HOST, TIER_DISK)}
        self.buffers = lora_lib.init_lora_buffers(cfg, dtype=dtype)
        # Sharded serving: pin slot buffers to the engine's mesh so the delta
        # matmuls compose with the column-sharded base projections without
        # resharding (parallel/sharding.py lora_specs).
        self._mesh = mesh
        if mesh is not None:
            from llm_instance_gateway_tpu.parallel import sharding as sharding_lib

            self._lora_specs = sharding_lib.lora_specs(cfg)
            self.buffers = sharding_lib.shard_pytree(
                self.buffers, self._lora_specs, mesh)

    def _pin(self, buffers):
        """Re-pin buffers to the mesh after an eager .at[].set mutation."""
        if self._mesh is None:
            return buffers
        from llm_instance_gateway_tpu.parallel import sharding as sharding_lib

        return sharding_lib.shard_pytree(buffers, self._lora_specs, self._mesh)

    # -- queries -----------------------------------------------------------
    def running_adapters(self) -> list[str]:
        with self._lock:
            return sorted(self._adapters)

    def adapter_ranks(self) -> dict[str, int]:
        """Resident adapter name -> LoRA rank — the heterogeneity signal
        the gateway's rank-aware fair-share weighting consumes (exported
        as the ``adapter_ranks`` label of ``tpu:lora_requests_info``).
        Host-tier adapters are included: the planner prices a promotion's
        rank cost before it happens."""
        with self._lock:
            ranks = {name: info.rank for name, info in self._adapters.items()}
            for name, (_w, _a, rank, _src) in self._host.items():
                ranks.setdefault(name, rank)
            return ranks

    def adapter_tiers(self) -> dict[str, str]:
        """Adapter name -> residency tier for every adapter this replica
        holds in RAM (slot or host).  Disk-tier adapters are unknowable
        here (the checkpoint store is unbounded); the placement planner
        treats absence as disk.  Each name maps to exactly one tier — the
        conservation invariant the exposition lint pins."""
        with self._lock:
            tiers = {name: TIER_SLOT for name in self._adapters}
            for name in self._host:
                tiers[name] = TIER_HOST
            return tiers

    def residency_snapshot(self) -> dict[str, list[str]]:
        """Tier -> sorted adapter names (tpu:adapter_residency_info)."""
        with self._lock:
            return {TIER_SLOT: sorted(self._adapters),
                    TIER_HOST: sorted(self._host)}

    def residency_counters(self) -> tuple[dict, dict]:
        """(tier transitions {(from, to): n}, per-tier load latency
        {tier: [sum_s, count]}) — copies for the metrics snapshot."""
        with self._lock:
            return (dict(self.tier_transitions),
                    {t: list(sc) for t, sc in self.load_seconds.items()})

    def _note_transition(self, frm: str, to: str) -> None:
        """Caller holds self._lock."""
        key = (frm, to)
        self.tier_transitions[key] = self.tier_transitions.get(key, 0) + 1

    def _note_load(self, tier: str, seconds: float) -> None:
        with self._lock:
            sc = self.load_seconds.setdefault(tier, [0.0, 0])
            sc[0] += seconds
            sc[1] += 1

    def _host_put(self, name: str, weights: dict, alpha: float, rank: int,
                  source: str) -> None:
        """Insert into the bounded host tier (caller holds self._lock);
        LRU overflow falls off to disk (the checkpoint is the backstop)."""
        self._host[name] = (weights, alpha, rank, source)
        self._host.move_to_end(name)
        while len(self._host) > self.host_cache_slots:
            evicted, _ = self._host.popitem(last=False)
            self._note_transition(TIER_HOST, TIER_DISK)
            logger.info("host cache full: adapter %s fell to disk", evicted)

    @property
    def max_slots(self) -> int:
        return self.cfg.max_lora_slots

    def slot_for(self, adapter_name: str | None) -> int:
        """Slot id for a request (-1 = base model). Raises if not resident."""
        if adapter_name is None:
            return -1
        with self._lock:
            info = self._adapters.get(adapter_name)
        if info is None:
            raise AdapterError(f"adapter {adapter_name!r} is not loaded")
        return info.slot

    def acquire(self, adapter_name: str | None) -> int:
        """Resolve AND pin: the slot cannot be unloaded/recycled until the
        matching ``release``.  The engine acquires at admission and releases
        at finish, so live decodes never read a repurposed slot buffer."""
        if adapter_name is None:
            return -1
        with self._lock:
            info = self._adapters.get(adapter_name)
            if info is None:
                raise AdapterError(f"adapter {adapter_name!r} is not loaded")
            self._active[adapter_name] = self._active.get(adapter_name, 0) + 1
            return info.slot

    def release(self, adapter_name: str | None) -> None:
        if adapter_name is None:
            return
        with self._lock:
            n = self._active.get(adapter_name, 0)
            if n <= 1:
                self._active.pop(adapter_name, None)
            else:
                self._active[adapter_name] = n - 1

    def active_requests(self, adapter_name: str) -> int:
        with self._lock:
            return self._active.get(adapter_name, 0)

    # -- mutations ---------------------------------------------------------
    def load(
        self,
        name: str,
        weights: dict | None = None,
        alpha: float = 16.0,
        rank: int = 8,
        checkpoint_path: str | None = None,
    ) -> AdapterInfo:
        """Load an adapter into a free slot (idempotent per name)."""
        if not name or not all(c.isalnum() or c in "._-" for c in name):
            raise AdapterError(
                f"invalid adapter name {name!r}: use [A-Za-z0-9._-] "
                "(names flow into Prometheus labels and routing configs)"
            )
        with self._mutate_lock:
            with self._lock:
                if name in self._adapters:
                    return self._adapters[name]  # resident (sidecar.py:185-188)
                if not self._free_slots:
                    raise AdapterError(
                        f"no free adapter slots (max {self.cfg.max_lora_slots})"
                    )
                slot = self._free_slots.pop(0)
                # Promotion path: a host-tier copy skips the Orbax restore
                # — the whole point of the residency ladder.  The entry is
                # popped (not copied) so the name is never in two tiers.
                # A caller supplying NEW weights, or a DIFFERENT checkpoint
                # path than the cached copy came from, is publishing a new
                # version: the stale host copy must not shadow it — it is
                # discarded (the caller's source is authoritative).
                cached = self._host.pop(name, None)
                if cached is not None and (
                        weights is not None
                        or (checkpoint_path is not None
                            and checkpoint_path != cached[3])):
                    self._note_transition(TIER_HOST, TIER_DISK)
                    logger.info(
                        "discarding stale host copy of %s (source %s; "
                        "caller supplied a new source)", name, cached[3])
                    cached = None
            source = checkpoint_path or "inline"
            from_tier, timed_tier = TIER_DISK, None
            t0 = self._clock()
            try:
                if cached is not None:
                    weights, alpha, rank, source = cached
                    from_tier = timed_tier = TIER_HOST
                elif checkpoint_path is not None:
                    weights, alpha, rank = load_adapter_checkpoint(
                        checkpoint_path)
                    timed_tier = TIER_DISK
                if weights is None:
                    raise AdapterError("either weights or checkpoint_path required")
                self.buffers = self._pin(lora_lib.load_adapter(
                    self.buffers, self.cfg, slot, weights, alpha, rank
                ))
            except Exception:
                with self._lock:
                    self._free_slots.insert(0, slot)
                    if cached is not None:  # promotion failed: keep the copy
                        self._host[name] = cached
                raise
            if timed_tier is not None:
                self._note_load(timed_tier, self._clock() - t0)
            info = AdapterInfo(
                name=name, slot=slot, rank=rank, alpha=alpha,
                source=source, weights=weights,
            )
            with self._lock:
                self._adapters[name] = info
                self._note_transition(from_tier, TIER_SLOT)
        logger.info("loaded adapter %s into slot %d (rank %d, from %s)",
                    name, slot, rank, from_tier)
        return info

    def unload(self, name: str) -> bool:
        with self._mutate_lock:
            with self._lock:
                # Busy-check and pop atomically: a concurrent acquire() holds
                # the same lock and increments only while the name is still
                # registered, so no request can slip in after the check.
                active = self._active.get(name, 0)
                if active:
                    raise AdapterBusyError(
                        f"adapter {name!r} has {active} in-flight request(s); "
                        "retry after they drain"
                    )
                info = self._adapters.pop(name, None)
                if info is None:
                    # Host-tier unload needs no buffer work — drop the copy.
                    if self._host.pop(name, None) is not None:
                        self._note_transition(TIER_HOST, TIER_DISK)
                        logger.info("unloaded host-cached adapter %s", name)
                        return True
                    return False
            self.buffers = self._pin(
                lora_lib.unload_adapter(self.buffers, self.cfg, info.slot))
            with self._lock:
                self._free_slots.append(info.slot)
                self._note_transition(TIER_SLOT, TIER_DISK)
        logger.info("unloaded adapter %s from slot %d", name, info.slot)
        return True

    def demote(self, name: str) -> bool:
        """Slot -> host RAM: free the device slot, keep the weights hot so
        a later ``load`` is one device put instead of an Orbax restore.
        Refuses (AdapterBusyError -> HTTP 409) while the adapter has
        in-flight or decode_wait-parked requests — the engine acquires at
        admission and releases at finish, so a demoted slot can never be
        recycled under a live decode (the same pin ``unload`` honors).
        Refuses outright when the host tier is disabled: "demoting" into
        a zero-slot cache would silently discard the weights (fatal for
        inline-loaded adapters with no checkpoint backstop) while
        claiming tier=host."""
        if self.host_cache_slots <= 0:
            raise AdapterError(
                "cannot demote: host cache disabled (host_cache_slots=0); "
                "use unload if the checkpoint store is the backstop")
        with self._mutate_lock:
            with self._lock:
                active = self._active.get(name, 0)
                if active:
                    raise AdapterBusyError(
                        f"adapter {name!r} has {active} in-flight request(s); "
                        "retry after they drain"
                    )
                info = self._adapters.pop(name, None)
                if info is None:
                    return False
                if info.weights is None:
                    # No host copy to park (legacy load path): a demote
                    # would lose the weights entirely — refuse.
                    self._adapters[name] = info
                    raise AdapterError(
                        f"adapter {name!r} has no host-side weights to "
                        "demote (reload it from a checkpoint first)")
            self.buffers = self._pin(
                lora_lib.unload_adapter(self.buffers, self.cfg, info.slot))
            with self._lock:
                self._free_slots.append(info.slot)
                self._host_put(name, info.weights, info.alpha, info.rank,
                               info.source)
                self._note_transition(TIER_SLOT, TIER_HOST)
        logger.info("demoted adapter %s: slot %d -> host RAM", name,
                    info.slot)
        return True

    def prefetch(self, name: str, checkpoint_path: str) -> bool:
        """Disk -> host RAM: Orbax-restore into the host tier WITHOUT
        consuming a device slot, so a later promotion is cheap.  Idempotent
        for already-RAM-resident names (slot or host)."""
        if not name or not all(c.isalnum() or c in "._-" for c in name):
            raise AdapterError(
                f"invalid adapter name {name!r}: use [A-Za-z0-9._-]")
        if self.host_cache_slots <= 0:
            raise AdapterError("host cache disabled (host_cache_slots=0)")
        with self._mutate_lock:
            with self._lock:
                if name in self._adapters or name in self._host:
                    return False  # already RAM-resident
            t0 = self._clock()
            weights, alpha, rank = load_adapter_checkpoint(checkpoint_path)
            self._note_load(TIER_DISK, self._clock() - t0)
            with self._lock:
                self._host_put(name, weights, alpha, rank, checkpoint_path)
                self._note_transition(TIER_DISK, TIER_HOST)
        logger.info("prefetched adapter %s into host RAM (rank %d)",
                    name, rank)
        return True

    def evict_host(self, name: str) -> bool:
        """Host RAM -> disk: drop the host copy (the checkpoint remains
        the backstop).  Slot-resident adapters are untouched — demote
        first."""
        with self._mutate_lock, self._lock:
            if self._host.pop(name, None) is None:
                return False
            self._note_transition(TIER_HOST, TIER_DISK)
        logger.info("evicted adapter %s from host RAM", name)
        return True
