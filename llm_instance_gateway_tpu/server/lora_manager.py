"""LoRA adapter lifecycle on the serving process: registry + Orbax hot-swap.

This replaces the reference's vLLM-side adapter machinery: where the sidecar
POSTs ``/v1/load_lora_adapter`` and vLLM pulls safetensors into CUDA slots
(``tools/dynamic-lora-sidecar/sidecar/sidecar.py:177-213``), our server
restores an **Orbax checkpoint** directly into the pre-allocated JAX slot
buffers (``models.lora``) — no recompilation, no process restart, and the
swap is one device-buffer write (BASELINE.json north star: "hot-swaps
adapters into a JAX/XLA serving process via Orbax restore").

Checkpoint layout (written by ``save_adapter`` / the training pipeline):
a pytree ``{"meta": {"alpha": f, "rank": r}, "weights": {target: {"a": ...,
"b": ...}}}`` saved with ``orbax.checkpoint.PyTreeCheckpointer``.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from llm_instance_gateway_tpu.models import lora as lora_lib

logger = logging.getLogger(__name__)


class AdapterError(Exception):
    pass


class AdapterBusyError(AdapterError):
    """Adapter has in-flight requests pinned to its slot (HTTP 409).

    Unloading a slot that live decodes still read would silently degrade
    those requests to base-model output — and a subsequent load() into the
    recycled slot would hand them a *different tenant's* weights.  The
    sidecar reconciler simply retries on its next pass once traffic drains.
    """


@dataclass
class AdapterInfo:
    name: str
    slot: int
    rank: int
    alpha: float
    source: str  # checkpoint path or "inline"


def save_adapter(path: str, weights: dict, alpha: float, rank: int) -> None:
    """Write an adapter checkpoint (numpy pytree) via Orbax."""
    import orbax.checkpoint as ocp

    tree = {
        "meta": {"alpha": np.float32(alpha), "rank": np.int32(rank)},
        "weights": {
            t: {k: np.asarray(v, np.float32) for k, v in tv.items()}
            for t, tv in weights.items()
        },
    }
    ocp.PyTreeCheckpointer().save(path, tree)


def load_adapter_checkpoint(path: str) -> tuple[dict, float, int]:
    import orbax.checkpoint as ocp

    tree = ocp.PyTreeCheckpointer().restore(path)
    meta = tree["meta"]
    return tree["weights"], float(meta["alpha"]), int(meta["rank"])


class LoRAManager:
    """Thread-safe adapter registry bound to the engine's slot buffers.

    Mirrors the metric semantics of ``vllm:lora_requests_info``
    (``backend/vllm/metrics.go:19-32``): ``running_adapters`` is the set the
    gateway's affinity filter matches against; ``max_slots`` is max_lora.
    """

    def __init__(self, cfg, dtype=jnp.bfloat16, mesh=None):
        self.cfg = cfg
        self._lock = threading.Lock()
        # Serializes whole load/unload operations: the buffer update is a
        # read-modify-write of self.buffers, and concurrent HTTP admin calls
        # run in separate executor threads — without this, the second writer
        # would silently drop the first one's weights.
        self._mutate_lock = threading.Lock()
        self._adapters: dict[str, AdapterInfo] = {}
        self._active: dict[str, int] = {}  # name -> in-flight request count
        self._free_slots = list(range(cfg.max_lora_slots))
        self.buffers = lora_lib.init_lora_buffers(cfg, dtype=dtype)
        # Sharded serving: pin slot buffers to the engine's mesh so the delta
        # matmuls compose with the column-sharded base projections without
        # resharding (parallel/sharding.py lora_specs).
        self._mesh = mesh
        if mesh is not None:
            from llm_instance_gateway_tpu.parallel import sharding as sharding_lib

            self._lora_specs = sharding_lib.lora_specs(cfg)
            self.buffers = sharding_lib.shard_pytree(
                self.buffers, self._lora_specs, mesh)

    def _pin(self, buffers):
        """Re-pin buffers to the mesh after an eager .at[].set mutation."""
        if self._mesh is None:
            return buffers
        from llm_instance_gateway_tpu.parallel import sharding as sharding_lib

        return sharding_lib.shard_pytree(buffers, self._lora_specs, self._mesh)

    # -- queries -----------------------------------------------------------
    def running_adapters(self) -> list[str]:
        with self._lock:
            return sorted(self._adapters)

    def adapter_ranks(self) -> dict[str, int]:
        """Resident adapter name -> LoRA rank — the heterogeneity signal
        the gateway's rank-aware fair-share weighting consumes (exported
        as the ``adapter_ranks`` label of ``tpu:lora_requests_info``)."""
        with self._lock:
            return {name: info.rank for name, info in self._adapters.items()}

    @property
    def max_slots(self) -> int:
        return self.cfg.max_lora_slots

    def slot_for(self, adapter_name: str | None) -> int:
        """Slot id for a request (-1 = base model). Raises if not resident."""
        if adapter_name is None:
            return -1
        with self._lock:
            info = self._adapters.get(adapter_name)
        if info is None:
            raise AdapterError(f"adapter {adapter_name!r} is not loaded")
        return info.slot

    def acquire(self, adapter_name: str | None) -> int:
        """Resolve AND pin: the slot cannot be unloaded/recycled until the
        matching ``release``.  The engine acquires at admission and releases
        at finish, so live decodes never read a repurposed slot buffer."""
        if adapter_name is None:
            return -1
        with self._lock:
            info = self._adapters.get(adapter_name)
            if info is None:
                raise AdapterError(f"adapter {adapter_name!r} is not loaded")
            self._active[adapter_name] = self._active.get(adapter_name, 0) + 1
            return info.slot

    def release(self, adapter_name: str | None) -> None:
        if adapter_name is None:
            return
        with self._lock:
            n = self._active.get(adapter_name, 0)
            if n <= 1:
                self._active.pop(adapter_name, None)
            else:
                self._active[adapter_name] = n - 1

    def active_requests(self, adapter_name: str) -> int:
        with self._lock:
            return self._active.get(adapter_name, 0)

    # -- mutations ---------------------------------------------------------
    def load(
        self,
        name: str,
        weights: dict | None = None,
        alpha: float = 16.0,
        rank: int = 8,
        checkpoint_path: str | None = None,
    ) -> AdapterInfo:
        """Load an adapter into a free slot (idempotent per name)."""
        if not name or not all(c.isalnum() or c in "._-" for c in name):
            raise AdapterError(
                f"invalid adapter name {name!r}: use [A-Za-z0-9._-] "
                "(names flow into Prometheus labels and routing configs)"
            )
        with self._mutate_lock:
            with self._lock:
                if name in self._adapters:
                    return self._adapters[name]  # resident (sidecar.py:185-188)
                if not self._free_slots:
                    raise AdapterError(
                        f"no free adapter slots (max {self.cfg.max_lora_slots})"
                    )
                slot = self._free_slots.pop(0)
            try:
                if checkpoint_path is not None:
                    weights, alpha, rank = load_adapter_checkpoint(checkpoint_path)
                if weights is None:
                    raise AdapterError("either weights or checkpoint_path required")
                self.buffers = self._pin(lora_lib.load_adapter(
                    self.buffers, self.cfg, slot, weights, alpha, rank
                ))
            except Exception:
                with self._lock:
                    self._free_slots.insert(0, slot)
                raise
            info = AdapterInfo(
                name=name, slot=slot, rank=rank, alpha=alpha,
                source=checkpoint_path or "inline",
            )
            with self._lock:
                self._adapters[name] = info
        logger.info("loaded adapter %s into slot %d (rank %d)", name, slot, rank)
        return info

    def unload(self, name: str) -> bool:
        with self._mutate_lock:
            with self._lock:
                # Busy-check and pop atomically: a concurrent acquire() holds
                # the same lock and increments only while the name is still
                # registered, so no request can slip in after the check.
                active = self._active.get(name, 0)
                if active:
                    raise AdapterBusyError(
                        f"adapter {name!r} has {active} in-flight request(s); "
                        "retry after they drain"
                    )
                info = self._adapters.pop(name, None)
            if info is None:
                return False
            self.buffers = self._pin(
                lora_lib.unload_adapter(self.buffers, self.cfg, info.slot))
            with self._lock:
                self._free_slots.append(info.slot)
        logger.info("unloaded adapter %s from slot %d", name, info.slot)
        return True
