"""Tokenizers for the model server.

Default is a self-contained byte-level tokenizer (UTF-8 bytes + specials) so
the server runs hermetically — this environment has zero egress, and the
reference's tokenization also lives outside the repo (inside vLLM).  Real
checkpoints bring their own tokenizer: ``HFTokenizer`` wraps a *local*
``transformers`` tokenizer directory when one is provided.
"""

from __future__ import annotations

BOS_ID = 256
EOS_ID = 257
PAD_ID = 258
BYTE_VOCAB = 259


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are bytes, then BOS/EOS/PAD."""

    vocab_size = BYTE_VOCAB
    bos_id = BOS_ID
    eos_id = EOS_ID
    pad_id = PAD_ID

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return [BOS_ID] + ids if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Local HuggingFace tokenizer wrapper (no network access)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer  # local files only

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = self._tok.vocab_size
        # Explicit None checks: `or 0` would turn a *missing* special token
        # into token id 0 — the engine would then hard-stop any row that
        # samples id 0 (eos) or prepend a junk token to every prompt (bos).
        # The engine already handles eos_id=None (no device-side stop).
        self.bos_id = self._tok.bos_token_id  # may be None: no BOS prepended
        self.eos_id = self._tok.eos_token_id  # may be None: length-capped only
        self.pad_id = (
            self._tok.pad_token_id if self._tok.pad_token_id is not None
            else (self.eos_id if self.eos_id is not None else 0)
        )

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            return [self.bos_id] + ids
        return ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> str | None:
        """The model's own chat template rendered over OpenAI-shaped
        messages (with the generation prompt appended), or None when the
        checkpoint's tokenizer ships no template — the server then falls
        back to the plain role-prefix transcript.  Matches vLLM's
        behavior: serving a chat model with its trained template is a
        correctness issue, not cosmetics."""
        if not getattr(self._tok, "chat_template", None):
            return None
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True)


def load_tokenizer(path: str | None = None):
    return HFTokenizer(path) if path else ByteTokenizer()
