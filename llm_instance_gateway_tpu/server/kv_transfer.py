"""Cross-engine prefill/decode disaggregation: the KV-block handoff plane.

Disaggregated serving (InfiniLoRA, CaraServe; PAPERS.md) runs prefill and
decode on SEPARATE replicas so a long prefill never steals MXU time from
co-resident decode slots.  The engine already disaggregates the two phases
*inside* one process (``decode_wait`` parks prefilled KV off-cache until a
slot frees); this module is the missing cross-process seam:

- ``PrefillHandoff``: a prefilled request's paged KV blocks (raw engine-dtype
  or int8-quantized lanes, per layer) plus the sampling carry — the first
  sampled token, its logprob info, position, and everything needed to rebuild
  the ``Request`` on the decode side (sampling params, stop ids, the original
  OpenAI body for envelope shaping).
- A compact self-describing wire format (``to_bytes``/``from_bytes``): one
  JSON header + raw little-endian array payloads.  No pickle — handoffs cross
  trust boundaries between replicas.

The engine half lives in ``server/engine.py``: ``Engine.prefill_only()``
produces a handoff on a prefill-role replica (no decode slot touched);
``Engine.attach_prefilled()`` admits it straight into a decode slot on a
decode-role replica, skipping prefill entirely.  The gateway half
(``gateway/scheduling/scheduler.py`` pool roles + ``gateway/proxy.py``
two-hop relay) routes one request across both.

Quantization note (token parity): the int8 wire lane quantizes with the
exact math of the engine's KV-cache quantizer (``transformer._kv_quantize``
— symmetric per-(position, kv-head) max-abs, f32 scales).  Dequantize →
re-quantize is value-stable (the max element maps to exactly ±127, so the
scale round-trips), which is what keeps a quantized decode engine's cache
bit-identical to collocated serving when fed either wire lane.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field

import numpy as np

_MAGIC = b"LIGKVH1\n"

# Wire dtypes are whitelisted: the header is attacker-influencable text and
# np.dtype() resolves arbitrary strings (object dtypes included).
_WIRE_DTYPES = ("float32", "float16", "bfloat16", "int8")


def _np_dtype(name: str):
    if name not in _WIRE_DTYPES:
        raise ValueError(f"unsupported handoff dtype {name!r}")
    if name == "bfloat16":
        import ml_dtypes  # jax dependency; always importable next to it

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _quantize_host(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host mirror of ``transformer._kv_quantize``: [..., hd] -> (int8,
    f32 scale [...]) — same f32 math and half-to-even rounding, so a wire
    round-trip re-quantizes to identical values."""
    xf = np.asarray(x, np.float32)
    s = np.maximum(np.max(np.abs(xf), axis=-1) / 127.0, 1e-8)
    q = np.clip(np.round(xf / s[..., None]), -127, 127).astype(np.int8)
    return q, s.astype(np.float32)


@dataclass
class PrefillHandoff:
    """One prefilled request, serializable across an engine boundary.

    KV layout: ``k``/``v`` are ``[L, n, Kh, hd]`` trimmed to the true prompt
    length (no bucket padding crosses the wire).  ``kv_format`` is ``"raw"``
    (engine compute dtype, recorded in ``kv_dtype``) or ``"int8"``
    (``k``/``v`` int8 + ``k_scale``/``v_scale`` f32 ``[L, n, Kh]``).
    """

    request_id: str
    prompt_tokens: list[int]
    n: int
    adapter: str | None
    max_new_tokens: int
    sampling: dict
    stop_token_ids: list[int]
    # Tokenized multi-token stop strings (engine Request.stop_sequences):
    # the decode hop's device-side stop automata need the same suffixes
    # the prefill hop validated.
    stop_sequences: list[list[int]]
    logprobs: int | None
    # Sampling carry: the prefill's sampled first token and its logprob info.
    first_token: int
    first_lp: float | None
    first_top_vals: list[float] | None
    first_top_ids: list[int] | None
    t_submit: float
    kv_format: str
    kv_dtype: str
    k: np.ndarray
    v: np.ndarray
    k_scale: np.ndarray | None = None
    v_scale: np.ndarray | None = None
    # Original OpenAI request body (dict) — the decode hop shapes the client
    # envelope (stream/stop/logprobs/model name) from it.
    body: dict | None = None
    # End-to-end tracing: the request's trace id rides the wire so the
    # decode hop tags its spans with the SAME id even when the transport
    # drops the x-lig-trace-id header (tracing.py).
    trace_id: str | None = None
    _extra: dict = field(default_factory=dict)

    # -- KV access ----------------------------------------------------------

    @property
    def n_layers(self) -> int:
        return int(self.k.shape[0])

    @property
    def kv_tokens(self) -> int:
        return int(self.k.shape[1])

    def kv_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(k, v) as float arrays ``[L, n, Kh, hd]`` ready for cache insert
        (int8 wire lanes dequantize here; the decode engine's insert seam
        re-quantizes if ITS cache is int8)."""
        if self.kv_format == "int8":
            k = self.k.astype(np.float32) * self.k_scale[..., None]
            v = self.v.astype(np.float32) * self.v_scale[..., None]
            return k, v
        return self.k, self.v

    def first_lp_info(self):
        """(lp, top_vals, top_ids) numpy tuple or None — the shape
        ``Engine._store_logprobs`` consumes."""
        if self.first_lp is None:
            return None
        return (np.float32(self.first_lp),
                np.asarray(self.first_top_vals, np.float32),
                np.asarray(self.first_top_ids, np.int32))

    # -- wire format --------------------------------------------------------

    def to_bytes(self) -> bytes:
        arrays = [("k", self.k), ("v", self.v)]
        if self.kv_format == "int8":
            arrays += [("k_scale", self.k_scale), ("v_scale", self.v_scale)]
        meta = {
            "request_id": self.request_id,
            "prompt_tokens": list(map(int, self.prompt_tokens)),
            "n": self.n,
            "adapter": self.adapter,
            "max_new_tokens": self.max_new_tokens,
            "sampling": self.sampling,
            "stop_token_ids": list(map(int, self.stop_token_ids)),
            "stop_sequences": [list(map(int, s))
                               for s in self.stop_sequences],
            "logprobs": self.logprobs,
            "first_token": self.first_token,
            "first_lp": self.first_lp,
            "first_top_vals": self.first_top_vals,
            "first_top_ids": self.first_top_ids,
            "t_submit": self.t_submit,
            "kv_format": self.kv_format,
            "kv_dtype": self.kv_dtype,
            "body": self.body,
            "trace_id": self.trace_id,
            "arrays": [
                {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)}
                for name, a in arrays
            ],
        }
        head = json.dumps(meta).encode()
        out = [_MAGIC, struct.pack("<I", len(head)), head]
        out += [np.ascontiguousarray(a).tobytes() for _, a in arrays]
        return b"".join(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "PrefillHandoff":
        if data[: len(_MAGIC)] != _MAGIC:
            raise ValueError("not a PrefillHandoff payload (bad magic)")
        off = len(_MAGIC)
        (head_len,) = struct.unpack_from("<I", data, off)
        off += 4
        meta = json.loads(data[off:off + head_len].decode())
        off += head_len
        parsed: dict[str, np.ndarray] = {}
        for spec in meta.pop("arrays"):
            dt = _np_dtype(spec["dtype"])
            shape = [int(d) for d in spec["shape"]]
            if any(d < 0 for d in shape):
                # A negative dim makes nbytes negative: the truncation
                # check below would pass and the cursor would move
                # BACKWARDS, aliasing array payloads.  Reject at the parse
                # boundary (header text is attacker-influencable).
                raise ValueError(f"negative dimension in handoff shape "
                                 f"{shape}")
            count = int(np.prod(shape, dtype=np.int64))
            nbytes = count * dt.itemsize
            if off + nbytes > len(data):
                raise ValueError("truncated handoff payload")
            parsed[spec["name"]] = np.frombuffer(
                data, dtype=dt, count=count, offset=off
            ).reshape(shape)
            off += nbytes
        samp = meta.get("sampling") or {}
        if samp.get("logit_bias"):
            # JSON stringifies int keys; restore them.
            samp["logit_bias"] = {
                int(k): float(v) for k, v in samp["logit_bias"].items()}
        return cls(
            request_id=meta["request_id"],
            prompt_tokens=[int(t) for t in meta["prompt_tokens"]],
            n=int(meta["n"]),
            adapter=meta["adapter"],
            max_new_tokens=int(meta["max_new_tokens"]),
            sampling=samp,
            stop_token_ids=[int(t) for t in meta["stop_token_ids"]],
            stop_sequences=[[int(t) for t in s]
                            for s in meta.get("stop_sequences") or []],
            logprobs=meta["logprobs"],
            first_token=int(meta["first_token"]),
            first_lp=meta["first_lp"],
            first_top_vals=meta["first_top_vals"],
            first_top_ids=meta["first_top_ids"],
            t_submit=float(meta.get("t_submit") or 0.0),
            kv_format=meta["kv_format"],
            kv_dtype=meta["kv_dtype"],
            k=parsed["k"],
            v=parsed["v"],
            k_scale=parsed.get("k_scale"),
            v_scale=parsed.get("v_scale"),
            body=meta.get("body"),
            trace_id=meta.get("trace_id"),
        )


def export_handoff(request, k, v, n: int, first_token: int, lp_info=None,
                   quantize: str | None = None) -> PrefillHandoff:
    """Build a handoff from a prefill's outputs.

    ``k``/``v`` are the prefill programs' ``[L, 1, S_bucket, Kh, hd]`` device
    (or host) arrays; only the first ``n`` positions cross the wire.
    ``quantize="int8"`` halves the wire size (per-(position, kv-head) f32
    scales, same math as the engine's int8 KV cache).
    """
    if quantize not in (None, "int8"):
        raise ValueError(f"quantize={quantize!r}: only 'int8' (or None)")
    k_np = np.asarray(k)[:, 0, :n]
    v_np = np.asarray(v)[:, 0, :n]
    sp = request.sampling
    samp = {
        "temperature": sp.temperature,
        "top_k": sp.top_k,
        "top_p": sp.top_p,
        "seed": sp.seed,
        "presence_penalty": sp.presence_penalty,
        "frequency_penalty": sp.frequency_penalty,
        "logit_bias": ({str(t): float(b) for t, b in sp.logit_bias.items()}
                       if sp.logit_bias else None),
    }
    first_lp = first_top_vals = first_top_ids = None
    if lp_info is not None and request.logprobs is not None:
        lp, top_v, top_i = lp_info
        first_lp = float(np.asarray(lp))
        first_top_vals = np.asarray(top_v, np.float32).tolist()
        first_top_ids = np.asarray(top_i, np.int32).tolist()
    # One carry dict for both wire lanes: a new carried field added here
    # reaches int8 AND raw handoffs, instead of silently diverging.
    carry = dict(
        request_id=request.request_id,
        prompt_tokens=list(request.prompt_tokens), n=n,
        adapter=request.adapter,
        max_new_tokens=request.max_new_tokens,
        sampling=samp, stop_token_ids=list(request.stop_token_ids),
        stop_sequences=[list(s) for s in request.stop_sequences],
        logprobs=request.logprobs, first_token=int(first_token),
        first_lp=first_lp, first_top_vals=first_top_vals,
        first_top_ids=first_top_ids, t_submit=request.t_submit,
        kv_dtype=str(k_np.dtype),
    )
    if quantize == "int8":
        kq, ks = _quantize_host(k_np)
        vq, vs = _quantize_host(v_np)
        return PrefillHandoff(**carry, kv_format="int8",
                              k=kq, v=vq, k_scale=ks, v_scale=vs)
    return PrefillHandoff(**carry, kv_format="raw", k=k_np, v=v_np)


def make_request(handoff: PrefillHandoff):
    """Rebuild the engine ``Request`` a handoff describes (decode side)."""
    from llm_instance_gateway_tpu.server.engine import Request, SamplingParams

    s = handoff.sampling
    bias = s.get("logit_bias")
    if bias:
        # export_handoff stringifies keys for the JSON header; normalize
        # here so a handoff attached WITHOUT a wire round-trip carries the
        # same int-keyed dict the engine validates against.
        bias = {int(k): float(v) for k, v in bias.items()}
    return Request(
        prompt_tokens=list(handoff.prompt_tokens),
        max_new_tokens=handoff.max_new_tokens,
        sampling=SamplingParams(
            temperature=float(s.get("temperature", 0.0)),
            top_k=int(s.get("top_k", 0)),
            top_p=float(s.get("top_p", 1.0)),
            seed=s.get("seed"),
            presence_penalty=float(s.get("presence_penalty", 0.0)),
            frequency_penalty=float(s.get("frequency_penalty", 0.0)),
            logit_bias=bias,
        ),
        adapter=handoff.adapter,
        stop_token_ids=tuple(handoff.stop_token_ids),
        stop_sequences=tuple(tuple(s) for s in handoff.stop_sequences),
        request_id=handoff.request_id,
        logprobs=handoff.logprobs,
    )
