"""Model-server HTTP API: OpenAI-style inference + admin + metrics.

The surface the gateway (and the LoRA sidecar) expects from a pool replica —
the union of what vLLM exposed to the reference:

- ``POST /v1/completions``        OpenAI completions (prompt string or token ids)
- ``POST /v1/chat/completions``   chat (checkpoint tokenizer's own chat
                                  template when it ships one, else a
                                  role-prefix transcript)
- ``GET  /v1/models``             base model + resident adapters (sidecar diff
                                  source, ``sidecar.py:140-155``)
- ``POST /v1/load_lora_adapter``  ``{"lora_name": ..., "lora_path": ...}``
                                  (vLLM-compatible field names, sidecar.py:177-195)
- ``POST /v1/unload_lora_adapter`` ``{"lora_name": ...}``
- ``GET  /metrics``               tpu:* exposition (gateway scrape contract,
                                  including the tpu:prefill_seconds /
                                  tpu:handoff_seconds /
                                  tpu:decode_step_seconds histograms)
- ``GET  /debug/traces``          recent request traces (span JSON,
                                  ``?trace_id=`` filter, ``?since=<seq>``
                                  incremental cursor — the fleet
                                  collector's delta poll)
- ``GET  /debug/events``          replica-side flight recorder (admission
                                  rejections, handoff refusals, drain
                                  transitions; ``?since=`` cursor)
- ``GET  /debug/usage``           per-adapter capacity attribution snapshot
                                  (step-seconds / tokens / KV block-seconds
                                  per {adapter, phase} + pool waste;
                                  server/usage.py)
- ``GET  /debug/profile``         step-timeline profiler snapshot (per-
                                  dispatch wall / host-sync gap / idle
                                  attribution + recent dispatch records;
                                  server/profiler.py, rendered by
                                  tools/profile_report.py)
- ``GET  /health``                200 once the engine loop is up

Tracing: every inference request adopts the ``x-lig-trace-id`` header (or
mints one), records engine-phase spans (queue wait, prefill, decode, handoff
serialize/deserialize/attach) into a bounded ring, echoes the id on every
response, and returns its spans in a compact ``x-lig-spans`` header so the
gateway proxy can merge the cross-process timeline into one trace.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import queue as queue_mod
import time

from aiohttp import web

from llm_instance_gateway_tpu.server import metrics as metrics_mod
from llm_instance_gateway_tpu.server.engine import (
    Engine,
    EngineDraining,
    MAX_LOGIT_BIAS,
    Request,
    SamplingParams,
)
from llm_instance_gateway_tpu.server.lora_manager import (
    AdapterBusyError,
    AdapterError,
    LoRAManager,
)
from llm_instance_gateway_tpu.server.tokenizer import load_tokenizer
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu import tracing

logger = logging.getLogger(__name__)

MAX_N = 8          # n / best_of cap (each candidate occupies engine capacity)
MAX_LOGPROBS = 5   # engine.LOGPROB_TOPK — the OpenAI completions maximum
# OpenAI chat accepts top_logprobs up to 20; the engine computes a top-5
# device-side (LOGPROB_TOPK), so requests above MAX_LOGPROBS are accepted
# and truncated, with the cap noted in the response's logprobs object
# (README "OpenAI surface divergences").
OPENAI_MAX_TOP_LOGPROBS = 20

_FFFD = "�"
# A partial UTF-8 character pending completion by a later byte-fallback
# token is at most 3 bytes (a 4-byte sequence missing its last byte); its
# decode renders at most that many replacement chars, so a longer trailing
# U+FFFD run is genuine model output, never an artifact.
_MAX_PARTIAL_FFFD = 3


class ModelServer:
    def __init__(self, engine: Engine, tokenizer, model_name: str,
                 lora_manager: LoRAManager | None = None,
                 aliases: set[str] | None = None):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        # Extra names the base model answers to (e.g. the CLI preset alias
        # when a checkpoint brought its own name) — existing clients keep
        # working across a checkpoint swap.
        self.aliases = {model_name} | (aliases or set())
        self.lora = lora_manager
        # Per-process span ring served by /debug/traces (tracing.py).
        self.tracer = tracing.Tracer()
        # Server-side flight recorder (events.py): admission rejections,
        # handoff failures, drain/role changes — served by /debug/events
        # and counted in the tpu:events_total family on /metrics.
        self.events = events_mod.EventJournal()
        if engine is not None and hasattr(engine, "event_sink"):
            # The engine reports lifecycle events (drain start) through
            # this seam without importing any HTTP-layer machinery.
            engine.event_sink = self.events.emit

    def build_app(self) -> web.Application:
        # Deterministic fault injection (gateway/faultinject.py): the
        # LIG_FAULTS env var names a JSON schedule; the middleware applies
        # blackhole/brownout/error/disconnect faults to /v1/* handlers so
        # the 3-process e2e chaos stack exercises the REAL server binary.
        middlewares = []
        faults_path = os.environ.get("LIG_FAULTS")
        if faults_path:
            from llm_instance_gateway_tpu.gateway import faultinject

            schedule = faultinject.FaultSchedule.from_file(faults_path)
            middlewares.append(
                faultinject.aiohttp_middleware(schedule,
                                               journal=self.events))
            logger.warning("fault injection armed from %s: %s",
                           faults_path, schedule.describe())
        app = web.Application(middlewares=middlewares)
        app.router.add_post("/v1/completions", self.handle_completions)
        app.router.add_post("/v1/chat/completions", self.handle_chat)
        # Cross-engine disaggregation hops (gateway/proxy.py two-hop relay).
        app.router.add_post("/v1/prefill", self.handle_prefill)
        app.router.add_post("/v1/attach", self.handle_attach)
        app.router.add_post("/v1/prefill/release", self.handle_release)
        app.router.add_get("/v1/models", self.handle_models)
        app.router.add_post("/v1/load_lora_adapter", self.handle_load_adapter)
        app.router.add_post("/v1/unload_lora_adapter", self.handle_unload_adapter)
        # Residency-ladder verbs (placement plane, server/lora_manager.py):
        # demote = slot -> host RAM, prefetch = disk -> host RAM (no slot),
        # evict = host RAM -> disk.  The lora_sidecar's planner mode drives
        # these from the gateway's /debug/placement decisions.
        app.router.add_post("/v1/demote_lora_adapter", self.handle_demote_adapter)
        app.router.add_post("/v1/prefetch_lora_adapter", self.handle_prefetch_adapter)
        app.router.add_post("/v1/evict_lora_adapter", self.handle_evict_adapter)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/debug/traces", self.handle_debug_traces)
        app.router.add_get("/debug/events", self.handle_debug_events)
        app.router.add_get("/debug/usage", self.handle_debug_usage)
        app.router.add_get("/debug/profile", self.handle_debug_profile)
        app.router.add_get("/debug/kv", self.handle_debug_kv)
        app.router.add_get("/health", self.handle_health)
        return app

    # -- tracing helpers ----------------------------------------------------
    @staticmethod
    def _trace_id_for(request: web.Request) -> str:
        return (tracing.header_trace_id(request.headers)
                or tracing.new_trace_id())

    @staticmethod
    def _engine_spans(req, decode_start: float | None = None,
                      with_decode: bool = True) -> list:
        """Span triples derived from a finished engine Request's wall-clock
        stamps: queue wait (submit -> prefill start), prefill compute
        (prefill start -> first token), decode (first token -> done).
        ``decode_start`` overrides the decode span's start (attach path:
        the first token predates THIS engine; decode begins at attach)."""
        spans = []
        if req.t_submit and req.t_prefill_start:
            spans.append(("engine.queue_wait", req.t_submit,
                          max(req.t_submit, req.t_prefill_start)))
        if req.t_prefill_start and req.t_first_token:
            spans.append(("engine.prefill", req.t_prefill_start,
                          max(req.t_prefill_start, req.t_first_token)))
        if with_decode:
            start = decode_start or req.t_first_token
            end = req.t_done or time.time()
            if start and end >= start:
                spans.append(("engine.decode", start, end))
        return spans

    def _record_spans(self, trace_id: str, spans, status: str = "ok") -> dict:
        """Record spans locally and build the response headers that echo the
        trace id and carry the spans back to the gateway."""
        for name, s, e in spans:
            self.tracer.record(trace_id, name, s, e)
        self.tracer.annotate(trace_id, model=self.model_name, status=status)
        headers = {tracing.TRACE_HEADER: trace_id}
        if spans and self.tracer.sampled(trace_id):
            headers[tracing.SPANS_HEADER] = tracing.wire_spans(spans)
        return headers

    def _reject(self, status: int, message: str, trace_id: str | None,
                reason: str) -> web.Response:
        """Capacity/lifecycle rejection: journal it (the flight recorder
        correlates replica-side 429/503/422 with gateway-side picks via the
        trace id) and answer the usual error envelope.  Plain 400 client
        errors do NOT come through here — they are request defects, not
        system events."""
        self.events.emit(events_mod.ADMISSION_REJECT, trace_id or "",
                         status=status, reason=reason)
        return _err(status, message, trace_id)

    # -- helpers -----------------------------------------------------------
    def _resolve_model(self, requested: str) -> str | None:
        """Adapter name if the request targets a resident adapter, else None
        (base model).  Unknown names raise AdapterError -> 404, matching
        vLLM's behavior the sidecar relies on."""
        if requested in ("", None) or requested in self.aliases:
            return None
        if self.lora is not None and requested in self.lora.running_adapters():
            return requested
        raise AdapterError(f"model {requested!r} is not served by this replica")

    def _encode_prompt(self, body: dict) -> list[int]:
        prompt = body.get("prompt", "")
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            return list(prompt)  # pre-tokenized
        if isinstance(prompt, list):
            prompt = " ".join(str(p) for p in prompt)
        return self.tokenizer.encode(str(prompt))

    def _make_request(self, body: dict, prompt_tokens: list[int], adapter,
                      logprobs: int | None = None,
                      candidate: int = 0) -> Request:
        seed = body.get("seed")
        if seed is not None:
            # n/best_of fan-out with one seed would produce identical
            # candidates (the draw depends only on seed+position); folding
            # the candidate index keeps each choice distinct yet the whole
            # response reproducible (vLLM does the same).
            seed = int(seed) + candidate
        presence = float(body.get("presence_penalty") or 0.0)
        frequency = float(body.get("frequency_penalty") or 0.0)
        raw_bias = body.get("logit_bias") or None
        logit_bias = ({int(k): float(v) for k, v in raw_bias.items()}
                      if raw_bias else None)
        return Request(
            prompt_tokens=prompt_tokens,
            max_new_tokens=int(body.get("max_tokens", 64)),
            sampling=SamplingParams(
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                seed=seed,
                presence_penalty=presence,
                frequency_penalty=frequency,
                logit_bias=logit_bias,
            ),
            adapter=adapter,
            stop_sequences=self._encode_stops(body),
            logprobs=logprobs,
        )

    def _encode_stops(self, body: dict) -> tuple[tuple[int, ...], ...]:
        """Tokenized OpenAI ``stop`` strings for the engine's device-side
        suffix automata — an EARLY-FREEZE accelerator, not the oracle.
        The text-level scan (_wait_with_stops / _truncate_at_stop) stays
        authoritative: an automaton hit only ends generation at a token
        tail whose decode CONTAINS the stop string (encode/decode
        round-trip), which the text truncation then cuts identically,
        while a stop spelled by a different token split simply misses the
        automaton and is caught by the text scan as before.  So this can
        only stop generation earlier, never change the response."""
        stop = body.get("stop")
        stops = ([stop] if isinstance(stop, str)
                 else [s for s in stop if isinstance(s, str)]
                 if isinstance(stop, list) else [])
        out = []
        for s in stops:
            if not s:
                continue
            try:
                ids = self.tokenizer.encode(s)
                if ids and self.tokenizer.decode(list(ids)) == s:
                    out.append(tuple(int(t) for t in ids))
            except Exception:  # pragma: no cover - defensive: odd tokenizer
                continue
        return tuple(out)

    @staticmethod
    def _parse_choice_params(body: dict) -> tuple[int, int, int | None, list[str]]:
        """(n, best_of, logprobs, stops) with OpenAI validation rules."""
        n = int(body.get("n", 1))
        best_of = int(body.get("best_of", max(n, 1)))
        if not 1 <= n <= MAX_N:
            raise ValueError(f"n must be in [1, {MAX_N}]")
        if not n <= best_of <= MAX_N:
            raise ValueError(f"best_of must be in [n, {MAX_N}]")
        logprobs = body.get("logprobs")
        if logprobs is not None:
            logprobs = int(logprobs)
            if not 0 <= logprobs <= MAX_LOGPROBS:
                raise ValueError(f"logprobs must be in [0, {MAX_LOGPROBS}]")
        stop = body.get("stop")
        if stop is None:
            stops: list[str] = []
        elif isinstance(stop, str):
            stops = [stop]
        elif (isinstance(stop, list)
              and all(isinstance(s, str) for s in stop)):
            stops = list(stop)
        else:
            raise ValueError("stop must be a string or a list of strings")
        if len(stops) > 4:
            raise ValueError("at most 4 stop sequences are supported")
        for name in ("presence_penalty", "frequency_penalty"):
            val = float(body.get(name) or 0.0)  # null == unset
            if not -2.0 <= val <= 2.0:
                raise ValueError(f"{name} must be in [-2, 2]")
        bias = body.get("logit_bias")
        if bias is not None:
            if not isinstance(bias, dict):
                raise ValueError("logit_bias must be an object")
            if len(bias) > MAX_LOGIT_BIAS:
                raise ValueError("logit_bias supports at most "
                                 f"{MAX_LOGIT_BIAS} entries")
            for k, v in bias.items():
                if not -100.0 <= float(v) <= 100.0:
                    raise ValueError("logit_bias values must be in "
                                     "[-100, 100]")
                int(k)  # token ids must be integral (ValueError otherwise)
        return n, best_of, logprobs, [s for s in stops if s]

    def _wait_with_stops(self, req: Request, stops: list[str],
                         timeout_s: float = 600.0,
                         submit: bool = True) -> Request:
        """generate(), plus early cancellation the moment a stop string
        appears in the decoded text (the exact cut happens afterwards in
        _truncate_at_stop — generation must not keep burning the slot).

        Decoding is incremental (only unconsumed tokens) and the stop search
        only rescans a window the new piece could have completed, so a long
        generation stays O(n), not O(n^2), on the executor thread.
        ``submit=False`` waits on an ALREADY-submitted request (the attach
        hop admits through ``engine.attach_prefilled``)."""
        if submit:
            self.engine.submit(req)
        deadline = time.monotonic() + timeout_s
        max_stop = max((len(s) for s in stops), default=0)
        text = ""
        consumed = 0
        while True:
            req.stream_event.wait(0.25)
            req.stream_event.clear()
            done = req.done.is_set()
            n = len(req.output_tokens)
            if stops and n > consumed:
                piece = self.tokenizer.decode(req.output_tokens[consumed:n])
                if piece.endswith("�") and not done:
                    pass  # incomplete UTF-8 tail: re-decode next wake
                else:
                    window_start = max(0, len(text) - max_stop + 1)
                    text += piece
                    consumed = n
                    if any(s in text[window_start:] for s in stops):
                        req.cancelled.set()
                        req.done.wait(30)
                        return req
            if done:
                return req
            if time.monotonic() > deadline:
                req.error = "generation timed out"
                req.cancelled.set()
                return req

    def _truncate_at_stop(self, req: Request, stops: list[str]) -> tuple[str, bool]:
        """Cut text AND the per-token records at the earliest stop match.
        Returns (final text, whether a stop hit)."""
        full = self.tokenizer.decode(req.output_tokens)
        if not stops:
            return full, False
        hits = [(full.index(s), s) for s in stops if s in full]
        if not hits:
            return full, False
        idx, _ = min(hits)
        # Smallest token count whose decoded prefix already contains a stop
        # ("contains a stop" is monotone in the prefix length, so binary
        # search): everything from that token on is post-stop and dropped.
        lo, hi = 1, len(req.output_tokens)
        while lo < hi:
            mid = (lo + hi) // 2
            if any(s in self.tokenizer.decode(req.output_tokens[:mid])
                   for s in stops):
                hi = mid
            else:
                lo = mid + 1
        keep = lo
        del req.output_tokens[keep:]
        del req.output_logprobs[keep:]
        del req.output_top_logprobs[keep:]
        req.finish_reason = "stop"
        return full[:idx], True

    @staticmethod
    def _held_back(cur: str, full: str) -> int:
        """How many trailing replacement chars of ``cur`` (a prefix decode)
        are partial-multi-byte artifacts rather than genuine U+FFFD output.

        A char the model actually emitted survives verbatim into the FULL
        decode at the same index; an artifact resolves into a different
        character once the completing bytes arrive.  So: hold back the
        smallest trailing-U+FFFD suffix whose removal makes ``cur`` agree
        with the full decode, bounded by one UTF-8 char's worth of pending
        bytes (``_MAX_PARTIAL_FFFD``) — beyond that the run is genuine."""
        run = len(cur) - len(cur.rstrip(_FFFD))
        cap = min(run, _MAX_PARTIAL_FFFD)
        for hold in range(cap + 1):
            keep = len(cur) - hold
            if full[:keep] == cur[:keep]:
                return hold
        return cap

    def _per_token_records(self, req: Request, k: int,
                           text_limit: int | None = None):
        """Per-generated-token ``(piece, logprob, deduped_tops)`` rows — the
        ONE walk both logprobs envelopes (completions and chat) build from.

        Piece attribution holds back trailing replacement chars while more
        tokens remain *and the full decode resolves them*: a UTF-8
        character split across byte-fallback tokens is attributed whole to
        its COMPLETING token (predecessors emit ""), while a token that
        GENUINELY decodes to U+FFFD keeps its char in place (``_held_back``
        distinguishes the two; held-back chars are bounded by one UTF-8
        char's max pending bytes).  Either way the pieces' concatenation
        equals the full decode exactly.  ``deduped_tops`` keeps the most
        probable id per surface string (byte-fallback ids can collide).

        ``text_limit`` clips the walk to the RETURNED text (stop-sequence
        truncation is character-granular while the token records are
        token-granular: the kept token completing a stop would otherwise
        leak the stop's tail into the envelope, OpenAI trims it)."""
        rows = []
        committed = ""
        n = len(req.output_tokens)
        full = None  # full decode, computed lazily on the first FFFD tail
        for i in range(n):
            if text_limit is not None and len(committed) >= text_limit:
                break
            cur = self.tokenizer.decode(req.output_tokens[: i + 1])
            if i + 1 < n and cur.endswith(_FFFD):
                if full is None:
                    full = self.tokenizer.decode(req.output_tokens)
                hold = self._held_back(cur, full)
                if hold:
                    cur = cur[:-hold]
            piece = cur[len(committed):]
            if text_limit is not None:
                piece = piece[: max(0, text_limit - len(committed))]
            committed += piece
            lp = (req.output_logprobs[i]
                  if i < len(req.output_logprobs) else None)
            tops: dict[str, float] = {}
            if k > 0 and i < len(req.output_top_logprobs):
                for tok, v in req.output_top_logprobs[i].items():
                    key = self.tokenizer.decode([tok])
                    v = max(v, -1e9)
                    if key not in tops or v > tops[key]:
                        tops[key] = v
            rows.append((piece, None if lp is None else max(lp, -1e9), tops))
        return rows

    def _logprobs_json(self, req: Request, k: int,
                       text_limit: int | None = None) -> dict:
        """OpenAI completions ``logprobs`` object (tokens / token_logprobs /
        top_logprobs / text_offset)."""
        tokens, token_lps, tops, offsets = [], [], [], []
        offset = 0
        for piece, lp, top in self._per_token_records(req, k, text_limit):
            offsets.append(offset)
            offset += len(piece)
            tokens.append(piece)
            token_lps.append(lp)
            if k > 0:
                tops.append(top)
        return {
            "tokens": tokens,
            "token_logprobs": token_lps,
            "top_logprobs": tops if k > 0 else None,
            "text_offset": offsets,
        }

    def _chat_logprobs_json(self, req: Request, top_n: int,
                            text_limit: int | None = None) -> dict:
        """OpenAI CHAT ``logprobs`` object — ``choices[].logprobs.content[]``
        entries with token / logprob / bytes / top_logprobs (the chat form:
        per-token objects with UTF-8 byte arrays, no text_offset — distinct
        envelope over the same ``_per_token_records`` walk the completions
        form uses).  ``bytes`` carries the attributed piece's UTF-8, so the
        concatenation of all bytes arrays equals the content's encoding."""
        content = []
        for piece, lp, top in self._per_token_records(req, top_n, text_limit):
            content.append({
                "token": piece,
                "logprob": lp,
                "bytes": list(piece.encode("utf-8", "surrogatepass")),
                "top_logprobs": [
                    {"token": k, "logprob": v,
                     "bytes": list(k.encode("utf-8", "surrogatepass"))}
                    for k, v in sorted(top.items(), key=lambda kv: -kv[1])
                ][:top_n],
            })
        return {"content": content}

    def _chat_prompt(self, messages: list) -> tuple[str, bool]:
        """Chat messages -> (prompt text, add_bos).  A checkpoint
        tokenizer's own chat template wins (HFTokenizer.apply_chat_template
        — the format the model was TRAINED on); tokenizers without one get
        the plain role-prefix transcript.  Templated prompts encode with
        add_bos=False: most real templates render the BOS token as text,
        and prepending another would feed [BOS, BOS, ...] — a stream the
        model never trained on.  Template failures (role restrictions,
        strict alternation, non-string content) raise ValueError so the
        handler returns a 400, not a 500 — the request was valid OpenAI but
        invalid for THIS model's template, a client-fixable condition."""
        apply = getattr(self.tokenizer, "apply_chat_template", None)
        if apply is not None:
            try:
                templated = apply(messages)
            except Exception as e:  # jinja TemplateError, TypeError, ...
                raise ValueError(
                    f"messages are not renderable by this model's chat "
                    f"template: {e}") from e
            if templated is not None:
                return templated, False
        return "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in messages
        ) + "\nassistant:", True

    @staticmethod
    def _parse_chat_logprobs(body: dict) -> tuple[bool, int, int]:
        """(logprobs flag, EFFECTIVE top-N, REQUESTED top-N) with OpenAI
        chat validation.  The OpenAI range [0, 20] is accepted in full;
        the engine records a device-side top-5 (LOGPROB_TOPK), so the
        effective N truncates there and responses note the cap when it
        bit (``top_logprobs_truncated_to``)."""
        lp_flag = bool(body.get("logprobs"))
        top_n = body.get("top_logprobs")
        if top_n is None:
            return lp_flag, 0, 0
        if not lp_flag:
            raise ValueError("top_logprobs requires logprobs: true")
        top_n = int(top_n)
        if not 0 <= top_n <= OPENAI_MAX_TOP_LOGPROBS:
            raise ValueError(
                f"top_logprobs must be in [0, {OPENAI_MAX_TOP_LOGPROBS}]")
        return lp_flag, min(top_n, MAX_LOGPROBS), top_n

    async def _run(self, req: Request, stops: list[str] | None = None) -> Request:
        loop = asyncio.get_running_loop()
        try:
            if stops:
                return await loop.run_in_executor(
                    None, self._wait_with_stops, req, stops)
            return await loop.run_in_executor(None, self.engine.generate, req)
        except asyncio.CancelledError:
            # Non-streaming client disconnected: free the slot too.
            req.cancelled.set()
            raise

    async def _run_many(self, reqs: list[Request],
                        stops: list[str]) -> list[Request]:
        """Run candidates concurrently; if ANY submit/run fails, cancel the
        siblings (gather alone would leave them decoding for nobody — load
        amplification exactly when capacity is scarce) and re-raise the
        first failure."""
        results = await asyncio.gather(
            *(self._run(r, stops=stops) for r in reqs),
            return_exceptions=True)
        failure = next(
            (r for r in results if isinstance(r, BaseException)), None)
        if failure is not None:
            for r in reqs:
                r.cancelled.set()
            raise failure
        return list(results)

    # -- streaming ---------------------------------------------------------
    async def _stream_sse(self, http_request: web.Request, req, model: str,
                          object_name: str, make_delta,
                          timeout_s: float = 600.0,
                          stops: list[str] | None = None,
                          echo_prefix: str | None = None,
                          submit: bool = True,
                          trace_id: str | None = None,
                          decode_start: float | None = None):
        """Server-sent-events generation stream (OpenAI stream=true shape).

        Tokens appear in ``req.output_tokens`` as the engine decodes (in
        K-step blocks); each wake decodes only the unconsumed suffix and
        emits it as one chunk.  A suffix ending in a replacement char is held
        back whole — likely a multi-byte UTF-8 sequence the next block
        completes.  Submission happens BEFORE headers so saturation is a real
        429 (the gateway's backpressure contract), and the done flag is read
        BEFORE the token count so the final re-diff can't drop a tail.
        """
        # Mark the request as SSE-consumed BEFORE submission: the engine's
        # adaptive dispatch planner caps fused steps for streaming rows
        # (EngineConfig.adaptive_stream_cap) so a live stream keeps
        # per-token cadence instead of n_steps-sized bursts.
        req.streaming = True
        if submit:
            try:
                self.engine.submit(req)
            except EngineDraining as e:
                return self._reject(503, str(e), trace_id, "draining")  # replica leaving the set
            except ValueError as e:
                return _err(400, str(e), trace_id)
            except queue_mod.Full:
                return self._reject(429, "prefill queue is full",
                                    trace_id, "queue_full")

        # From here the request occupies engine capacity: ANY exit before
        # completion (disconnect during prepare, write failure, handler
        # cancel, unexpected exception) must release the slot — enforced by
        # the finally below, not by enumerating exception types.
        try:
            stream_headers = {
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "x-accel-buffering": "no",
            }
            if trace_id:
                stream_headers[tracing.TRACE_HEADER] = trace_id
            resp = web.StreamResponse(headers=stream_headers)
            await resp.prepare(http_request)
            loop = asyncio.get_running_loop()
            consumed = 0  # tokens already emitted as text
            deadline = time.monotonic() + timeout_s

            async def emit(payload: dict) -> None:
                await resp.write(f"data: {json.dumps(payload)}\n\n".encode())

            if echo_prefix:
                # OpenAI echo under streaming: the prompt text leads the
                # stream as its own chunk.
                await emit({
                    "id": f"cmpl-{req.request_id}",
                    "object": object_name,
                    "model": model,
                    "choices": [make_delta(echo_prefix, None)],
                })
            if stops:
                return await self._stream_sse_loop_stops(
                    req, model, object_name, make_delta, resp, loop,
                    deadline, emit, stops,
                )
            return await self._stream_sse_loop(
                req, model, object_name, make_delta, resp, loop, consumed,
                deadline, emit,
            )
        finally:
            if not req.done.is_set():
                # Stream ended without the request completing (disconnect,
                # deadline, any exception): release the decode slot instead
                # of generating to completion for nobody.
                req.cancelled.set()
            elif trace_id:
                # Completed stream: the engine stamps are final — record the
                # phase spans (streams can't carry x-lig-spans post-hoc, so
                # they live on THIS server's /debug/traces, same trace id).
                self._record_spans(
                    trace_id,
                    self._engine_spans(req, decode_start=decode_start),
                    status=req.finish_reason or "ok")

    async def _stream_sse_loop(self, req, model, object_name, make_delta,
                               resp, loop, consumed, deadline, emit):
        while True:
            await loop.run_in_executor(None, req.stream_event.wait, 0.25)
            req.stream_event.clear()
            done = req.done.is_set()  # read BEFORE the token count
            n = len(req.output_tokens)
            if n > consumed:
                # PER-TOKEN chunking: the engine publishes each fused-block
                # token individually (per-step emission from the trim
                # walk), and each token's text DELTA becomes its own SSE
                # chunk — a K-step fused dispatch no longer arrives as one
                # concatenated burst.  Deltas come from prefix-diffing
                # growing decodes of the unconsumed window (the
                # concatenation-safe pattern the non-stream logprob walk
                # uses): per-token decode() is NOT concatenative for
                # SentencePiece-style tokenizers (leading-space stripping),
                # so decoding each token span independently would eat
                # inter-word spaces.  A prefix still ending in U+FFFD is
                # held back (likely a multi-byte sequence the next token
                # completes); the window is tiny (per-step wakes), so the
                # quadratic prefix decode stays O(burst) per dispatch.
                window = req.output_tokens[consumed:n]
                prev = ""
                clean = 0  # tokens of the window emitted cleanly
                for i in range(1, len(window) + 1):
                    text = self.tokenizer.decode(window[:i])
                    if text.endswith("�"):
                        if i < len(window):
                            continue  # next token may complete the bytes
                        if not done:
                            break     # hold the incomplete tail back
                    delta = text[len(prev):]
                    prev = text
                    clean = i
                    if delta:
                        await emit({
                            "id": f"cmpl-{req.request_id}",
                            "object": object_name,
                            "model": model,
                            "choices": [make_delta(delta, None)],
                        })
                consumed += clean
            if done:
                # Final re-diff: anything appended since the last emit (or a
                # held-back tail) rides the final chunk.
                tail = (
                    self.tokenizer.decode(req.output_tokens[consumed:])
                    if len(req.output_tokens) > consumed else ""
                )
                await emit({
                    "id": f"cmpl-{req.request_id}",
                    "object": object_name,
                    "model": model,
                    "choices": [make_delta(tail, req.finish_reason or "stop")],
                    "usage": {
                        "prompt_tokens": len(req.prompt_tokens),
                        "completion_tokens": len(req.output_tokens),
                        "total_tokens": len(req.prompt_tokens) + len(req.output_tokens),
                    },
                })
                await resp.write(b"data: [DONE]\n\n")
                return resp
            if time.monotonic() > deadline:
                req.cancelled.set()  # stop burning the slot for a dead stream
                await emit({"error": {"message": "generation timed out"}})
                await resp.write(b"data: [DONE]\n\n")
                return resp

    async def _stream_sse_loop_stops(self, req, model, object_name,
                                     make_delta, resp, loop, deadline, emit,
                                     stops):
        """Character-based streaming with stop-sequence scanning.

        Emitted text always lags the decoded text by ``holdback`` characters
        (longest stop minus one) while generating, so no prefix of a stop
        sequence ever reaches the client before the match is decided."""
        holdback = max(len(s) for s in stops) - 1
        max_stop = holdback + 1
        emitted = 0
        consumed = 0  # tokens folded into ``text`` so far
        text = ""
        hits: list[tuple[int, str]] = []

        async def send(delta: str, fin: str | None, usage: bool = False):
            payload = {
                "id": f"cmpl-{req.request_id}",
                "object": object_name,
                "model": model,
                "choices": [make_delta(delta, fin)],
            }
            if usage:
                payload["usage"] = {
                    "prompt_tokens": len(req.prompt_tokens),
                    "completion_tokens": len(req.output_tokens),
                    "total_tokens": (len(req.prompt_tokens)
                                     + len(req.output_tokens)),
                }
            await emit(payload)

        while True:
            await loop.run_in_executor(None, req.stream_event.wait, 0.25)
            req.stream_event.clear()
            done = req.done.is_set()  # read BEFORE decoding
            n = len(req.output_tokens)
            if n > consumed:
                # Incremental decode + windowed search: O(total) over the
                # generation, not O(n^2).
                piece = self.tokenizer.decode(req.output_tokens[consumed:n])
                if piece.endswith("�") and not done:
                    pass  # incomplete UTF-8 tail: re-decode next wake
                else:
                    window = max(0, len(text) - max_stop + 1)
                    text += piece
                    consumed = n
                    hits = [(text.index(s, window), s)
                            for s in stops if s in text[window:]]
            if hits:
                idx, _ = min(hits)
                req.cancelled.set()  # free the slot; text is final
                await loop.run_in_executor(None, req.done.wait, 30)
                self._truncate_at_stop(req, stops)  # usage matches the cut
                if idx > emitted:
                    await send(text[emitted:idx], None)
                await send("", "stop", usage=True)
                await resp.write(b"data: [DONE]\n\n")
                return resp
            limit = len(text) if done else max(emitted, len(text) - holdback)
            if limit > emitted:
                await send(text[emitted:limit], None)
                emitted = limit
            if done:
                await send("", req.finish_reason or "stop", usage=True)
                await resp.write(b"data: [DONE]\n\n")
                return resp
            if time.monotonic() > deadline:
                req.cancelled.set()
                await emit({"error": {"message": "generation timed out"}})
                await resp.write(b"data: [DONE]\n\n")
                return resp

    # -- inference ---------------------------------------------------------
    async def handle_completions(self, request: web.Request) -> web.Response:
        trace_id = self._trace_id_for(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body", trace_id)
        try:
            adapter = self._resolve_model(body.get("model", self.model_name))
        except AdapterError as e:
            return _err(404, str(e), trace_id)
        try:
            n, best_of, logprobs, stops = self._parse_choice_params(body)
        except (ValueError, TypeError) as e:
            return _err(400, str(e), trace_id)
        prompt_tokens = self._encode_prompt(body)
        echo = bool(body.get("echo"))

        def echo_text() -> str:
            # The client's own string round-trips exactly; only
            # pre-tokenized (list-of-int) prompts need a decode, where
            # tokenizer normalization is inherent to the request form.
            prompt = body.get("prompt", "")
            if isinstance(prompt, str):
                return prompt
            return self.tokenizer.decode(prompt_tokens)

        if echo and logprobs is not None:
            # OpenAI echo+logprobs returns PROMPT logprobs, which the
            # engine does not record; reject rather than mislabel.
            return _err(400, "echo is not supported together with logprobs",
                        trace_id)
        if body.get("stream"):
            if n > 1 or best_of > 1:
                return _err(400, "streaming supports n=1 / best_of=1",
                            trace_id)
            if logprobs is not None:
                # Explicit rejection beats a silently-null field: chunks
                # carry no logprobs object.
                return _err(400, "logprobs is not supported with streaming",
                            trace_id)
            req = self._make_request(body, prompt_tokens, adapter)
            prefix = echo_text() if echo else None
            return await self._stream_sse(
                request, req, body.get("model", self.model_name),
                "text_completion",
                lambda delta, fin: {"index": 0, "text": delta, "finish_reason": fin},
                stops=stops, echo_prefix=prefix, trace_id=trace_id,
            )
        # best_of candidates decode concurrently (the engine batches them);
        # ranking needs per-token logprobs, so candidates record at least the
        # sampled-token values even when the client didn't ask.
        record = logprobs if logprobs is not None else (
            0 if best_of > n else None)
        reqs = [
            self._make_request(body, list(prompt_tokens), adapter,
                               logprobs=record, candidate=i)
            for i in range(best_of)
        ]
        try:
            reqs = await self._run_many(reqs, stops)
        except EngineDraining as e:
            return self._reject(503, str(e), trace_id, "draining")
        except ValueError as e:
            return _err(400, str(e), trace_id)
        except queue_mod.Full:
            # Backpressure the gateway cleanly; its scheduler already sees the
            # queue depth via /metrics and will shed/redirect.
            return self._reject(429, "prefill queue is full", trace_id,
                                "queue_full")
        for r in reqs:
            if r.error:
                return _err(500, r.error, trace_id)
        texts = {id(r): self._truncate_at_stop(r, stops)[0] for r in reqs}
        # OpenAI usage semantics: completion_tokens counts ALL generated
        # candidates, including best_of ones not returned.
        completion_tokens = sum(len(r.output_tokens) for r in reqs)
        if best_of > n:
            # OpenAI best_of: keep the n candidates with the highest mean
            # token logprob.
            def mean_lp(r: Request) -> float:
                return (sum(r.output_logprobs) / len(r.output_logprobs)
                        if r.output_logprobs else float("-inf"))

            reqs.sort(key=mean_lp, reverse=True)
            reqs = reqs[:n]
        echo_prefix = echo_text() if echo else ""
        choices = []
        for i, r in enumerate(reqs):
            choice = {
                "index": i,
                "text": echo_prefix + texts[id(r)],
                "finish_reason": r.finish_reason,
            }
            if logprobs is not None:
                choice["logprobs"] = self._logprobs_json(
                    r, logprobs, text_limit=len(texts[id(r)]))
            choices.append(choice)
        headers = self._record_spans(
            trace_id, self._engine_spans(reqs[0]),
            status=reqs[0].finish_reason or "ok")
        return web.json_response({
            "id": f"cmpl-{reqs[0].request_id}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": body.get("model", self.model_name),
            "choices": choices,
            "usage": {
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": completion_tokens,
                "total_tokens": len(prompt_tokens) + completion_tokens,
            },
            "ttft_ms": round(reqs[0].ttft_s * 1000, 2),
        }, headers=headers)

    async def handle_chat(self, request: web.Request) -> web.Response:
        trace_id = self._trace_id_for(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body", trace_id)
        messages = body.get("messages", [])
        try:
            adapter = self._resolve_model(body.get("model", self.model_name))
        except AdapterError as e:
            return _err(404, str(e), trace_id)
        try:
            prompt, add_bos = self._chat_prompt(messages)
            n, best_of, _, stops = self._parse_choice_params(body)
            lp_flag, top_n, top_req = self._parse_chat_logprobs(body)
        except (ValueError, TypeError) as e:
            return _err(400, str(e), trace_id)
        prompt_tokens = self.tokenizer.encode(prompt, add_bos=add_bos)
        if body.get("stream"):
            if n > 1 or best_of > 1:
                return _err(400, "streaming supports n=1 / best_of=1",
                            trace_id)
            if lp_flag:
                return _err(400, "logprobs are not supported with "
                                 "streaming chat completions", trace_id)
            req = self._make_request(body, prompt_tokens, adapter)
            return await self._stream_sse(
                request, req, body.get("model", self.model_name),
                "chat.completion.chunk",
                lambda delta, fin: {
                    "index": 0,
                    "delta": ({"content": delta} if delta else {}),
                    "finish_reason": fin,
                },
                stops=stops, trace_id=trace_id,
            )
        reqs = [self._make_request(body, list(prompt_tokens), adapter,
                                   logprobs=top_n if lp_flag else None,
                                   candidate=i)
                for i in range(n)]
        try:
            reqs = await self._run_many(reqs, stops)
        except EngineDraining as e:
            return self._reject(503, str(e), trace_id, "draining")
        except ValueError as e:
            return _err(400, str(e), trace_id)
        except queue_mod.Full:
            return self._reject(429, "prefill queue is full", trace_id,
                                "queue_full")
        for r in reqs:
            if r.error:
                return _err(500, r.error, trace_id)
        choices = []
        for i, r in enumerate(reqs):
            text, _ = self._truncate_at_stop(r, stops)
            choice = {
                "index": i,
                "message": {"role": "assistant", "content": text},
                "finish_reason": r.finish_reason,
            }
            if lp_flag:
                choice["logprobs"] = self._chat_logprobs_json(
                    r, top_n, text_limit=len(text))
                if top_req > top_n:
                    # Divergence note: the client asked for more than the
                    # engine's device-side top-k records.
                    choice["logprobs"]["top_logprobs_truncated_to"] = top_n
            choices.append(choice)
        completion_tokens = sum(len(r.output_tokens) for r in reqs)
        headers = self._record_spans(
            trace_id, self._engine_spans(reqs[0]),
            status=reqs[0].finish_reason or "ok")
        return web.json_response({
            "id": f"chatcmpl-{reqs[0].request_id}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": body.get("model", self.model_name),
            "choices": choices,
            "usage": {
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": completion_tokens,
                "total_tokens": len(prompt_tokens) + completion_tokens,
            },
        }, headers=headers)

    # -- disaggregation hops (server/kv_transfer.py) -------------------------
    async def handle_prefill(self, request: web.Request) -> web.Response:
        """Hop 1: prefill only, return the serialized ``PrefillHandoff``.

        Accepts the standard completions/chat body.  Shapes the handoff
        path can't carry (candidate fan-out, echo) and prompts beyond the
        prefill bucket answer 422 — the gateway treats any non-200 as
        "serve this single-hop instead", so unsupported requests degrade,
        never fail.
        """
        trace_id = self._trace_id_for(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body", trace_id)
        try:
            adapter = self._resolve_model(body.get("model", self.model_name))
        except AdapterError as e:
            return _err(404, str(e), trace_id)
        try:
            n, best_of, logprobs, _stops = self._parse_choice_params(body)
            if isinstance(body.get("messages"), list):
                prompt, add_bos = self._chat_prompt(body["messages"])
                prompt_tokens = self.tokenizer.encode(prompt, add_bos=add_bos)
                lp_flag, top_n, _ = self._parse_chat_logprobs(body)
                logprobs = top_n if lp_flag else None
            else:
                prompt_tokens = self._encode_prompt(body)
        except (ValueError, TypeError) as e:
            return _err(400, str(e), trace_id)
        if n > 1 or best_of > 1 or body.get("echo"):
            return self._reject(422, "prefill hop supports single-candidate, "
                                     "non-echo requests", trace_id,
                                "prefill_unsupported")
        req = self._make_request(body, prompt_tokens, adapter,
                                 logprobs=logprobs)
        loop = asyncio.get_running_loop()
        try:
            handoff = await loop.run_in_executor(
                None, lambda: self.engine.prefill_only(req))
        except EngineDraining as e:
            return self._reject(503, str(e), trace_id, "draining")
        except queue_mod.Full:
            return self._reject(429, "prefill queue is full", trace_id,
                                "queue_full")
        except ValueError as e:
            return self._reject(422, str(e), trace_id,
                                "prefill_refused")  # e.g. beyond the bucket set
        except RuntimeError as e:
            return _err(500, str(e), trace_id)
        handoff.body = body  # envelope params ride to the decode hop
        handoff.trace_id = trace_id  # one id across both hops
        t_ser0 = time.time()
        wire = handoff.to_bytes()
        t_ser1 = time.time()
        self.engine.observe_handoff(t_ser1 - t_ser0)
        headers = self._record_spans(
            trace_id,
            self._engine_spans(req, with_decode=False)
            + [("handoff.serialize", t_ser0, t_ser1)],
            status="handoff")
        headers.update({"x-request-id": req.request_id,
                        "x-prefill-ttft-ms": f"{req.ttft_s * 1000:.2f}"})
        return web.Response(
            body=wire,
            content_type="application/octet-stream",
            headers=headers,
        )

    async def handle_attach(self, request: web.Request) -> web.Response:
        """Hop 2: admit a ``PrefillHandoff`` straight into decode and answer
        in the normal OpenAI envelope (streaming included) — the client
        response is indistinguishable from collocated serving."""
        from llm_instance_gateway_tpu.server.kv_transfer import PrefillHandoff

        raw = await request.read()
        t_des0 = time.time()
        try:
            handoff = PrefillHandoff.from_bytes(raw)
        except Exception as e:
            return _err(400, f"malformed handoff: {e}",
                        self._trace_id_for(request))
        t_des1 = time.time()
        # The trace id prefers the header but survives header-stripping
        # transports via the handoff's own field.
        trace_id = (tracing.header_trace_id(request.headers)
                    or handoff.trace_id or tracing.new_trace_id())
        body = handoff.body or {}
        chat = isinstance(body.get("messages"), list)
        try:
            _, _, _, stops = self._parse_choice_params(body)
        except (ValueError, TypeError) as e:
            return _err(400, str(e), trace_id)
        try:
            req = self.engine.attach_prefilled(handoff)
        except EngineDraining as e:
            return self._reject(503, str(e), trace_id, "draining")
        except queue_mod.Full:
            return self._reject(429, "attach admission queue is full",
                                trace_id, "queue_full")
        except AdapterError as e:
            return _err(404, str(e), trace_id)
        except ValueError as e:
            return self._reject(422, str(e), trace_id, "attach_refused")
        t_att = time.time()
        self.engine.observe_handoff(t_att - t_des0)
        attach_spans = [("handoff.deserialize", t_des0, t_des1),
                        ("handoff.attach", t_des1, t_att)]
        model = body.get("model", self.model_name)
        if body.get("stream"):
            for name, s, e in attach_spans:
                self.tracer.record(trace_id, name, s, e)
            if chat:
                return await self._stream_sse(
                    request, req, model, "chat.completion.chunk",
                    lambda delta, fin: {
                        "index": 0,
                        "delta": ({"content": delta} if delta else {}),
                        "finish_reason": fin,
                    },
                    stops=stops, submit=False, trace_id=trace_id,
                    decode_start=t_att)
            return await self._stream_sse(
                request, req, model, "text_completion",
                lambda delta, fin: {"index": 0, "text": delta,
                                    "finish_reason": fin},
                stops=stops, submit=False, trace_id=trace_id,
                decode_start=t_att)
        loop = asyncio.get_running_loop()
        try:
            if stops:
                await loop.run_in_executor(
                    None, lambda: self._wait_with_stops(
                        req, stops, submit=False))
            else:
                await loop.run_in_executor(None, req.done.wait, 600.0)
        except asyncio.CancelledError:
            req.cancelled.set()
            raise
        if not req.done.is_set():
            req.error = "generation timed out"
            req.cancelled.set()
        if req.error:
            return _err(500, req.error, trace_id)
        trace_headers = self._record_spans(
            trace_id,
            attach_spans + [("engine.decode", t_att,
                             req.t_done or time.time())],
            status=req.finish_reason or "ok")
        text, _ = self._truncate_at_stop(req, stops)
        completion_tokens = len(req.output_tokens)
        usage = {
            "prompt_tokens": len(req.prompt_tokens),
            "completion_tokens": completion_tokens,
            "total_tokens": len(req.prompt_tokens) + completion_tokens,
        }
        if chat:
            choice = {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": req.finish_reason,
            }
            if req.logprobs is not None:
                choice["logprobs"] = self._chat_logprobs_json(
                    req, req.logprobs, text_limit=len(text))
                try:
                    top_req = int(body.get("top_logprobs") or 0)
                except (TypeError, ValueError):
                    top_req = 0
                if top_req > req.logprobs:
                    # The prefill hop already capped the recorded top-k;
                    # keep the truncation note on the attach path too.
                    choice["logprobs"]["top_logprobs_truncated_to"] = (
                        req.logprobs)
            return web.json_response({
                "id": f"chatcmpl-{req.request_id}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": model,
                "choices": [choice],
                "usage": usage,
            }, headers=trace_headers)
        choice = {
            "index": 0,
            "text": text,
            "finish_reason": req.finish_reason,
        }
        if req.logprobs is not None:
            choice["logprobs"] = self._logprobs_json(
                req, req.logprobs, text_limit=len(text))
        return web.json_response({
            "id": f"cmpl-{req.request_id}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": model,
            "choices": [choice],
            "usage": usage,
            "ttft_ms": round(req.ttft_s * 1000, 2),
        }, headers=trace_headers)

    async def handle_release(self, request: web.Request) -> web.Response:
        """Best-effort release of abandoned disaggregation work: the
        gateway posts ``{"request_id": ...}`` when a decode hop failed
        AFTER the handoff bytes were delivered — the engine may hold the
        imported KV parked (or decoding) with nobody left to read the
        response.  Idempotent; unknown ids answer ``released: false``
        (the request finished, was never admitted, or already swept by
        the engine's ``--handoff-ttl-s`` backstop)."""
        trace_id = self._trace_id_for(request)
        try:
            body = await request.json()
            request_id = body["request_id"]
        except (json.JSONDecodeError, KeyError, TypeError):
            return _err(400, "body must be JSON with request_id", trace_id)
        released = bool(self.engine.release_request(str(request_id)))
        if released:
            logger.info("released abandoned request %s", request_id)
        return web.json_response(
            {"request_id": request_id, "released": released},
            headers={tracing.TRACE_HEADER: trace_id})

    # -- admin -------------------------------------------------------------
    async def handle_models(self, request: web.Request) -> web.Response:
        data = [{"id": self.model_name, "object": "model", "root": self.model_name}]
        if self.lora is not None:
            data += [
                {"id": name, "object": "model", "root": self.model_name,
                 "parent": self.model_name}
                for name in self.lora.running_adapters()
            ]
        return web.json_response({"object": "list", "data": data})

    async def handle_load_adapter(self, request: web.Request) -> web.Response:
        if self.lora is None:
            return _err(400, "LoRA serving is not enabled")
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body")
        name = body.get("lora_name")
        path = body.get("lora_path")
        if not name or not path:
            return _err(400, "lora_name and lora_path are required")
        if name in self.aliases:
            # A base-model alias would shadow the adapter in _resolve_model:
            # requests naming it would silently get un-adapted output.
            return _err(409, f"adapter name {name!r} collides with the base "
                             "model's served names")
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                None, lambda: self.lora.load(name, checkpoint_path=path)
            )
        except AdapterError as e:
            return _err(409, str(e))
        except Exception as e:
            logger.exception("adapter load failed")
            return _err(500, f"failed to load adapter: {e}")
        return web.json_response({"status": "ok", "loaded": name})

    async def handle_unload_adapter(self, request: web.Request) -> web.Response:
        if self.lora is None:
            return _err(400, "LoRA serving is not enabled")
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body")
        name = body.get("lora_name")
        if not name:
            return _err(400, "lora_name is required")
        try:
            removed = self.lora.unload(name)
        except AdapterBusyError as e:
            return _err(409, str(e))
        if not removed:
            return _err(404, f"adapter {name!r} not loaded")
        return web.json_response({"status": "ok", "unloaded": name})

    async def handle_demote_adapter(self, request: web.Request) -> web.Response:
        """Slot -> host RAM (409 while in-flight/parked requests pin the
        slot; the planner/sidecar just retries next pass)."""
        if self.lora is None:
            return _err(400, "LoRA serving is not enabled")
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body")
        name = body.get("lora_name")
        if not name:
            return _err(400, "lora_name is required")
        try:
            demoted = self.lora.demote(name)
        except AdapterBusyError as e:
            return _err(409, str(e))
        except AdapterError as e:
            return _err(409, str(e))
        if not demoted:
            return _err(404, f"adapter {name!r} not slot-resident")
        return web.json_response({"status": "ok", "demoted": name,
                                  "tier": "host"})

    async def handle_prefetch_adapter(self, request: web.Request) -> web.Response:
        """Disk -> host RAM without consuming a device slot (the Orbax
        restore runs off the event loop like a load); idempotent for
        RAM-resident names."""
        if self.lora is None:
            return _err(400, "LoRA serving is not enabled")
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body")
        name = body.get("lora_name")
        path = body.get("lora_path")
        if not name or not path:
            return _err(400, "lora_name and lora_path are required")
        if name in self.aliases:
            return _err(409, f"adapter name {name!r} collides with the base "
                             "model's served names")
        loop = asyncio.get_running_loop()
        try:
            fetched = await loop.run_in_executor(
                None, lambda: self.lora.prefetch(name, path))
        except AdapterError as e:
            return _err(409, str(e))
        except Exception as e:
            logger.exception("adapter prefetch failed")
            return _err(500, f"failed to prefetch adapter: {e}")
        return web.json_response({"status": "ok", "prefetched": name,
                                  "already_resident": not fetched})

    async def handle_evict_adapter(self, request: web.Request) -> web.Response:
        """Host RAM -> disk (slot-resident adapters are untouched: demote
        first — eviction must never race a live decode)."""
        if self.lora is None:
            return _err(400, "LoRA serving is not enabled")
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "invalid JSON body")
        name = body.get("lora_name")
        if not name:
            return _err(400, "lora_name is required")
        if not self.lora.evict_host(name):
            return _err(404, f"adapter {name!r} not host-resident")
        return web.json_response({"status": "ok", "evicted": name})

    # -- ops ---------------------------------------------------------------
    async def handle_metrics(self, request: web.Request) -> web.Response:
        snap = self.engine.metrics_snapshot()
        # The engine doesn't know its served name; the phase-latency
        # histogram families are labeled by model + role at render time.
        snap.setdefault("model_name", self.model_name)
        text = metrics_mod.render(snap)
        # Flight-recorder counters (server-side twin of the gateway's
        # gateway_events_total family).
        text += "\n".join(self.events.render_prom("tpu:events_total")) + "\n"
        return web.Response(text=text, content_type="text/plain")

    async def handle_debug_traces(self, request: web.Request) -> web.Response:
        """Recent traces recorded by THIS replica (``?trace_id=`` filter).
        The gateway's /debug/traces shows the merged cross-process view;
        this endpoint is the replica-local ground truth (streaming decode
        spans live only here)."""
        return web.json_response(
            tracing.debug_traces_payload(self.tracer, request.query))

    async def handle_debug_events(self, request: web.Request) -> web.Response:
        """This replica's flight recorder (admission rejections, handoff
        refusals, drain transitions); same query contract as the gateway's
        ``/debug/events`` (``?since=``/``?kind=``/``?limit=``)."""
        return web.json_response(
            events_mod.debug_events_payload(self.events, request.query))

    async def handle_debug_usage(self, request: web.Request) -> web.Response:
        """This replica's per-adapter capacity attribution (server/usage.py):
        step-seconds / tokens / KV block-seconds per {adapter, phase} plus
        the pool-waste observables — the raw payload the gateway's
        ``gateway/usage.py`` rollup (and ``tools/lig_top.py``) aggregates.
        Tuple keys flatten to ``"adapter|phase"`` strings for JSON."""
        snap = self.engine.metrics_snapshot()
        usage = snap.get("usage") or {}
        flat = dict(usage)
        for key in ("step_seconds", "tokens"):
            flat[key] = {f"{a}|{p}": v
                         for (a, p), v in (usage.get(key) or {}).items()}
        return web.json_response({
            "model": self.model_name,
            "role": snap.get("pool_role", "collocated"),
            "running_lora_adapters": snap.get("running_lora_adapters", []),
            "waiting_lora_adapters": snap.get("waiting_lora_adapters", []),
            # Residency ladder alongside the usage shares (placement
            # plane): tier -> adapter names, so lig-top and operators see
            # WHERE each tenant's weights live, not just what they cost.
            "residency": snap.get("residency", {}),
            "usage": flat,
        })

    async def handle_debug_profile(self, request: web.Request) -> web.Response:
        """The step-timeline profiler's full payload (server/profiler.py):
        dispatch/host-sync/idle attribution summary, wall+gap histogram
        states, and the newest per-dispatch records — what
        ``tools/profile_report.py`` renders and the fleet collector's
        black-box dumps embed.  404 when ``step_profile`` is off."""
        profiler = getattr(self.engine, "profiler", None)
        if profiler is None:
            return _err(404, "step profiler is disabled "
                             "(EngineConfig.step_profile=False)")
        return web.json_response({"model": self.model_name,
                                  "role": self.engine.cfg.role,
                                  **profiler.snapshot()})

    async def handle_debug_kv(self, request: web.Request) -> web.Response:
        """The KV economy ledger's full payload (server/kv_ledger.py):
        block-state accounting, per-prefix reuse heatmap, fragmentation
        histograms, and the lifecycle event ring — what
        ``tools/kv_report.py`` renders, the gateway's ``gateway/kvobs.py``
        duplication index joins, and black-box dumps embed.  404 when the
        ledger is off (or the engine runs the contiguous-lane cache,
        which has no block economy)."""
        ledger = getattr(self.engine, "kv_ledger", None)
        if ledger is None:
            return _err(404, "kv ledger is disabled "
                             "(EngineConfig.kv_ledger=False or non-paged "
                             "cache)")
        self.engine._kv_ledger_sync()
        return web.json_response({"model": self.model_name,
                                  "role": self.engine.cfg.role,
                                  **ledger.snapshot()})

    async def handle_health(self, request: web.Request) -> web.Response:
        if self.engine.draining:
            # Readiness flip: the EPP's health-probed membership (and a k8s
            # readinessProbe) drops a draining replica from the routable
            # set while in-flight requests finish.  (On the SIGTERM path
            # aiohttp closes the listener before on_shutdown, so probes see
            # connection-refused instead — the same unready outcome; this
            # branch serves keep-alive connections and any future admin-
            # initiated drain.)
            return web.Response(status=503, text="draining")
        return web.Response(text="ok")


def _err(status: int, message: str,
         trace_id: str | None = None) -> web.Response:
    """Error envelope; when a trace id is known it rides both the body and
    the header so failed requests stay correlatable."""
    error: dict = {"message": message, "type": "invalid_request_error"}
    if trace_id:
        error["trace_id"] = trace_id
    return web.json_response(
        {"error": error},
        status=status,
        headers={tracing.TRACE_HEADER: trace_id} if trace_id else None,
    )


def main(argv=None) -> None:
    import jax.numpy as jnp
    import jax

    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.models.configs import ModelConfig
    from llm_instance_gateway_tpu.models import llama, gemma, mixtral, qwen
    from llm_instance_gateway_tpu.server.engine import EngineConfig

    all_configs: dict[str, ModelConfig] = {}
    for mod in (llama, gemma, mixtral, qwen):
        all_configs.update(mod.CONFIGS)

    parser = argparse.ArgumentParser(description="TPU model server")
    parser.add_argument("--model", default="llama3-tiny", choices=sorted(all_configs))
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--decode-slots", type=int, default=8)
    parser.add_argument("--max-seq-len", type=int, default=1024)
    parser.add_argument("--max-loras", type=int, default=4)
    parser.add_argument(
        "--host-cache-slots", type=int, default=8,
        help="host-RAM adapter cache size (the middle tier of the "
             "slot -> host -> disk residency ladder): demoted/prefetched "
             "adapters park here so promotion is one device put instead "
             "of an Orbax restore; 0 disables the tier")
    parser.add_argument(
        "--prefill-buckets", type=int, nargs="+", default=None,
        metavar="N",
        help="prefill bucket sizes (compiled-shape set). Default: powers "
             "of two up to min(--max-seq-len, 1024) — the 1024 cap keeps "
             "the long-prompt window open on big-context servers, where "
             "prompts above the largest bucket chunk-stream interleaved "
             "with decode (one monolithic prefill would freeze every "
             "active slot's TPOT) or run ONE ring-attention program when "
             "--mesh has a sequence axis")
    parser.add_argument("--decode-steps", type=int, default=8,
                        help="fused decode steps per host sync (K); "
                             "superseded when --adaptive-steps is set")
    parser.add_argument("--adaptive-steps", type=int, default=8,
                        metavar="CEILING",
                        help="adaptive multi-step dispatch: a per-dispatch "
                             "planner picks the fused step count (power of "
                             "two <= CEILING) from remaining budgets, "
                             "pending admissions/chunk streams, and SSE "
                             "cadence; 0 = static --decode-steps")
    parser.add_argument("--no-device-stops", action="store_true",
                        help="disable the device-side stop-string automata "
                             "(rows then stop via the host oracle only — "
                             "the A/B for the decode-lever bench)")
    parser.add_argument("--no-kv-ledger", action="store_true",
                        help="disable the KV block-lifecycle ledger "
                             "(tpu:kv_* families + /debug/kv; the A/B "
                             "for the kv_ledger_ratio bench)")
    parser.add_argument("--stream-lanes", type=int, default=1,
                        help="concurrent chunk-stream lanes: how many "
                             "long prompts may stream into reserved cache "
                             "lanes at once (fair round-robin); 1 = a "
                             "second long prompt head-of-line waits")
    parser.add_argument("--prefill-batch", type=int, default=1,
                        help="group up to P same-bucket queued prompts into "
                             "one prefill program (contiguous-lane cache)")
    parser.add_argument("--pipeline-decode", action="store_true",
                        help="overlap token readback with the next decode "
                             "block (finish detection lags one block)")
    parser.add_argument("--quantize", choices=["none", "int8"], default="none",
                        help="weight-only quantization of the big projections")
    parser.add_argument("--tokenizer", default=None, help="local HF tokenizer dir")
    parser.add_argument("--checkpoint", default=None, help="Orbax params dir")
    parser.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float32"])
    parser.add_argument(
        "--platform", default=None, choices=["cpu", "tpu", "axon"],
        help="override the JAX platform (the image's sitecustomize pins the "
             "TPU; pass cpu for hermetic runs)",
    )
    parser.add_argument(
        "--paged-kv-block", type=int, default=None, metavar="TOKENS",
        help="enable the paged KV cache with this block size (e.g. 64); "
             "kv metrics then report allocated/total blocks (vLLM "
             "gpu_cache_usage_perc semantics)",
    )
    parser.add_argument(
        "--drain-grace", type=float, default=30.0, metavar="S",
        help="graceful termination: on SIGTERM, flip /health to 503 (the "
             "EPP drops the replica), refuse new requests, and give "
             "in-flight ones this many seconds to finish",
    )
    parser.add_argument(
        "--kv-quantize", choices=("none", "int8"), default="none",
        help="store the KV cache int8 with per-position/head scales — "
             "halves the HBM traffic long-context decode is bound by "
             "(composes with both the contiguous-lane and paged caches, "
             "prefix caching included)",
    )
    parser.add_argument(
        "--paged-kv-blocks", type=int, default=None, metavar="N",
        help="paged pool size in blocks; below slots*ceil(max_seq/block) "
             "oversubscribes HBM for short-sequence traffic",
    )
    parser.add_argument(
        "--speculative", type=int, default=0, metavar="K",
        help="speculative decoding: a draft model proposes K tokens per "
             "cycle, the target verifies them in one multi-token forward; "
             "greedy requests keep exact parity.  Requires --draft-model",
    )
    parser.add_argument(
        "--draft-model", default=None, choices=sorted(all_configs),
        help="model preset for the speculative draft (must share the "
             "target's tokenizer/vocab), e.g. gemma-2b under gemma-7b",
    )
    parser.add_argument(
        "--draft-checkpoint", default=None,
        help="Orbax params dir for the draft model (random weights "
             "otherwise — dev mode)",
    )
    parser.add_argument(
        "--role", choices=("collocated", "prefill", "decode"),
        default="collocated",
        help="disaggregation role: 'prefill' replicas serve /v1/prefill "
             "handoffs, 'decode' replicas admit them via /v1/attach, "
             "'collocated' (default) serves whole requests; the role is "
             "advisory — every server keeps the full API",
    )
    parser.add_argument(
        "--prefix-cache", action="store_true",
        help="retain finished prompts' full KV blocks (content-addressed, "
             "refcounted) so prompts sharing a prefix skip recomputing it; "
             "requires --paged-kv-block",
    )
    parser.add_argument(
        "--handoff-ttl-s", type=float, default=60.0,
        help="abandoned-handoff backstop: an attach-imported request still "
             "parked in decode_wait this many seconds after admission is "
             "presumed abandoned (its gateway rerouted) and is released; "
             "0 disables. The gateway's POST /v1/prefill/release is the "
             "fast path, this TTL the safety net.",
    )
    parser.add_argument(
        "--mesh", default=None, metavar="AXIS=N[,AXIS=N...]",
        help="serve sharded over a device mesh, e.g. 'tensor=8' on a v5e-8 "
             "pool or 'data=2,tensor=4'; axes: data,fsdp,tensor,expert,"
             "sequence (parallel/mesh.py). Default: single device.",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    args = parser.parse_args(argv)

    if args.paged_kv_blocks is not None and args.paged_kv_block is None:
        parser.error("--paged-kv-blocks requires --paged-kv-block")
    if args.prefill_buckets and max(args.prefill_buckets) > args.max_seq_len:
        # A silently-ignored oversized bucket would also inflate _ring_pad
        # and close the ring window with no diagnostic.
        parser.error(
            f"--prefill-buckets {max(args.prefill_buckets)} exceeds "
            f"--max-seq-len {args.max_seq_len}")
    if args.prefix_cache and args.paged_kv_block is None:
        parser.error("--prefix-cache requires --paged-kv-block")
    if args.speculative > 0 and args.draft_model is None:
        parser.error("--speculative requires --draft-model")
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import dataclasses
    cfg = dataclasses.replace(all_configs[args.model], max_lora_slots=args.max_loras)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    tokenizer = load_tokenizer(args.tokenizer)
    served_name = args.model  # CLI preset alias (cfg.name can differ from it)
    if args.checkpoint:
        from llm_instance_gateway_tpu.models.convert import load_serving_checkpoint

        ckpt_cfg, params = load_serving_checkpoint(args.checkpoint)
        if ckpt_cfg is not None:
            # Converted checkpoints carry their architecture; the preset
            # --model only contributes serving knobs like max_lora_slots.
            cfg = dataclasses.replace(
                ckpt_cfg, max_lora_slots=args.max_loras,
                max_lora_rank=cfg.max_lora_rank,
            )
            served_name = cfg.name  # checkpoint architectures bring their name
            logger.info("model config restored from checkpoint: %s", cfg.name)
        logger.info("restored params from %s", args.checkpoint)
    else:
        logger.warning("no --checkpoint: serving RANDOM weights (dev mode)")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    # Validate AFTER the checkpoint may have replaced the architecture.
    if tokenizer.vocab_size > cfg.vocab_size:
        raise SystemExit(
            f"tokenizer vocab {tokenizer.vocab_size} exceeds model vocab {cfg.vocab_size}"
        )
    if args.quantize == "int8":
        from llm_instance_gateway_tpu.ops.quant import quantize_params

        params = quantize_params(params)
        logger.info("weights quantized to int8 (per-output-channel)")

    mesh = None
    if args.mesh:
        from llm_instance_gateway_tpu.parallel.mesh import (
            MeshConfig, initialize_distributed, make_mesh,
        )

        initialize_distributed()  # no-op single-host; DCN wiring on pods
        axes = {}
        for part in args.mesh.split(","):
            k, _, v = part.partition("=")
            axes[k.strip()] = int(v)
        mesh = make_mesh(MeshConfig(**axes))
        logger.info("serving sharded over mesh %s", dict(mesh.shape))

    draft_params = draft_cfg = None
    if args.speculative > 0:
        draft_cfg = all_configs[args.draft_model]
        if args.draft_checkpoint:
            from llm_instance_gateway_tpu.models.convert import (
                load_serving_checkpoint,
            )

            dc, draft_params = load_serving_checkpoint(args.draft_checkpoint)
            if dc is not None:
                draft_cfg = dc
        else:
            logger.warning("no --draft-checkpoint: draft uses RANDOM "
                           "weights (dev mode — proposals rarely accepted)")
            draft_params = transformer.init_params(
                draft_cfg, jax.random.PRNGKey(1), dtype=dtype)
        if args.quantize == "int8":
            draft_params = quantize_params(draft_params)

    lora_manager = LoRAManager(cfg, dtype=dtype, mesh=mesh,
                               host_cache_slots=args.host_cache_slots)
    engine = Engine(
        cfg, params,
        EngineConfig(
            decode_slots=args.decode_slots, max_seq_len=args.max_seq_len,
            prefill_buckets=(
                tuple(sorted(args.prefill_buckets))
                if args.prefill_buckets else
                tuple(b for b in (16, 32, 64, 128, 256, 512, 1024)
                      if b <= args.max_seq_len)
                or (min(args.max_seq_len, 1024),)),
            decode_steps_per_sync=args.decode_steps,
            adaptive_steps=args.adaptive_steps,
            device_stops=not args.no_device_stops,
            stream_lanes=args.stream_lanes,
            pipeline_decode=args.pipeline_decode,
            prefill_batch=args.prefill_batch,
            paged_kv_block=args.paged_kv_block,
            paged_kv_blocks=args.paged_kv_blocks,
            prefix_cache=args.prefix_cache,
            kv_ledger=not args.no_kv_ledger,
            role=args.role,
            handoff_ttl_s=args.handoff_ttl_s,
            speculative_k=args.speculative,
            kv_cache_quant=(None if args.kv_quantize == "none"
                            else args.kv_quantize),
        ),
        lora_manager=lora_manager,
        eos_id=tokenizer.eos_id,
        dtype=dtype,
        mesh=mesh,
        draft_params=draft_params,
        draft_cfg=draft_cfg,
    )
    engine.start()
    server = ModelServer(engine, tokenizer, served_name, lora_manager,
                         aliases={args.model})
    app = server.build_app()

    async def _graceful_drain(app_):
        # SIGTERM path (aiohttp stops accepting, then runs on_shutdown while
        # in-flight handlers get shutdown_timeout to finish): flip /health
        # to 503, refuse new submits, and let the engine decode the
        # in-flight work to completion before the loop stops.
        logger.info("draining engine (grace %.0fs)", args.drain_grace)
        loop = asyncio.get_running_loop()
        drained = await loop.run_in_executor(
            None, lambda: engine.drain(args.drain_grace))
        logger.info("drain %s", "complete" if drained else "timed out")

    app.on_shutdown.append(_graceful_drain)
    try:
        web.run_app(app, port=args.port,
                    shutdown_timeout=args.drain_grace + 30)
    finally:
        engine.stop()


if __name__ == "__main__":
    main()
