"""Block-lifecycle ledger for the paged KV pool — the KV economy's books.

The engine's existing KV telemetry is one utilization gauge and one
cumulative reuse counter; this ledger is the attribution layer underneath
(the instrument-before-the-lever move ROADMAP item 2's fleet KV economy
needs, the way the step profiler preceded the decode levers):

- **Per-state block accounting** whose states tile the total block budget:
  ``free`` (allocator free list), ``active`` (distinct physical blocks
  referenced by live row tables — counted as a SET, a shared prefix block
  mapped into five rows is one block), ``prefix_resident`` (zero-ref
  cached blocks in the evictable LRU), and ``parked`` (block-equivalents
  of ``decode_wait`` KV, real HBM held OUTSIDE the pool).  The budget is
  ``pool blocks + parked equivalents``, so Σ(states) == total is a
  CONSERVATION invariant — and because free/active/prefix_resident are
  recounted from the allocator's ground truth on every sync rather than
  derived from each other, a leaked or double-allocated block breaks the
  sum instead of hiding in a residual (tests/test_kv_ledger.py pins it
  through the rendered exposition, like the usage plane's wall
  conservation).
- **Per-prefix reuse table** behind a bounded LRU: hit count, tokens
  saved, resident chain depth, last-touch age per content-addressed
  prefix id (the hex of the deepest chained block hash — adapter-seeded
  and content-addressed, so the SAME prompt prefix yields the SAME id on
  every replica; the gateway's fleet duplication index joins on it).
- **Fragmentation + headroom histograms**: free-run lengths over the
  physical block ids (a pool can be 40% free and still unable to serve a
  long sequence's worth of contiguity-friendly growth) and the parked
  share of the budget, sampled at sync passes.
- **A bounded lifecycle event ring** (alloc/evict/reuse/park/... with
  timestamps) for ``/debug/kv`` post-mortems.

Engine-thread-hot like the usage tracker: every ``note_*`` is a couple of
dict ops under the ledger's own lock, and the state recount rides the
existing per-dispatch KV sync.  ``bench.py``'s ``kv_ledger_ratio``
microbench rides the <1.05 overhead bar; ``EngineConfig.kv_ledger`` is
the A/B off switch.
"""

from __future__ import annotations

import collections
import time

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.tracing import Histogram

# Free-run lengths in BLOCKS (power-of-two buckets: the question is "can a
# max_blocks_per_seq growth burst find room", not a latency tail).
FREE_RUN_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
# Parked share of the total block budget (same even bins as the decode
# occupancy histogram).
PARKED_SHARE_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

# Lifecycle event kinds (the ``kind`` label of
# ``tpu:kv_block_events_total`` and the ring's ``kind`` field).
EVENT_KINDS = ("alloc", "evict", "reuse_hit", "reuse_unwind", "register",
               "release", "cache_park", "park", "unpark", "sweep")

# States of the block-budget accounting (the ``state`` label of
# ``tpu:kv_blocks``; the order is the exposition order).
STATES = ("free", "active", "prefix_resident", "parked")


def free_run_lengths(free_blocks) -> list[int]:
    """Lengths of maximal runs of consecutive physical block ids in the
    free list (order-insensitive: the allocator pops/pushes LIFO)."""
    if not free_blocks:
        return []
    ids = sorted(free_blocks)
    runs = []
    run = 1
    for prev, cur in zip(ids, ids[1:]):
        if cur == prev + 1:
            run += 1
        else:
            runs.append(run)
            run = 1
    runs.append(run)
    return runs


class KvLedger:
    """Accumulates the pool's block economy; ``snapshot()`` is the export
    seam (``metrics_snapshot``'s ``kv_ledger`` key -> ``render_kv`` ->
    the ``tpu:kv_*`` families + ``/debug/kv``)."""

    def __init__(self, n_blocks: int, block_tokens: int,
                 prefix_table_cap: int = 512, ring_cap: int = 256,
                 top_prefixes: int = 32, clock=time.monotonic):
        self.n_blocks = max(1, n_blocks)
        self.block_tokens = max(1, block_tokens)
        self.prefix_table_cap = max(1, prefix_table_cap)
        self.top_prefixes = max(1, top_prefixes)
        self._clock = clock
        self._lock = witness_lock("KvLedger._lock")
        # Cumulative lifecycle counters by kind (tpu:kv_block_events_total).
        self.events: dict[str, int] = {}
        # prefix id -> {hits, tokens_saved, blocks, last_touch}; bounded
        # LRU on touch order (register/hit), evictions counted so a
        # heatmap over a hostile prefix flood stays honest about loss.
        self.prefixes: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict())
        self.prefix_table_evictions = 0
        # Last-synced state counts (recounted from allocator ground truth
        # each sync; the conservation test reads these through render_kv).
        self._states = {s: 0 for s in STATES}
        self._parked_tokens = 0
        self._free_view: tuple[int, ...] = ()
        self._syncs = 0
        self.parked_share = Histogram(PARKED_SHARE_BUCKETS)
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, ring_cap))

    # -- charging (engine thread) -----------------------------------------
    def _note(self, kind: str, **attrs) -> None:
        with self._lock:
            self.events[kind] = self.events.get(kind, 0) + attrs.pop("n", 1)
            self._ring.append({"t": round(self._clock(), 4), "kind": kind,
                               **attrs})

    def note_alloc(self, n: int = 1) -> None:
        self._note("alloc", n=n)

    def note_evict(self, prefix: str) -> None:
        """A cached block was evicted LRU to satisfy an allocation.  The
        prefix entry (if the evicted block was a chain terminus) loses one
        resident block; intermediate blocks of a longer chain decrement
        nothing here — the chain's entry decays as ITS terminus goes."""
        with self._lock:
            self.events["evict"] = self.events.get("evict", 0) + 1
            entry = self.prefixes.get(prefix)
            if entry is not None and entry["blocks"] > 0:
                entry["blocks"] -= 1
            self._ring.append({"t": round(self._clock(), 4),
                               "kind": "evict", "prefix": prefix})

    def note_reuse_hit(self, prefix: str, blocks: int, tokens: int) -> None:
        with self._lock:
            self.events["reuse_hit"] = self.events.get("reuse_hit", 0) + 1
            entry = self._touch(prefix)
            entry["hits"] += 1
            entry["tokens_saved"] += tokens
            entry["blocks"] = max(entry["blocks"], blocks)
            self._ring.append({"t": round(self._clock(), 4),
                               "kind": "reuse_hit", "prefix": prefix,
                               "blocks": blocks, "tokens": tokens})

    def note_reuse_unwind(self, prefix: str, blocks: int, tokens: int) -> None:
        """Mirror of the engine's reuse unwind (prefix-bucket admission
        that mapped a prefix, then failed to grow the suffix): the hit is
        cancelled exactly where ``prefix_reused_tokens`` is decremented, so
        ledger tokens-saved stays equal to the engine counter."""
        with self._lock:
            self.events["reuse_unwind"] = (
                self.events.get("reuse_unwind", 0) + 1)
            entry = self.prefixes.get(prefix)
            if entry is not None:
                entry["hits"] = max(0, entry["hits"] - 1)
                entry["tokens_saved"] = max(0, entry["tokens_saved"] - tokens)
            self._ring.append({"t": round(self._clock(), 4),
                               "kind": "reuse_unwind", "prefix": prefix,
                               "blocks": blocks, "tokens": tokens})

    def note_register(self, prefix: str, blocks: int) -> None:
        with self._lock:
            self.events["register"] = self.events.get("register", 0) + 1
            entry = self._touch(prefix)
            entry["blocks"] = max(entry["blocks"], blocks)
            self._ring.append({"t": round(self._clock(), 4),
                               "kind": "register", "prefix": prefix,
                               "blocks": blocks})

    def note_release(self, freed: int, cached: int) -> None:
        """A row's table cleared: ``freed`` uncached blocks returned to
        the free list, ``cached`` dropped to zero refs and parked in the
        evictable LRU (content kept — the prefix-resident state)."""
        with self._lock:
            self.events["release"] = self.events.get("release", 0) + 1
            if cached:
                self.events["cache_park"] = (
                    self.events.get("cache_park", 0) + cached)
            self._ring.append({"t": round(self._clock(), 4),
                               "kind": "release", "freed": freed,
                               "cached": cached})

    def note_park(self, tokens: int, source: str) -> None:
        self._note("park", tokens=tokens, source=source)

    def note_unpark(self, tokens: int) -> None:
        self._note("unpark", tokens=tokens)

    def note_sweep(self, tokens: int, reason: str) -> None:
        self._note("sweep", tokens=tokens, reason=reason)

    def _touch(self, prefix: str) -> dict:
        """Entry for ``prefix``, moved to the LRU's MRU end (lock held)."""
        entry = self.prefixes.get(prefix)
        if entry is None:
            entry = {"hits": 0, "tokens_saved": 0, "blocks": 0,
                     "last_touch": self._clock()}
            self.prefixes[prefix] = entry
            while len(self.prefixes) > self.prefix_table_cap:
                self.prefixes.popitem(last=False)
                self.prefix_table_evictions += 1
        else:
            entry["last_touch"] = self._clock()
            self.prefixes.move_to_end(prefix)
        return entry

    def sync_states(self, free_blocks, active_blocks: int,
                    prefix_resident: int, parked_tokens: int) -> None:
        """Engine-thread state recount (rides the per-dispatch KV sync):
        the three pool states from allocator ground truth, the parked
        block-equivalents, a swap-published free-list view for the
        fragmentation histogram, and one parked-share sample."""
        parked_blocks = -(-max(0, parked_tokens) // self.block_tokens)
        with self._lock:
            self._states = {
                "free": len(free_blocks),
                "active": active_blocks,
                "prefix_resident": prefix_resident,
                "parked": parked_blocks,
            }
            self._parked_tokens = max(0, parked_tokens)
            self._free_view = tuple(free_blocks)
            self._syncs += 1
            self.parked_share.observe(
                parked_blocks / (self.n_blocks + parked_blocks)
                if parked_blocks else 0.0)

    # -- export (any thread) ----------------------------------------------
    def snapshot(self) -> dict:
        """Copy-out for ``metrics_snapshot()`` / ``/debug/kv``.  The
        free-run histogram is computed HERE (scrape rate) from the
        swap-published free view, not on the dispatch path."""
        with self._lock:
            states = dict(self._states)
            parked_tokens = self._parked_tokens
            free_view = self._free_view
            events = dict(self.events)
            now = self._clock()
            prefixes = [
                {"prefix": p, "hits": e["hits"],
                 "tokens_saved": e["tokens_saved"], "blocks": e["blocks"],
                 "age_s": round(max(0.0, now - e["last_touch"]), 3)}
                for p, e in self.prefixes.items()]
            table_size = len(self.prefixes)
            table_evictions = self.prefix_table_evictions
            parked_share = self.parked_share.state()
            ring = list(self._ring)
            syncs = self._syncs
        prefixes.sort(key=lambda e: (-e["hits"], -e["tokens_saved"],
                                     e["prefix"]))
        free_runs = Histogram(FREE_RUN_BUCKETS)
        for run in free_run_lengths(free_view):
            free_runs.observe(float(run))
        return {
            "blocks_total": self.n_blocks + states["parked"],
            "pool_blocks": self.n_blocks,
            "block_tokens": self.block_tokens,
            "states": states,
            "parked_tokens": parked_tokens,
            "events": events,
            "prefixes": prefixes[: self.top_prefixes],
            "prefix_table_size": table_size,
            "prefix_table_evictions": table_evictions,
            "free_runs": free_runs.state(),
            "parked_share": parked_share,
            "ring": ring,
            "syncs": syncs,
        }


def render_kv(kv: dict) -> list[str]:
    """Exposition lines for one ``KvLedger.snapshot()`` payload (the
    ``server/metrics.py`` render seam).  Prefix ids are hex but escape
    anyway — one hostile label must not poison the scrape."""
    from llm_instance_gateway_tpu.tracing import escape_label, render_histogram

    lines = [
        "# TYPE tpu:kv_blocks_total gauge",
        "tpu:kv_blocks_total %d" % kv.get("blocks_total", 0),
        "# TYPE tpu:kv_block_tokens gauge",
        "tpu:kv_block_tokens %d" % kv.get("block_tokens", 1),
        "# TYPE tpu:kv_blocks gauge",
    ]
    states = kv.get("states") or {}
    for state in STATES:
        lines.append('tpu:kv_blocks{state="%s"} %d'
                     % (escape_label(state), states.get(state, 0)))
    events = kv.get("events") or {}
    lines.append("# TYPE tpu:kv_block_events_total counter")
    if events:
        for kind in sorted(events):
            lines.append('tpu:kv_block_events_total{kind="%s"} %d'
                         % (escape_label(kind), events[kind]))
    else:
        lines.append("tpu:kv_block_events_total 0")
    prefixes = kv.get("prefixes") or []
    if prefixes:
        lines.append("# TYPE tpu:kv_prefix_hits_total counter")
        for e in prefixes:
            lines.append('tpu:kv_prefix_hits_total{prefix="%s"} %d'
                         % (escape_label(e["prefix"]), e["hits"]))
        lines.append("# TYPE tpu:kv_prefix_tokens_saved_total counter")
        for e in prefixes:
            lines.append('tpu:kv_prefix_tokens_saved_total{prefix="%s"} %d'
                         % (escape_label(e["prefix"]), e["tokens_saved"]))
        lines.append("# TYPE tpu:kv_prefix_resident_blocks gauge")
        for e in prefixes:
            lines.append('tpu:kv_prefix_resident_blocks{prefix="%s"} %d'
                         % (escape_label(e["prefix"]), e["blocks"]))
    if kv.get("free_runs"):
        lines += render_histogram("tpu:kv_free_run_blocks", kv["free_runs"])
    if kv.get("parked_share"):
        lines += render_histogram("tpu:kv_parked_share", kv["parked_share"])
    return lines
