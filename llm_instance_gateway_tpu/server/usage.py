"""Per-adapter capacity attribution on the serving engine.

One decode batch multiplexes many LoRA adapters and the base model per-row
(the engine's premise), so pool-level gauges can say how BUSY a replica is
but not WHO is consuming it.  This tracker charges every unit of engine
capacity to the {adapter} that used it:

- **Step seconds** (``tpu:adapter_step_seconds_total{adapter,phase}``):
  each decode dispatch's wall time is split evenly across the slots active
  in that batch (every active row advances one token per step regardless of
  adapter, so even attribution is exact for the fused program); each
  prefill's wall time is charged whole to its owner.  The engine-side
  ``tpu:step_seconds_total{phase}`` accumulates the same wall at the same
  call sites, so Σ-per-adapter == engine total is an INVARIANT the
  conservation test (tests/test_usage.py) pins within 1%.
- **Tokens** (``tpu:adapter_tokens_total{adapter,phase}``): prompt tokens
  at prefill, emitted tokens at decode.
- **KV block seconds** (``tpu:adapter_kv_block_seconds_total{adapter}``):
  the time-integral of KV blocks held, including requests PARKED in
  ``decode_wait`` (prefilled KV pinned off-cache is real HBM nobody else
  can use).  Unit: paged-block-seconds under the paged cache, token-seconds
  (block=1) on the contiguous-lane cache.
- **Pool waste** nobody previously saw: the decode batch occupancy
  histogram (``tpu:decode_batch_occupancy``, active/total slots per
  dispatch), ``tpu:idle_slot_seconds_total`` (slot-seconds spent empty
  while the batch stepped), and ``tpu:prefill_padding_tokens_total``
  (bucket/ring padding tokens prefilled and thrown away).

The tracker is engine-thread-hot: ``charge_decode`` is a handful of dict
ops per DISPATCH (not per token), bounded by the <5% attribution-overhead
bar ``bench.py``'s ``usage_attribution_ratio`` microbench rides on every
emission.  All methods take the tracker's own lock only — safe to call
from the engine loop and snapshot from the scrape thread.
"""

from __future__ import annotations

import time

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.tracing import Histogram

# Attribution key for requests with no LoRA adapter (base-model rows).
BASE = "base"

# Decode-batch occupancy fractions (active/total slots).  Eight even bins:
# the signal is "how full do dispatches run", not a latency tail.
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

PHASE_PREFILL = "prefill"
PHASE_DECODE = "decode"


def owner_key(adapter: str | None) -> str:
    return adapter if adapter else BASE


class UsageTracker:
    """Accumulates per-adapter consumption; snapshot() is the export seam."""

    def __init__(self, decode_slots: int, kv_block: int = 1,
                 clock=time.monotonic):
        self.decode_slots = max(1, decode_slots)
        self.kv_block = max(1, kv_block)
        self._clock = clock
        self._lock = witness_lock("UsageTracker._lock")
        self.step_seconds: dict[tuple[str, str], float] = {}
        self.tokens: dict[tuple[str, str], int] = {}
        self.kv_block_seconds: dict[str, float] = {}
        # Engine wall per phase, accumulated at the SAME call sites as the
        # per-adapter charges — the conservation denominator.
        self.engine_step_seconds: dict[str, float] = {}
        self.idle_slot_seconds = 0.0
        self.padding_tokens = 0
        self.occupancy = Histogram(OCCUPANCY_BUCKETS)
        # KV holdings integral: the holdings recorded at the LAST sync are
        # charged for the elapsed interval on the next sync/snapshot.
        self._kv_holdings: tuple[tuple[str, float], ...] = ()
        self._kv_t: float | None = None

    # -- charging (engine thread) -----------------------------------------
    def charge_step(self, phase: str, wall_s: float,
                    owners: list[str | None],
                    tokens: dict[str, int] | None = None) -> None:
        """Split ``wall_s`` evenly across ``owners`` (adapter names; None =
        base).  No-op with an empty owner list — an unowned dispatch must
        not skew the conservation invariant."""
        if not owners or wall_s <= 0.0:
            return
        share = wall_s / len(owners)
        with self._lock:
            self.engine_step_seconds[phase] = (
                self.engine_step_seconds.get(phase, 0.0) + wall_s)
            for owner in owners:
                key = (owner_key(owner), phase)
                self.step_seconds[key] = self.step_seconds.get(key, 0.0) + share
            for owner, n in (tokens or {}).items():
                if n:
                    key = (owner, phase)
                    self.tokens[key] = self.tokens.get(key, 0) + n

    def charge_decode(self, wall_s: float, owners: list[str | None],
                      tokens: dict[str, int] | None = None) -> None:
        """One decode dispatch: step-second attribution plus the pool-waste
        observables (occupancy + idle-slot-seconds)."""
        active = len(owners)
        with self._lock:
            self.occupancy.observe(active / self.decode_slots)
            if wall_s > 0.0:
                self.idle_slot_seconds += (
                    wall_s * (self.decode_slots - active))
        self.charge_step(PHASE_DECODE, wall_s, owners, tokens)

    def charge_padding(self, pad_tokens: int) -> None:
        if pad_tokens > 0:
            with self._lock:
                self.padding_tokens += pad_tokens

    def sync_kv(self, holdings: list[tuple[str | None, int]] | None,
                now: float | None = None) -> None:
        """Charge the PREVIOUS holdings for the elapsed interval, then (if
        ``holdings`` is not None) replace them.  ``holdings`` is
        [(adapter, kv_tokens_held), ...]; tokens convert to blocks here."""
        now = self._clock() if now is None else now
        with self._lock:
            if self._kv_t is not None:
                dt = now - self._kv_t
                if dt > 0.0:
                    for owner, blocks in self._kv_holdings:
                        self.kv_block_seconds[owner] = (
                            self.kv_block_seconds.get(owner, 0.0)
                            + blocks * dt)
            self._kv_t = now
            if holdings is not None:
                self._kv_holdings = tuple(
                    (owner_key(a), -(-t // self.kv_block))
                    for a, t in holdings if t > 0)

    # -- export (any thread) ----------------------------------------------
    def snapshot(self) -> dict:
        """Copy-out for ``Engine.metrics_snapshot()`` — flushes the pending
        KV interval first so a scrape between engine syncs still sees
        up-to-date block-seconds."""
        self.sync_kv(None)
        with self._lock:
            return {
                "step_seconds": dict(self.step_seconds),
                "tokens": dict(self.tokens),
                "kv_block_seconds": dict(self.kv_block_seconds),
                "engine_step_seconds": dict(self.engine_step_seconds),
                "idle_slot_seconds": self.idle_slot_seconds,
                "padding_tokens": self.padding_tokens,
                "occupancy": self.occupancy.state(),
                "kv_block_tokens": self.kv_block,
            }


def render_usage(usage: dict, model: str) -> list[str]:
    """Exposition lines for one ``UsageTracker.snapshot()`` payload (the
    ``server/metrics.py`` render seam; labels escaped there via the shared
    helpers)."""
    from llm_instance_gateway_tpu.tracing import escape_label, render_histogram

    lines = []
    m = escape_label(model)
    step = usage.get("step_seconds") or {}
    if step:
        lines.append("# TYPE tpu:adapter_step_seconds_total counter")
        for (adapter, phase) in sorted(step):
            lines.append(
                'tpu:adapter_step_seconds_total{model="%s",adapter="%s",'
                'phase="%s"} %.6f'
                % (m, escape_label(adapter), escape_label(phase),
                   step[(adapter, phase)]))
    toks = usage.get("tokens") or {}
    if toks:
        lines.append("# TYPE tpu:adapter_tokens_total counter")
        for (adapter, phase) in sorted(toks):
            lines.append(
                'tpu:adapter_tokens_total{model="%s",adapter="%s",'
                'phase="%s"} %d'
                % (m, escape_label(adapter), escape_label(phase),
                   toks[(adapter, phase)]))
    kv = usage.get("kv_block_seconds") or {}
    if kv:
        lines.append("# TYPE tpu:adapter_kv_block_seconds_total counter")
        for adapter in sorted(kv):
            lines.append(
                'tpu:adapter_kv_block_seconds_total{model="%s",'
                'adapter="%s"} %.6f' % (m, escape_label(adapter), kv[adapter]))
    engine_s = usage.get("engine_step_seconds") or {}
    if engine_s:
        lines.append("# TYPE tpu:step_seconds_total counter")
        for phase in sorted(engine_s):
            lines.append('tpu:step_seconds_total{phase="%s"} %.6f'
                         % (escape_label(phase), engine_s[phase]))
    lines.append("# TYPE tpu:idle_slot_seconds_total counter")
    lines.append("tpu:idle_slot_seconds_total %.6f"
                 % usage.get("idle_slot_seconds", 0.0))
    lines.append("# TYPE tpu:prefill_padding_tokens_total counter")
    lines.append("tpu:prefill_padding_tokens_total %d"
                 % usage.get("padding_tokens", 0))
    occ = usage.get("occupancy")
    if occ:
        lines += render_histogram("tpu:decode_batch_occupancy", occ)
    return lines
