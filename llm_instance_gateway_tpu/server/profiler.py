"""Engine step-timeline profiler: where does a decode step's wall go?

ROADMAP item 2 names the engine "dispatch-bound" — device-side stop
detection, multi-step scheduling, and chunked prefill all exist to shave
the Python step loop and the host<->device round-trip — but until now
nothing MEASURED that loop: ``tpu:decode_step_seconds`` sees only the
dispatch+readback wall, and the time the engine thread spends BETWEEN
dispatches (token materialization, admission, stream writes, paged-table
sync) was invisible.  This module is the evidence layer: a bounded ring
recorder charged at the same call sites as the usage tracker
(``server/usage.py``), splitting the engine thread's timeline into three
disjoint buckets:

- **dispatch**: the jitted program call plus its host sync (the
  ``step_s`` every decode/spec/pipelined block already measures, and the
  prefill compute wall) — ``tpu:dispatch_wall_seconds{phase}``;
- **host-sync**: the gap between one dispatch's end and the next
  dispatch's start while the engine had work — the Python step-loop tax
  multi-step scheduling amortizes — ``tpu:dispatch_gap_seconds{kind=
  "host"}``;
- **idle**: gaps that contain a ``_work.wait`` (no admissible work), so
  loop overhead is never blamed on an empty queue —
  ``tpu:dispatch_gap_seconds{kind="idle"}``.

``tools/profile_report.py`` renders the attribution table (shares of the
three buckets summing to 100%); the committed ``PROFILE_BASELINE.json``
run is the baseline every ROADMAP item-2 lever gets measured against.
Per-dispatch records (wall, gap, batch occupancy, step count, net slot
churn) ride ``/debug/profile`` for timeline views.

The recorder sits on the engine thread's hottest path, so it follows the
usage tracker's budget discipline: ``note_dispatch`` is a few float ops
+ two histogram observes + a bounded-deque append per DISPATCH (not per
token), behind the ``EngineConfig.step_profile`` off-switch that exists
for the bench A/B (``step_profile_ratio`` <= 1.05), not for production
use.
"""

from __future__ import annotations

import collections
import os
import time

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.tracing import Histogram

# Dispatch walls are ~100µs (tiny CPU models) to ~100ms (remote TPU
# tunnels); gaps run µs to ms.  One shared edge set keeps the two
# families comparable on a dashboard.
DISPATCH_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                    5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 1.0)

GAP_HOST = "host"
GAP_IDLE = "idle"

# How many per-dispatch records /debug/profile ships (the ring may hold
# more; JSON payloads stay bounded).
SNAPSHOT_RECORDS = 256


class StepProfiler:
    """Bounded per-dispatch timeline recorder for one engine.

    All mutators run on the engine thread; ``snapshot()``/``hist_state()``
    copy out under the lock for the scrape thread (the UsageTracker
    locking pattern).
    """

    def __init__(self, capacity: int | None = None, clock=time.perf_counter):
        if capacity is None:
            capacity = int(os.environ.get("LIG_PROFILE_CAPACITY", "2048"))
        self.capacity = max(1, capacity)
        self._clock = clock
        self._lock = witness_lock("StepProfiler._lock")
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._seq = 0
        # End of the previous dispatch on the engine-thread clock; None
        # until the first dispatch (no gap to attribute yet).
        self._last_end: float | None = None
        # The engine loop found no work since the last dispatch: the next
        # gap contains a wait and is attributed idle, not host-sync.
        self._idle_pending = False
        # Dispatch wall that happened OFF the engine-thread gap clock
        # (prefill walls are stamped with time.time in _record_ttft, so
        # they cannot anchor the perf_counter gap chain; their wall is
        # subtracted from the next gap instead of double-counting as
        # host-sync).
        self._foreign_wall = 0.0
        self._prev_active = 0
        # Cumulative buckets (the attribution table's numerators).
        self.dispatch_seconds: dict[str, float] = {}
        self.dispatches: dict[str, int] = {}
        self.gap_seconds: dict[str, float] = {GAP_HOST: 0.0, GAP_IDLE: 0.0}
        self.padding_tokens = 0
        self.wall_hist: dict[str, Histogram] = {}
        self.gap_hist: dict[str, Histogram] = {
            GAP_HOST: Histogram(DISPATCH_BUCKETS),
            GAP_IDLE: Histogram(DISPATCH_BUCKETS),
        }

    # -- engine-thread mutators ---------------------------------------------
    def note_idle(self) -> None:
        """The loop is about to wait for work: the next inter-dispatch gap
        is queue idleness, not step-loop overhead."""
        self._idle_pending = True

    def note_padding(self, pad_tokens: int) -> None:
        if pad_tokens > 0:
            with self._lock:
                self.padding_tokens += pad_tokens

    def note_dispatch(self, phase: str, t0: float | None, wall_s: float,
                      active: int = 0, total_slots: int = 0,
                      n_steps: int = 1) -> None:
        """Record one dispatch.

        ``t0`` is the dispatch start on the engine thread's perf_counter
        clock — it anchors the host-sync gap chain.  ``None`` means the
        wall was measured on a different clock (prefill): the wall is
        recorded but excluded from gap math, and subtracted from the next
        gap so prefill compute is never misattributed as host-sync.
        """
        if wall_s < 0.0:
            wall_s = 0.0
        gap = 0.0
        gap_kind = ""
        with self._lock:
            self.dispatch_seconds[phase] = (
                self.dispatch_seconds.get(phase, 0.0) + wall_s)
            self.dispatches[phase] = self.dispatches.get(phase, 0) + 1
            hist = self.wall_hist.get(phase)
            if hist is None:
                hist = self.wall_hist[phase] = Histogram(DISPATCH_BUCKETS)
            hist.observe(wall_s)
            if t0 is None:
                self._foreign_wall += wall_s
            else:
                if self._last_end is not None and t0 > self._last_end:
                    gap = max(0.0, t0 - self._last_end - self._foreign_wall)
                    gap_kind = GAP_IDLE if self._idle_pending else GAP_HOST
                    self.gap_seconds[gap_kind] += gap
                    self.gap_hist[gap_kind].observe(gap)
                self._foreign_wall = 0.0
                self._idle_pending = False
                self._last_end = t0 + wall_s
            self._seq += 1
            churn = active - self._prev_active
            self._prev_active = active
            self._ring.append((self._seq, phase, round(wall_s, 9),
                               round(gap, 9), gap_kind, active, total_slots,
                               n_steps, churn))

    # -- export (any thread) -------------------------------------------------
    def attribution(self) -> dict:
        """The gap-attribution summary: absolute seconds per bucket and
        shares of the tracked total — dispatch + host + idle tile the
        tracked timeline, so the shares sum to 100% by construction."""
        with self._lock:
            dispatch = sum(self.dispatch_seconds.values())
            host = self.gap_seconds[GAP_HOST]
            idle = self.gap_seconds[GAP_IDLE]
            by_phase = dict(self.dispatch_seconds)
            n = sum(self.dispatches.values())
        total = dispatch + host + idle
        if total > 0:
            # The largest bucket absorbs the rounding remainder so the
            # three rounded shares sum to exactly 1.0 — consumers (and
            # the committed-baseline test) rely on "100% by construction".
            shares = {"dispatch": round(dispatch / total, 6),
                      "host_sync": round(host / total, 6),
                      "idle": round(idle / total, 6)}
            largest = max(shares, key=lambda k: shares[k])
            shares[largest] = round(
                1.0 - sum(v for k, v in shares.items() if k != largest), 6)
        else:
            shares = {"dispatch": 0.0, "host_sync": 0.0, "idle": 0.0}
        return {
            "dispatches": n,
            "dispatch_seconds": round(dispatch, 6),
            "host_sync_seconds": round(host, 6),
            "idle_seconds": round(idle, 6),
            "tracked_seconds": round(total, 6),
            "dispatch_seconds_by_phase": {
                k: round(v, 6) for k, v in sorted(by_phase.items())},
            "shares": shares,
        }

    def hist_state(self) -> dict:
        """The small copy-out ``Engine.metrics_snapshot()`` embeds — the
        ``tpu:dispatch_wall_seconds`` / ``tpu:dispatch_gap_seconds``
        exposition source (server/metrics.py)."""
        with self._lock:
            return {
                "wall": {p: h.state()
                         for p, h in sorted(self.wall_hist.items())},
                "gap": {k: h.state()
                        for k, h in sorted(self.gap_hist.items())},
            }

    def snapshot(self) -> dict:
        """The full ``/debug/profile`` payload: attribution summary,
        histogram states, and the newest per-dispatch records."""
        with self._lock:
            records = list(self._ring)[-SNAPSHOT_RECORDS:]
            padding = self.padding_tokens
        return {
            "capacity": self.capacity,
            "seq": self._seq,
            "padding_tokens": padding,
            "attribution": self.attribution(),
            "hist": self.hist_state(),
            "records": [
                {"seq": seq, "phase": phase, "wall_s": wall, "gap_s": gap,
                 **({"gap_kind": kind} if kind else {}),
                 "active": active, "slots": slots, "n_steps": n_steps,
                 "slot_churn": churn}
                for (seq, phase, wall, gap, kind, active, slots, n_steps,
                     churn) in records],
        }


def render_profile(hist: dict) -> list[str]:
    """Exposition lines for one ``StepProfiler.hist_state()`` payload
    (the server/metrics.py render seam)."""
    from llm_instance_gateway_tpu.tracing import render_histogram

    lines: list[str] = []
    first = True
    for phase, state in (hist.get("wall") or {}).items():
        lines += render_histogram("tpu:dispatch_wall_seconds", state,
                                  {"phase": phase}, type_line=first)
        first = False
    first = True
    for kind, state in (hist.get("gap") or {}).items():
        lines += render_histogram("tpu:dispatch_gap_seconds", state,
                                  {"kind": kind}, type_line=first)
        first = False
    return lines
