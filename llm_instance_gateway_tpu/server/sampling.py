"""Token sampling: greedy, temperature, top-k, top-p — batched and jittable.

All paths are shape-static (top-k uses a fixed k; top-p masks a sorted copy)
so the decode step compiles once regardless of per-request sampling params.
Per-row parameters arrive as arrays, letting one batch mix sampling configs —
required for multiplexed serving where every slot is a different request.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample(
    logits: jax.Array,        # [B, V] f32
    key: jax.Array,
    temperature: jax.Array,   # [B] f32; 0 = greedy
    top_k: jax.Array,         # [B] int32; 0 = disabled
    top_p: jax.Array,         # [B] f32; 1.0 = disabled
    valid_vocab: int | None = None,  # static: ids >= this are MXU padding
) -> jax.Array:
    """Returns sampled token ids [B].

    ``valid_vocab`` masks the vocab-padding columns (the lm_head is padded to
    a multiple of 128 for MXU tiling with zero — hence logit 0.0 — columns);
    without the mask, temperature sampling could emit ids the tokenizer has
    never heard of.
    """
    b, v = logits.shape
    if valid_vocab is not None and valid_vocab < v:
        pad_mask = jnp.arange(v) < valid_vocab
        logits = jnp.where(pad_mask[None, :], logits, NEG_INF)
    greedy = jnp.argmax(logits, axis=-1)

    # Temperature scaling (guard zero; greedy rows are selected at the end).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # Top-k: mask everything below the k-th largest.  Fixed-shape sort.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, v) - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]
    masked = jnp.where(scaled >= kth, scaled, NEG_INF)

    # Top-p over the already-top-k-masked distribution.  Top-k masking cannot
    # reorder a descending sort, so the sorted masked values are derivable
    # from the first sort — no second O(V log V) sort in the decode hot loop.
    ranks = jnp.arange(v)[None, :]
    sorted_masked = jnp.where(ranks <= k_idx[:, None], sorted_desc, NEG_INF)
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    # Keep tokens while exclusive-cumulative < top_p; the top-1 token is kept
    # unconditionally so top_p=0 degrades to argmax instead of a full mask.
    cutoff_mask = ((cumulative - probs_sorted) < top_p[:, None]) | (ranks == 0)
    threshold = jnp.where(cutoff_mask, sorted_masked, jnp.inf).min(axis=-1)  # [B]
    masked = jnp.where(masked >= threshold[:, None], masked, NEG_INF)

    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
