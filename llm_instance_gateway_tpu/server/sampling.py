"""Token sampling: greedy, temperature, top-k, top-p — batched and jittable.

All paths are shape-static (top-k uses a fixed k; top-p masks a sorted copy)
so the decode step compiles once regardless of per-request sampling params.
Per-row parameters arrive as arrays, letting one batch mix sampling configs —
required for multiplexed serving where every slot is a different request.

Also home to the DEVICE-SIDE stop-sequence automaton the fused decode block
evaluates per step (``stop_hist_update``/``stop_suffix_hit``): each row
carries a ring of its last ``STOP_LEN`` emitted tokens, and a per-row table
of right-aligned stop suffixes (-1 padded) matches against it with one
masked compare — no host round-trip per emitted token, which is what lets
``decode_steps_per_sync``/adaptive fusion stay safe for requests carrying
stop strings (the host only trims the overshoot once per dispatch).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Device stop-automaton lanes: at most STOP_SEQS token-suffix sequences per
# row (the OpenAI surface caps `stop` at 4 strings), each at most STOP_LEN
# tokens.  Rows whose stops exceed either bound fall back to the host
# oracle (the engine leaves their lanes empty) — correctness never depends
# on fitting the lanes, only the fused-dispatch freeze does.
STOP_SEQS = 4
STOP_LEN = 8


def encode_stop_rows(
    sequences,                 # iterable of token-id tuples for ONE row
) -> "tuple[list[list[int]], list[int]] | None":
    """(ids [STOP_SEQS][STOP_LEN] right-aligned -1-padded, lens [STOP_SEQS])
    device lanes for one row's stop sequences, or ``None`` when they do not
    fit the static lanes (too many, too long, or empty entries)."""
    seqs = [tuple(int(t) for t in s) for s in sequences]
    if len(seqs) > STOP_SEQS or any(
            not s or len(s) > STOP_LEN for s in seqs):
        return None
    ids = [[-1] * STOP_LEN for _ in range(STOP_SEQS)]
    lens = [0] * STOP_SEQS
    for j, s in enumerate(seqs):
        ids[j][STOP_LEN - len(s):] = list(s)
        lens[j] = len(s)
    return ids, lens


def stop_hist_update(hist: jax.Array, sampled: jax.Array,
                     advance: jax.Array) -> jax.Array:
    """Shift each advancing row's token history left and append the newly
    sampled token (``hist`` [B, STOP_LEN] int32, -1 = not yet generated).
    Frozen rows (``advance`` False) keep their history unchanged."""
    shifted = jnp.concatenate(
        [hist[:, 1:], sampled[:, None].astype(hist.dtype)], axis=1)
    return jnp.where(advance[:, None], shifted, hist)


def stop_suffix_hit(hist: jax.Array, stop_ids: jax.Array,
                    stop_lens: jax.Array) -> jax.Array:
    """[B] bool: some stop sequence matches the row's history suffix.

    ``stop_ids`` [B, STOP_SEQS, STOP_LEN] is right-aligned with -1 padding,
    so an element-wise masked compare against the history tail IS the
    suffix match; -1 history entries (fewer tokens generated than the stop
    is long) can never equal a validated stop id, so short histories never
    false-match.  All-pad lanes are excluded via ``stop_lens`` > 0."""
    pad = stop_ids < 0
    eq = stop_ids == hist[:, None, :]
    matched = jnp.all(pad | eq, axis=-1)          # [B, STOP_SEQS]
    return jnp.any(matched & (stop_lens > 0), axis=-1)


def sample(
    logits: jax.Array,        # [B, V] f32
    key: jax.Array,
    temperature: jax.Array,   # [B] f32; 0 = greedy
    top_k: jax.Array,         # [B] int32; 0 = disabled
    top_p: jax.Array,         # [B] f32; 1.0 = disabled
    valid_vocab: int | None = None,  # static: ids >= this are MXU padding
    seeds: jax.Array | None = None,      # [B] int32; -1 = engine RNG
    positions: jax.Array | None = None,  # [B] int32 — current input position
    bias_ids: jax.Array | None = None,   # [B, K] int32; -1 = unused entry
    bias_vals: jax.Array | None = None,  # [B, K] f32 — OpenAI logit_bias
) -> jax.Array:
    """Returns sampled token ids [B].

    ``valid_vocab`` masks the vocab-padding columns (the lm_head is padded to
    a multiple of 128 for MXU tiling with zero — hence logit 0.0 — columns);
    without the mask, temperature sampling could emit ids the tokenizer has
    never heard of.

    ``seeds``/``positions``: per-request reproducible sampling (the OpenAI
    ``seed`` param).  A row with seed >= 0 draws from
    fold_in(PRNGKey(seed), position) instead of the shared engine key, so
    its tokens depend only on (seed, position, distribution) — identical
    across runs, restarts, and whatever else shares its batch.  Rows at -1
    keep the engine-RNG draw bit-for-bit.
    """
    b, v = logits.shape
    if valid_vocab is not None and valid_vocab < v:
        pad_mask = jnp.arange(v) < valid_vocab
        logits = jnp.where(pad_mask[None, :], logits, NEG_INF)
    if bias_ids is not None:
        # OpenAI logit_bias: applied before EVERYTHING (greedy argmax
        # included).  Pad entries (-1) scatter zero onto a clipped index.
        rows = jnp.arange(b)[:, None]
        logits = logits.at[rows, jnp.clip(bias_ids, 0, v - 1)].add(
            jnp.where(bias_ids >= 0, bias_vals, 0.0))
    greedy = jnp.argmax(logits, axis=-1)

    # Temperature scaling (guard zero; greedy rows are selected at the end).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # Top-k: mask everything below the k-th largest.  Fixed-shape sort.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, v) - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]
    masked = jnp.where(scaled >= kth, scaled, NEG_INF)

    # Top-p over the already-top-k-masked distribution.  Top-k masking cannot
    # reorder a descending sort, so the sorted masked values are derivable
    # from the first sort — no second O(V log V) sort in the decode hot loop.
    ranks = jnp.arange(v)[None, :]
    sorted_masked = jnp.where(ranks <= k_idx[:, None], sorted_desc, NEG_INF)
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    # Keep tokens while exclusive-cumulative < top_p; the top-1 token is kept
    # unconditionally so top_p=0 degrades to argmax instead of a full mask.
    cutoff_mask = ((cumulative - probs_sorted) < top_p[:, None]) | (ranks == 0)
    threshold = jnp.where(cutoff_mask, sorted_masked, jnp.inf).min(axis=-1)  # [B]
    masked = jnp.where(masked >= threshold[:, None], masked, NEG_INF)

    sampled = jax.random.categorical(key, masked, axis=-1)
    if seeds is not None:
        def seeded_draws(_):
            def row_draw(seed, pos, row_logits):
                k = jax.random.fold_in(
                    jax.random.PRNGKey(jnp.maximum(seed, 0)), pos)
                return jax.random.categorical(k, row_logits)

            seeded = jax.vmap(row_draw)(
                seeds, positions.astype(jnp.int32), masked)
            return jnp.where(seeds >= 0, seeded, sampled)

        # lax.cond: the common all-unseeded batch skips the B key setups
        # and the second full-vocab draw at runtime.
        sampled = jax.lax.cond(
            jnp.any(seeds >= 0), seeded_draws, lambda _: sampled, None)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
