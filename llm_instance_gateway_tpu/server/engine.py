"""Continuous-batching serving engine: prefill/decode split over static slots.

JetStream-style architecture (the TPU answer to vLLM's continuous batching the
reference routes to, SURVEY.md §2.5/§7):

- **Prefill** runs one prompt at a time, padded to a small set of bucket
  lengths (a handful of compiled shapes, never per-request recompiles), and
  produces the prompt KV + the first sampled token.
- **Insert** writes the prompt KV into a free row of the static decode cache
  (``[n_layers, decode_slots, max_seq_len, n_kv, hd]``).
- **Generate** advances ALL active slots one token per step with a single
  fixed-shape jitted function (decode + sampling fused into one program,
  cache donated so XLA updates it in place).

The engine thread interleaves: one prefill admission, then decode steps.
Queues are explicit and exported: ``prefill_queue`` (admission backlog) and
``decode_wait`` (prefilled but waiting for a free slot) — the two signals the
gateway's prefill-aware scheduler routes on (``gateway/scheduling``).

Requests carry an optional LoRA adapter name; the slot id from
``LoRAManager`` rides into the decode batch per-row, so one batch multiplexes
adapters and the base model (the premise of the gateway's affinity routing).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import logging
import queue as queue_mod
import threading
import time
import uuid
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.models import paged as paged_lib
from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import ModelConfig
from llm_instance_gateway_tpu.server.sampling import (
    STOP_LEN,
    STOP_SEQS,
    encode_stop_rows,
    sample,
    stop_hist_update,
    stop_suffix_hit,
)
from llm_instance_gateway_tpu.server.kv_ledger import KvLedger
from llm_instance_gateway_tpu.server.profiler import StepProfiler
from llm_instance_gateway_tpu.server.usage import UsageTracker, owner_key
from llm_instance_gateway_tpu.tracing import LATENCY_BUCKETS, Histogram

logger = logging.getLogger(__name__)

# Top-K alternatives computed device-side per step (the OpenAI completions
# API maximum).  Always computed — one compiled program for the whole batch,
# and a [B, K] top_k is noise next to the layer matmuls; the host stores
# values only for requests that asked.
LOGPROB_TOPK = 5
MAX_LOGIT_BIAS = 32  # per-request logit_bias entries (static lanes)

# Dispatch-size histogram edges for tpu:dispatch_steps: the planner only
# emits powers of two, so the buckets land exactly on its choices.
STEP_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


class EngineDraining(RuntimeError):
    """submit() refused because the engine is in graceful termination.

    Distinct type so the HTTP layer maps exactly this condition to a 503
    the gateway retries elsewhere; any other RuntimeError stays a 500
    (reference parity: only a draining replica reports itself unroutable;
    pkg/ext-proc/handlers/server.go's ResourceExhausted mapping is the
    analogous single-condition translation)."""


def _logprob_info(logits, sampled, valid_vocab: int):
    """(sampled-token logprob, top-K logprobs, top-K ids) from raw logits.

    Model logprobs (pre-temperature), padded-vocab positions masked out —
    consistent with what the sampler can actually emit.
    """
    masked = jnp.where(
        jnp.arange(logits.shape[-1]) < valid_vocab, logits, -jnp.inf
    )
    logp = jax.nn.log_softmax(masked, axis=-1)
    sampled_lp = jnp.take_along_axis(logp, sampled[..., None], axis=-1)[..., 0]
    top_v, top_i = jax.lax.top_k(logp, LOGPROB_TOPK)
    return sampled_lp, top_v, top_i


@dataclass
class EngineConfig:
    decode_slots: int = 8
    max_seq_len: int = 1024
    prefill_buckets: tuple[int, ...] = (16, 32, 64, 128, 256, 512, 1024)
    max_queue: int = 256
    # Decode steps fused into one jitted program per host sync.  Each host
    # round-trip costs dispatch latency (tens of ms through a remote TPU
    # tunnel); K>1 amortizes it.  Stop detection is device-side (rows freeze
    # at EOS/budget and emit invalid steps), so large K costs only K-step
    # admission latency, not wasted tokens.
    decode_steps_per_sync: int = 1
    # Adaptive multi-step dispatch (ROADMAP item 2): > 0 makes this the
    # CEILING of a per-dispatch planner that picks n_steps from the live
    # batch instead of the static decode_steps_per_sync — the minimum
    # remaining token budget across active rows (never fuse past the point
    # every row is frozen), pending admissions / parked inserts / chunk
    # streams (a waiting prefill or stream chunk must not stall behind a
    # fused block: those dispatches drop to 1 step), and SSE cadence
    # (dispatches serving a streaming consumer cap at
    # ``adaptive_stream_cap`` so fusion can't regress perceived TPOT).
    # Choices quantize to powers of two, so the compiled-variant set stays
    # log2(ceiling) deep.  0 = off (static decode_steps_per_sync).
    adaptive_steps: int = 0
    # Fused-step cap while any active row streams to an SSE consumer (the
    # planner input that keeps adaptive fusion from batching a stream's
    # tokens into bursts).  1 = per-token cadence (default).
    adaptive_stream_cap: int = 1
    # Device-side stop sequences: compile each row's stop strings (token
    # suffixes) into per-row automata evaluated INSIDE the fused decode
    # program, so a row whose stop hits mid-block freezes there with zero
    # host round-trips (the host oracle still trims once per dispatch —
    # token parity is structural, not probabilistic).  False = host-only
    # stop checks (the A/B oracle for the bench and the parity tests).
    device_stops: bool = True
    # Concurrent chunk-stream lanes: how many long prompts may stream
    # chunk-by-chunk into reserved cache lanes AT ONCE (fair round-robin,
    # one chunk per engine cycle across lanes).  1 = the old single-lane
    # behavior where a second long prompt head-of-line blocks behind the
    # first.  Lanes beyond the first admit only with KV headroom left for
    # active decode growth (paged pools).
    stream_lanes: int = 1
    # Pipelined decode: dispatch block N+1 from the device-resident token/
    # position/budget carry BEFORE reading block N's tokens, overlapping the
    # host readback with compute.  Slot FREEING still lags one block (the
    # frozen row just decodes invalid steps until the host sees the stop).
    pipeline_decode: bool = False
    # Tokens/sec EMA smoothing for the exported throughput gauge.
    tps_ema_alpha: float = 0.2
    # Prefill-ahead depth: prompts prefilled while all decode slots are busy
    # wait here (KV held off-cache) and insert the instant a slot frees —
    # the decode batch never idles a slot waiting for a prefill, and the
    # queue length is the true ``tpu:decode_queue_size`` the gateway's
    # prefill-aware scheduler routes on.  None = decode_slots.
    decode_wait_cap: int | None = None
    # Grouped prefill admission: when several queued prompts land in the
    # SAME bucket and free slots exist, up to this many prefill as ONE
    # [P, bucket] program instead of P dispatches — small-batch prefill
    # underfills the MXU and each dispatch pays the host round-trip, so
    # bursts admit near-P-times faster.  Compiled-shape set stays bounded:
    # buckets x group sizes (2..prefill_batch).  1 = off (existing path).
    # Applies to the direct-admission path of the contiguous-lane cache;
    # paged admissions stay per-request (block allocation is per-row
    # backpressure).
    prefill_batch: int = 1
    # Abandoned-handoff TTL: an attach-imported request still PARKED in
    # decode_wait this many seconds after admission is presumed abandoned
    # (its gateway gave up on the hop and rerouted) and is failed/freed by
    # the engine loop's sweep instead of eventually decoding tokens nobody
    # will read.  0 disables; the gateway's best-effort
    # ``POST /v1/prefill/release`` is the fast path, this is the backstop.
    handoff_ttl_s: float = 0.0
    # Paged KV cache (models/paged.py): block size in tokens; None = the
    # default contiguous-lane cache.  With paging, the kv metrics report
    # allocated/total blocks — vLLM's gpu_cache_usage_perc semantics, which
    # the reference's 0.8 routing threshold was tuned against — and
    # ``paged_kv_blocks`` may be set below slots*ceil(max_seq/block) to
    # oversubscribe HBM for short-sequence traffic (a request that outgrows
    # an exhausted pool fails with "kv pool exhausted"; keep the gateway's
    # KV threshold at/below 0.8 to stay clear of it).
    paged_kv_block: int | None = None
    paged_kv_blocks: int | None = None
    # Speculative decoding: a small DRAFT model proposes this many tokens
    # per cycle; the target model verifies them in ONE multi-token forward
    # (extend_step) — decode is HBM-weight-bound, so scoring K+1 tokens
    # costs barely more than one step, and accepted prefixes multiply
    # tokens/step.  Greedy rows (temperature 0) accept the longest matching
    # prefix — EXACT greedy parity with non-speculative decoding; sampled
    # rows fall back to one verified token per cycle.  Requires
    # ``draft_params``/``draft_cfg`` at Engine construction.  Composes with
    # BOTH engine loops, ``decode_steps_per_sync`` (cycles are fused into
    # one device-side scan of ceil(steps/(K+1)) cycles per dispatch — the
    # bench's pipelined fast path included), the paged cache
    # (extend_step_paged verify), and GSPMD serve meshes (draft replicated)
    # — including all three together on a tensor/expert mesh
    # (parity-tested).  paged + a data mesh is excluded by the engine's
    # own paged/mesh rule, independent of speculation; paged + a sequence
    # mesh (ring prefill) constructs, with speculation verifying through
    # extend_step_paged as usual.
    speculative_k: int = 0
    # KV-cache quantization ("int8" or None): K/V stored int8 with
    # per-(position, kv-head) f32 scales, dequantized inside the fused
    # attention reads — long-context decode is KV-bandwidth-bound and int8
    # halves that HBM traffic (the JetStream serving trade; scale overhead
    # 1/(2*head_dim)).  Composes with BOTH cache layouts: contiguous lanes
    # and the paged pool (vLLM's quantized-paged-KV composition — scale
    # pools index by physical block, so prefix-cache reuse carries them
    # for free), and with prefix caching, grouped admission, chunked
    # prefill, speculative decoding, and GSPMD meshes.  Decode attention
    # runs the int8-aware Pallas kernel
    # (ops/pallas_decode_attention.decode_attention_quant — dequantizes in
    # VMEM at the MXU feed), so the bandwidth win and the kernel win stack
    # on the lane path AND on the paged gathered view.
    kv_cache_quant: str | None = None
    # Pool role under cross-engine prefill/decode disaggregation
    # (server/kv_transfer.py): "prefill" replicas serve prefill_only()
    # handoffs, "decode" replicas serve attach_prefilled() imports,
    # "collocated" runs both phases locally (the default).  The role is
    # ADVISORY — every engine keeps the full API in every role, so a
    # gateway can always fall back to single-hop serving — but it is
    # exported via /metrics and drives the gateway's two-stage routing.
    role: str = "collocated"
    # Per-adapter capacity attribution (server/usage.py): charge decode
    # step wall time, tokens, and KV block-seconds to the {adapter} of
    # each active slot, plus pool-waste observables (batch occupancy,
    # idle-slot-seconds, prefill padding).  A few dict ops per DISPATCH;
    # the off switch exists for the bench.py overhead A/B
    # (usage_attribution_ratio), not for production use.
    usage_attribution: bool = True
    # Step-timeline profiler (server/profiler.py): per-dispatch wall /
    # host-sync gap / idle attribution in a bounded ring, exported as
    # tpu:dispatch_wall_seconds / tpu:dispatch_gap_seconds and served by
    # /debug/profile — the evidence layer for the dispatch-bound decode
    # levers (ROADMAP item 2).  Like usage_attribution, the off switch
    # exists for the bench A/B (step_profile_ratio <= 1.05), not for
    # production use.
    step_profile: bool = True
    # KV economy ledger (server/kv_ledger.py, paged mode only): per-state
    # block accounting (free/active/parked/prefix-resident tiling the
    # budget), a per-prefix reuse table, fragmentation/headroom
    # histograms, and a bounded lifecycle event ring — exported as the
    # tpu:kv_* families and served by /debug/kv.  Like the trackers
    # above, the off switch exists for the bench A/B (kv_ledger_ratio
    # < 1.05), not for production use.
    kv_ledger: bool = True
    # Prefix caching (paged mode only): full prompt blocks are
    # content-addressed (chained hashes, vLLM-style) and retained with
    # refcounts after a request finishes; a later prompt sharing the prefix
    # maps the cached blocks into its table and prefills only the suffix.
    # Reuse applies on BOTH admission paths: bucketed prompts (the shared
    # system prompt below the largest bucket — the common case) run one
    # suffix-bucket chunk program over the mapped prefix, and chunk-stream
    # prompts skip whole leading chunks.  Grouped/parked admissions
    # register their blocks for later consumers.  Zero-ref cached blocks
    # are evicted LRU when the pool needs space, so enabling this costs
    # nothing but the hashing.
    prefix_cache: bool = False


@dataclass
class SamplingParams:
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # Reproducible sampling (OpenAI `seed`): tokens depend only on
    # (seed, position, distribution) — batch composition, restarts, and
    # the engine RNG stop mattering.  None = engine RNG.
    seed: int | None = None
    # OpenAI penalties over GENERATED tokens (vLLM semantics — the prompt
    # does not count): presence subtracts a flat amount from every
    # already-emitted token's logit, frequency subtracts per occurrence.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # OpenAI logit_bias: {token_id: bias} added to the logits before every
    # pick (greedy included).  At most MAX_LOGIT_BIAS entries (static
    # device lanes).
    logit_bias: dict[int, float] | None = None


def _bias_arrays(sp: "SamplingParams"):
    """(ids [MAX_LOGIT_BIAS] int32, vals f32) device lanes for a request's
    logit_bias dict (-1 = unused entry)."""
    ids = np.full((MAX_LOGIT_BIAS,), -1, np.int32)
    vals = np.zeros((MAX_LOGIT_BIAS,), np.float32)
    if sp.logit_bias:
        for j, (tid, bv) in enumerate(sorted(sp.logit_bias.items())):
            ids[j] = tid
            vals[j] = bv
    return ids, vals


def _seed_i32(seed: int | None) -> int:
    """Map a user seed onto the device-side int32 lane: None -> -1 (engine
    RNG); any int masks to non-negative 31-bit (PRNGKey folds it, so only
    equality of masked values matters for reproducibility)."""
    return -1 if seed is None else (int(seed) & 0x7FFFFFFF)


@dataclass
class Request:
    prompt_tokens: list[int]
    max_new_tokens: int = 64
    sampling: SamplingParams = field(default_factory=SamplingParams)
    adapter: str | None = None
    stop_token_ids: tuple[int, ...] = ()
    # Multi-token stop sequences (tokenized stop strings): generation ends
    # the moment the output's token tail equals one of these; the matching
    # tokens are emitted and finish_reason is "stop" (same inclusion
    # semantics as stop_token_ids).  Evaluated device-side inside the
    # fused decode block when they fit the static automaton lanes
    # (sampling.STOP_SEQS x STOP_LEN) and ``EngineConfig.device_stops``;
    # the host oracle in the result walk is authoritative either way.
    stop_sequences: tuple[tuple[int, ...], ...] = ()
    # Set by the transport for SSE responses: the adaptive dispatch
    # planner caps fusion at ``adaptive_stream_cap`` while this request is
    # active, so a live stream keeps per-token cadence.
    streaming: bool = False
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:16])
    # Record per-token logprobs: None = off; 0 = sampled token only (e.g.
    # best_of ranking); 1..LOGPROB_TOPK = also that many top alternatives.
    logprobs: int | None = None
    # Lifecycle (filled by the engine).
    # Cross-engine disaggregation: prefill_only() deposits the request's
    # PrefillHandoff here (finish_reason "handoff").
    handoff: object = None
    output_tokens: list[int] = field(default_factory=list)
    output_logprobs: list[float] = field(default_factory=list)
    output_top_logprobs: list[dict[int, float]] = field(default_factory=list)
    finish_reason: str | None = None
    error: str | None = None
    t_submit: float = 0.0
    # Wall clock of the request's first prefill compute (queue wait ends
    # here; the tracing layer derives the engine.queue_wait/engine.prefill
    # span boundary from it).  0.0 = never prefilled on THIS engine (e.g.
    # an attached handoff, whose prefill ran on the prefill-role replica).
    t_prefill_start: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0
    done: threading.Event = field(default_factory=threading.Event)
    # Incremental consumption point for streaming responses.
    stream_event: threading.Event = field(default_factory=threading.Event)
    # Set by the transport when the client went away: the engine frees the
    # slot at the next block boundary instead of decoding to completion.
    cancelled: threading.Event = field(default_factory=threading.Event)

    @property
    def ttft_s(self) -> float:
        return (self.t_first_token - self.t_submit) if self.t_first_token else 0.0


class PagedPoolExhausted(Exception):
    """The paged KV pool has no free blocks (oversubscribed pool)."""


@dataclass
class _Slot:
    request: Request
    lora_slot: int
    position: int  # position of the NEXT token to generate
    # Pipelined mode: device array holding the prefill's first sampled token,
    # materialized when this slot's first decode block is processed.
    pending_first: object = None


class _HostBatch:
    """One device->host transfer for a grouped-prefill output set.

    The pipelined grouped path previously async-copied P separate 0-d
    device scalars (and later sync-transferred each at materialization),
    re-paying per-row dispatch round-trips the batched prefill was meant to
    amortize.  This starts ONE async copy per array and materializes all
    rows with one ``np.asarray`` per array on first access — mirroring the
    sync path's single bulk transfer.
    """

    __slots__ = ("arrays", "_host")

    def __init__(self, *arrays):
        self.arrays = arrays
        for a in arrays:
            try:
                a.copy_to_host_async()
            except AttributeError:
                pass
        self._host = None

    def host(self):
        if self._host is None:
            self._host = tuple(np.asarray(a) for a in self.arrays)
        return self._host


class _Row:
    """Row ``i`` of array ``a`` in a ``_HostBatch``: numpy-protocol view
    whose first host access materializes the whole batch.  ``dev`` carries
    the device slice for carry scatters that must stay device-resident
    (the pipelined loop's no-host-round-trip contract)."""

    __slots__ = ("_batch", "_a", "_i", "dev")

    def __init__(self, batch: _HostBatch, a: int, i: int, dev=None):
        self._batch, self._a, self._i, self.dev = batch, a, i, dev

    def _value(self):
        return self._batch.host()[self._a][self._i]

    def __array__(self, dtype=None, copy=None):
        v = np.asarray(self._value())
        return v.astype(dtype) if dtype is not None else v

    def __int__(self):
        return int(self._value())


@dataclass
class _WaitingPrefill:
    """A prefilled request parked in ``decode_wait``: prompt KV held
    off-cache until a decode slot frees (JetStream's prefill/decode
    disaggregation inside one engine)."""

    request: Request
    first_token: object  # device scalar (sync mode materializes eagerly)
    k: object            # [L, 1, bucket, Kh, hd]
    v: object
    n: int
    lora_slot: int
    first_token_host: int | None = None  # sync mode: already-emitted token
    # First-token (lp, top_v, top_i) device tuple; None once recorded.
    lp_info: object = None
    # Cross-engine attach: the first token was already emitted (on THIS
    # engine, at attach admission) — the pipelined insert must not schedule
    # a second pending-first materialization.
    first_emitted: bool = False
    # Imported via attach_prefilled: the insert may map already-cached
    # prefix blocks instead of re-writing identical content.
    from_handoff: bool = False
    # Wall clock at park time; with ``handoff_ttl_s`` set, an imported
    # handoff that sat parked past the TTL is abandoned work (the gateway
    # that posted it gave up and rerouted) and is swept instead of slotted.
    t_parked: float = 0.0


@dataclass
class _ChunkStream:
    """A long prompt streaming into a RESERVED cache lane one chunk per
    engine cycle, so active decode slots keep stepping between chunks
    (round-1 admitted the whole prompt in one blocking burst, coupling
    queued TTFT to running TPOT)."""

    request: Request
    slot_idx: int
    lora_slot: int
    next_start: int = 0
    last_logits: object = None


class Engine:
    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        engine_cfg: EngineConfig | None = None,
        lora_manager=None,
        eos_id: int | None = None,
        dtype=jnp.bfloat16,
        seed: int = 0,
        mesh=None,
        draft_params=None,
        draft_cfg: ModelConfig | None = None,
    ):
        self.model_cfg = model_cfg
        self.cfg = engine_cfg or EngineConfig()
        self.params = params
        self.lora = lora_manager
        self.eos_id = eos_id
        self._rng = jax.random.PRNGKey(seed)

        self._spec = self.cfg.speculative_k > 0
        if self._spec:
            if draft_params is None or draft_cfg is None:
                raise ValueError(
                    "speculative_k > 0 requires draft_params and draft_cfg")
            if draft_cfg.vocab_size != model_cfg.vocab_size:
                raise ValueError(
                    "draft and target models must share the token space "
                    f"({draft_cfg.vocab_size} != {model_cfg.vocab_size})")
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg

        b = self.cfg.decode_slots
        self.paged = self.cfg.paged_kv_block is not None
        self._kv_quant = self.cfg.kv_cache_quant is not None
        if self.cfg.kv_cache_quant not in (None, "int8"):
            raise ValueError(
                f"kv_cache_quant={self.cfg.kv_cache_quant!r}: only 'int8' "
                "(or None) is supported")
        if self.paged:
            self._block = self.cfg.paged_kv_block
            self._max_blocks_per_seq = -(-self.cfg.max_seq_len // self._block)
            self._n_blocks = (
                self.cfg.paged_kv_blocks
                if self.cfg.paged_kv_blocks is not None
                else b * self._max_blocks_per_seq
            )
            self.cache = paged_lib.init_paged_cache(
                model_cfg, b, self.cfg.max_seq_len,
                self._n_blocks, self._block, dtype=dtype,
                quantized=self._kv_quant,
            )
            # Host-side allocator: physical block 1..n are allocatable;
            # block 0 is the trash block (paged_lib.TRASH_BLOCK).
            self._free_blocks: list[int] = list(range(1, self._n_blocks + 1))
            self._row_blocks: list[list[int]] = [[] for _ in range(b)]
            self._tables_host = np.zeros(
                (b, self._max_blocks_per_seq), np.int32)
            self._tables_dirty = False
            # Prefix cache state: chain-hash -> block, block -> (hash, refs),
            # plus an LRU of zero-ref cached blocks (evicted on demand).
            self._prefix_enabled = self.cfg.prefix_cache
            self._prefix_table: dict[int, int] = {}
            self._block_hash: dict[int, int] = {}
            self._block_refs: dict[int, int] = {}
            self._evictable: "collections.OrderedDict[int, int]" = (
                collections.OrderedDict())
            self.prefix_reused_tokens = 0
        else:
            self._prefix_enabled = False
            self.cache = transformer.init_decode_cache(
                model_cfg, b, self.cfg.max_seq_len, dtype=dtype,
                quantized=self._kv_quant,
            )
        # Sharded serving (SURVEY §2.5/§7 ICI domain): pin params and the
        # decode cache to the mesh via GSPMD specs; every jitted step then
        # partitions from its committed inputs — XLA inserts the ICI
        # collectives (one psum per layer on attn/MLP outputs for Megatron
        # tensor parallelism), nothing in the loop code changes.
        self.mesh = mesh
        # Pallas kernels under the mesh: GSPMD can't partition an opaque
        # pallas_call, so under a >1 mesh the in-model auto-dispatch is
        # always turned OFF (it would replicate full arrays to every
        # device) — and the kernels come back as shard_map wrappers that
        # run them shard-local over the data/tensor axes
        # (ops/sharded_attention.py).  Head layouts the wrappers can't
        # split group-aligned keep the XLA fallback.
        self._prefill_attn_fn = None
        self._decode_attn_fn = None
        if mesh is not None and mesh.size > 1 and (
            model_cfg.use_flash_attention or model_cfg.use_pallas_decode
        ):
            from llm_instance_gateway_tpu.ops import sharded_attention

            wants_flash = model_cfg.use_flash_attention
            wants_decode = model_cfg.use_pallas_decode
            model_cfg = dataclasses.replace(
                model_cfg, use_flash_attention=False, use_pallas_decode=False)
            self.model_cfg = model_cfg
            if sharded_attention.mesh_supports(model_cfg, mesh):
                if wants_flash and mesh.shape.get("sequence", 1) == 1:
                    # sequence>1 prefill belongs to the ring path; bucketed
                    # prefill there stays XLA rather than paying redundant
                    # per-shard compute.
                    self._prefill_attn_fn = (
                        sharded_attention.make_flash_prefill(model_cfg, mesh))
                if (wants_decode and not self.paged
                        and b % mesh.shape.get("data", 1) == 0):
                    # The batch gate is load-bearing: a non-divisible B
                    # would force shard_map to replicate the data-sharded
                    # KV cache (a full-cache all-gather per layer per
                    # step) — worse than the XLA fallback.  Quantized
                    # lanes get the QUANT-AWARE wrapper (raw int8 + scales
                    # shard-local into the int8 kernel, dequant in VMEM):
                    # the bandwidth win and the kernel win stack under the
                    # mesh too — a plain wrapper here would materialize a
                    # full bf16 cache per layer per step.
                    self._decode_attn_fn = (
                        sharded_attention.make_cached_decode_quant(
                            model_cfg, mesh) if self._kv_quant else
                        sharded_attention.make_cached_decode(model_cfg, mesh))
                logger.info(
                    "mesh size %d: Pallas kernels via shard_map "
                    "(flash_prefill=%s, cached_decode=%s)", mesh.size,
                    self._prefill_attn_fn is not None,
                    self._decode_attn_fn is not None)
            else:
                logger.info(
                    "mesh size %d: head layout (%d q heads, %d kv heads) "
                    "does not split group-aligned over tensor=%d; using "
                    "XLA attention", mesh.size, model_cfg.n_heads,
                    model_cfg.n_kv_heads, mesh.shape.get("tensor", 1))
        if mesh is not None:
            from llm_instance_gateway_tpu.parallel import sharding as sharding_lib

            if mesh.shape.get("pipe", 1) > 1:
                # Serving decodes layer-by-layer through one cache; a pipe
                # axis would only replicate (parallel.pipeline covers the
                # training/prefill side).  Refuse rather than silently waste
                # 1/pipe of the pool.
                raise ValueError(
                    "serving meshes must have pipe=1; fold those devices "
                    "into tensor/data instead")
            if self.paged and mesh.shape.get("data", 1) > 1:
                # The block pool belongs to no mesh axis (rows serve
                # whichever requests the host allocator assigns), so the
                # batch can't shard over data.  Tensor/expert-parallel
                # paged serving — the big-model case — IS supported
                # (paged_cache_specs), and a sequence axis serves RING
                # PREFILL (the pool replicates over it; decode ignores it).
                raise ValueError(
                    "paged KV on a mesh requires data=1 (the pool "
                    "replicates over fsdp/sequence and shards kv-heads "
                    "over tensor): scale data-parallel replicas as "
                    "separate engine processes behind the gateway")
            self.params = sharding_lib.shard_pytree(
                self.params, sharding_lib.param_specs(model_cfg), mesh)
            self.cache = sharding_lib.shard_pytree(
                self.cache,
                (sharding_lib.paged_cache_specs(model_cfg, mesh,
                                                quantized=self._kv_quant)
                 if self.paged else
                 sharding_lib.cache_specs(model_cfg, mesh,
                                          quantized=self._kv_quant)),
                mesh)
        # Ring-attention prefill (parallel/long_context.py): with a
        # sequence axis in the mesh, prompts beyond the largest bucket run
        # as ONE sequence-parallel program over the ring instead of
        # chunk-streaming through the cache lane — each device holds S/n of
        # the activations, so the prompt budget scales with the mesh.
        self._ring = None
        if mesh is not None and mesh.shape.get("sequence", 1) > 1:
            # Lane AND paged engines: the ring computes sequence-sharded
            # prompt KV; the insert (lane dynamic-slice or paged block
            # scatter) reshards it to the cache's own spec under GSPMD.
            from llm_instance_gateway_tpu.parallel import long_context

            self._ring = long_context.make_sharded_prefill(model_cfg, mesh)
            # Pad ring prompts to this multiple: big enough to bound the
            # number of compiled shapes (like prefill buckets), aligned to
            # 8*seq_shards so every device's block is sublane-aligned.
            self._ring_pad = max(8 * mesh.shape["sequence"],
                                 max(self.cfg.prefill_buckets))
        self.slots: list[_Slot | None] = [None] * b
        self._slot_tokens = np.zeros((b,), np.int32)
        self._slot_positions = np.zeros((b,), np.int32)
        self._slot_lora = np.full((b,), -1, np.int32)
        self._slot_temp = np.zeros((b,), np.float32)
        self._slot_topk = np.zeros((b,), np.int32)
        self._slot_topp = np.ones((b,), np.float32)
        self._slot_seed = np.full((b,), -1, np.int32)
        self._slot_presence = np.zeros((b,), np.float32)
        self._slot_frequency = np.zeros((b,), np.float32)
        self._slot_bias_ids = np.full((b, MAX_LOGIT_BIAS), -1, np.int32)
        self._slot_bias_vals = np.zeros((b, MAX_LOGIT_BIAS), np.float32)
        # Generated-token occurrence counts, device-resident (transferring
        # [B, V] per dispatch would swamp the sync loop): rows zero at
        # registration, the decode scan updates them in its carry.
        self._dev_counts = None  # lazy: [B, V_padded] int32 on first use
        # Per-row token budget for device-side stop (0 = frozen row).
        self._slot_remaining = np.zeros((b,), np.int32)
        self._eos_for_device = jnp.int32(-1 if eos_id is None else eos_id)
        # Device stop-string automata (server/sampling.py): per-row stop
        # suffix lanes (right-aligned, -1 padded) programmed at slot
        # registration, plus the host history scratch the sync loop
        # rebuilds per dispatch (the pipelined loop keeps its history
        # device-resident in the dispatch carry instead).
        self._slot_stop_ids = np.full((b, STOP_SEQS, STOP_LEN), -1, np.int32)
        self._slot_stop_lens = np.zeros((b, STOP_SEQS), np.int32)
        self._slot_stop_hist = np.full((b, STOP_LEN), -1, np.int32)
        # Count of rows with programmed device stop lanes: gates the
        # per-dispatch history rebuild AND excludes speculative dispatch
        # (the spec block does not evaluate the automaton, so its history
        # carry would go stale mid-generation).
        self._stops_active = 0

        self.prefill_queue: queue_mod.Queue[Request] = queue_mod.Queue(
            maxsize=self.cfg.max_queue
        )
        # Prefilled-but-unslotted requests (the engine docstring's
        # ``decode_wait``); plus the head-of-line request pulled off the
        # queue but not yet admissible (e.g. a chunked prompt with no lane).
        self.decode_wait: "collections.deque[_WaitingPrefill]" = collections.deque()
        # Padded KV tokens pinned by decode_wait entries (engine-thread
        # mutated; read by the scrape thread — int updates are atomic).
        self._parked_kv_tokens = 0
        self._pending: Request | None = None
        # Long prompts stream chunk-by-chunk into RESERVED cache lanes,
        # interleaved with decode blocks (_stream_step): up to
        # ``stream_lanes`` at once, advanced fair round-robin (one chunk
        # per engine cycle across lanes) so a second 32k prompt no longer
        # head-of-line blocks behind the first.
        self._streams: list[_ChunkStream] = []
        self._stream_rr = 0  # round-robin cursor over self._streams
        self._reserved_slots: set[int] = set()
        self._work = threading.Condition()
        self._running = False
        self._draining = False
        # Flight-recorder seam (llm_instance_gateway_tpu/events.py): the
        # HTTP layer installs EventJournal.emit here so engine lifecycle
        # changes (drain start) land in /debug/events without the engine
        # importing any server machinery.  Signature: (kind, **attrs).
        self.event_sink = None
        # Requests mid-admission (popped from the queue, slot not yet
        # registered): counted into num_requests_waiting so drain() and the
        # routing signal never see a phantom-quiescent engine.
        self._admitting = 0
        # Live requests by id (inserted at submit/attach, removed in
        # _finish) — the handle ``release_request`` cancels through.
        self._live: dict[str, Request] = {}
        self._thread: threading.Thread | None = None

        # Telemetry (exported by server.metrics in the gateway contract).
        self._lock = witness_lock("Engine._lock")
        self.total_generated = 0
        self.total_requests = 0
        self.decode_tps_ema = 0.0
        self.ttft_history: list[float] = []
        # Phase-latency histograms, rendered as tpu:prefill_seconds /
        # tpu:handoff_seconds / tpu:decode_step_seconds (server/metrics.py).
        # Mutated under self._lock; exported copy-out via metrics_snapshot.
        self.phase_hist: dict[str, Histogram] = {
            "prefill": Histogram(LATENCY_BUCKETS),
            "handoff": Histogram(LATENCY_BUCKETS),
            "decode_step": Histogram(LATENCY_BUCKETS),
        }
        # Fused steps per PLAIN decode dispatch — the adaptive planner's
        # decision record, rendered as tpu:dispatch_steps (spec blocks'
        # token-row counts are not planner choices and stay out).
        # Mutated under self._lock like phase_hist.
        self.dispatch_steps_hist = Histogram(STEP_BUCKETS)
        # Capacity attribution (server/usage.py): who is consuming this
        # replica.  Own lock; charged from the engine thread, snapshotted
        # by the scrape thread.
        self.usage: UsageTracker | None = (
            UsageTracker(b, kv_block=self._block if self.paged else 1)
            if self.cfg.usage_attribution else None)
        # Step-timeline profiler (server/profiler.py): charged at the
        # same dispatch call sites as the usage tracker, plus idle marks
        # from the engine loop, so the dispatch/host-sync/idle attribution
        # tiles the engine thread's wall.
        self.profiler: StepProfiler | None = (
            StepProfiler() if self.cfg.step_profile else None)
        # KV economy ledger (server/kv_ledger.py): block lifecycle,
        # per-prefix reuse, fragmentation.  Own lock; charged at the
        # allocator/prefix/park sites, state-recounted on the KV sync,
        # snapshotted by the scrape thread.  Paged pool only — the
        # contiguous-lane cache has no block economy to account.
        self.kv_ledger: KvLedger | None = (
            KvLedger(self._n_blocks, self._block)
            if self.paged and self.cfg.kv_ledger else None)
        # LRU evictions since the last KV sync (journaled as ONE
        # aggregated kv_evict event per sync — eviction storms must not
        # flood the flight recorder's bounded ring).
        self._kv_evicts_pending = 0

        if self.paged:
            step_fn = paged_lib.decode_step_paged
        elif self._decode_attn_fn is not None:
            step_fn = functools.partial(
                transformer.decode_step, attention_fn=self._decode_attn_fn)
        else:
            step_fn = transformer.decode_step
        self._jit_prefill = jax.jit(functools.partial(
            self._prefill_impl, model_cfg, self._prefill_attn_fn))
        self._jit_prefill_many = jax.jit(
            functools.partial(self._prefill_many_impl, model_cfg,
                              self._prefill_attn_fn))
        self._jit_decode = jax.jit(
            functools.partial(self._decode_impl, model_cfg, step_fn),
            donate_argnames=("cache", "counts"),
            static_argnames=("n_steps", "penalized"),
        )
        # Insert donates the cache too: without donation every admission would
        # copy the full multi-GB decode cache.
        self._jit_insert = jax.jit(
            paged_lib.insert_prefill_paged if self.paged
            else transformer.insert_prefill,
            donate_argnames=("cache",),
        )
        # Chunked prefill for prompts beyond the largest bucket: one
        # chunk-sized program streams the prompt into the cache lane.
        self._jit_chunk = jax.jit(
            functools.partial(
                paged_lib.prefill_with_cache_paged if self.paged
                else transformer.prefill_with_cache,
                model_cfg,
            ),
            donate_argnames=("cache",),
        )
        def _sample_one(logits, key, t, k, p, seed, pos, bias_ids,
                        bias_vals):
            tok = sample(
                logits[None], key, jnp.full((1,), t, jnp.float32),
                jnp.full((1,), k, jnp.int32), jnp.full((1,), p, jnp.float32),
                valid_vocab=model_cfg.vocab_size,
                seeds=jnp.full((1,), seed, jnp.int32),
                positions=jnp.full((1,), pos, jnp.int32),
                bias_ids=bias_ids[None], bias_vals=bias_vals[None],
            )
            lp, top_v, top_i = _logprob_info(
                logits[None], tok, model_cfg.vocab_size)
            return tok[0], (lp[0], top_v[0], top_i[0])

        self._jit_sample_one = jax.jit(_sample_one)

        if self._spec:
            if mesh is not None and mesh.size > 1 and (
                draft_cfg.use_flash_attention or draft_cfg.use_pallas_decode
            ):
                # Same invariant as the target: GSPMD can't partition an
                # opaque pallas_call, and the draft's ops run inside the
                # sharded spec block — its in-model auto-dispatch must be
                # off too (XLA attention; the draft is small).
                draft_cfg = dataclasses.replace(
                    draft_cfg, use_flash_attention=False,
                    use_pallas_decode=False)
                self.draft_cfg = draft_cfg
            self.draft_cache = transformer.init_decode_cache(
                draft_cfg, b, self.cfg.max_seq_len, dtype=dtype)
            if mesh is not None:
                # The draft is small: replicate it on the mesh (its whole
                # point is being cheap) — the target keeps its GSPMD
                # shardings and XLA partitions the fused verify normally.
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(mesh, PartitionSpec())
                self.draft_params = jax.device_put(draft_params, rep)
                self.draft_cache = jax.device_put(self.draft_cache, rep)
            self._spec_ok = np.zeros((b,), bool)
            # The (token, position) the draft hasn't ingested yet — only set
            # after a FULLY-accepted cycle (d_K's kv is missing then).  Host
            # mirrors for the sync loop; the pipelined loop keeps the same
            # triple device-resident in its dispatch carry.
            self._spec_extra_tok = np.zeros((b,), np.int32)
            self._spec_extra_pos = np.zeros((b,), np.int32)
            self._spec_has_extra = np.zeros((b,), bool)
            self.spec_cycles = 0
            self.spec_emitted = 0

            def _draft_prefill(params, tokens, positions):
                _, k, v = transformer.prefill(draft_cfg, params, tokens,
                                              positions)
                return k, v

            self._jit_draft_prefill = jax.jit(_draft_prefill)
            self._jit_draft_insert = jax.jit(
                transformer.insert_prefill, donate_argnames=("cache",))
            self._jit_spec_block = jax.jit(
                functools.partial(self._spec_block_impl, model_cfg, draft_cfg),
                donate_argnames=("cache", "draft_cache"),
                static_argnames=("n_cycles", "k_steps"))

    # ------------------------------------------------------------------
    # jitted compute
    # ------------------------------------------------------------------

    @staticmethod
    def _prefill_impl(
        model_cfg, attn_fn, params, lora_bufs, tokens, positions, true_len,
        lora_slot, temp, topk, topp, key, seed, bias_ids, bias_vals,
    ):
        """Prefill one padded prompt; sample the first new token."""
        slot_ids = jnp.full((1,), lora_slot, jnp.int32)
        logits, k, v = transformer.prefill(
            model_cfg, params, tokens, positions, lora_bufs=lora_bufs,
            slot_ids=slot_ids, attention_fn=attn_fn,
        )
        last = logits[:, true_len - 1]  # [1, V]
        first_token = sample(
            last, key,
            temperature=jnp.full((1,), temp, jnp.float32),
            top_k=jnp.full((1,), topk, jnp.int32),
            top_p=jnp.full((1,), topp, jnp.float32),
            valid_vocab=model_cfg.vocab_size,
            seeds=jnp.full((1,), seed, jnp.int32),
            positions=jnp.full((1,), true_len - 1, jnp.int32),
            bias_ids=bias_ids[None], bias_vals=bias_vals[None],
        )
        lp, top_v, top_i = _logprob_info(last, first_token, model_cfg.vocab_size)
        return first_token[0], k, v, (lp[0], top_v[0], top_i[0])

    @staticmethod
    def _prefill_many_impl(
        model_cfg, attn_fn, params, lora_bufs, tokens, positions, true_lens,
        lora_slots, temps, topks, topps, key, seeds, bias_ids, bias_vals,
    ):
        """Prefill P padded same-bucket prompts as one program; sample each
        row's first token (the [P, bucket] generalization of
        ``_prefill_impl`` — per-row lengths, adapters, sampling params)."""
        logits, k, v = transformer.prefill(
            model_cfg, params, tokens, positions, lora_bufs=lora_bufs,
            slot_ids=lora_slots, attention_fn=attn_fn,
        )
        last = jnp.take_along_axis(
            logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]  # [P, V]
        first_tokens = sample(
            last, key, temps, topks, topps, valid_vocab=model_cfg.vocab_size,
            seeds=seeds, positions=true_lens - 1,
            bias_ids=bias_ids, bias_vals=bias_vals)
        lp, top_v, top_i = _logprob_info(last, first_tokens, model_cfg.vocab_size)
        return first_tokens, k, v, (lp, top_v, top_i)

    @staticmethod
    def _decode_impl(
        model_cfg, step_fn, params, lora_bufs, cache, tokens, positions,
        slot_ids, temp, topk, topp, key, remaining, eos_id, seeds,
        presence, frequency, counts, bias_ids, bias_vals,
        stop_ids, stop_lens, stop_hist,
        n_steps: int, penalized: bool = False,
    ):
        """``n_steps`` fused decode+sample steps with DEVICE-SIDE stop.

        Each row carries an activity state: ``remaining`` token budget and an
        implicit frozen flag (remaining <= 0 or EOS emitted).  Frozen rows
        stop advancing their position (their cache cell is overwritten in
        place — harmless, the lane is re-inserted on reuse) and emit
        ``valid=False`` steps, so a row that stops mid-block wastes no host
        tokens and large K blocks stay cheap at sequence tails.

        Stop-STRING automata extend the same freeze mechanism: each row
        carries its last ``STOP_LEN`` emitted tokens (``stop_hist``; the
        prefill's first token included, -1 = not yet generated) and a table
        of right-aligned stop suffixes (``stop_ids``/``stop_lens``,
        server/sampling.py).  A step whose sampled token completes a suffix
        emits it as a valid token and zeroes the budget — the row freezes
        mid-block with zero host round-trips, and the host result walk
        merely confirms the match once per dispatch.

        Returns (toks [K,B], valid [K,B], logprob triplet, next_tokens,
        next_positions, next_remaining, next_hist, counts, cache).
        Positions are clamped below max_seq_len so capped slots never write
        out of bounds.
        """
        if "tables" in cache:  # paged: logical length = table span * block
            max_len = cache["tables"].shape[1] * cache["k"].shape[2]
        else:
            max_len = cache["k"].shape[2]

        c0 = tokens.shape[0]

        def one_step(carry, step_key):
            cache, tokens, positions, remaining, hist, counts = carry
            active = remaining > 0
            safe_pos = jnp.minimum(positions, max_len - 1)
            # active gates the KV WRITE too: frozen/empty rows scatter
            # nothing (trash block / OOB-dropped) — their lane may already
            # belong to a mid-stream chunk prompt on a reserved slot.
            logits, cache = step_fn(
                model_cfg, params, cache, tokens, safe_pos,
                lora_bufs=lora_bufs, slot_ids=slot_ids, active=active,
            )
            if penalized:
                # OpenAI penalties over generated tokens: subtract BEFORE
                # both the greedy argmax and the draw.  ``penalized`` is a
                # STATIC flag — penalty-free dispatches compile without the
                # [B, V] pass (and take a [B, 1] dummy counts arg).
                logits = logits - (presence[:, None] * (counts > 0)
                                   + frequency[:, None] * counts)
            sampled = sample(logits, step_key, temp, topk, topp,
                             valid_vocab=model_cfg.vocab_size,
                             seeds=seeds, positions=safe_pos,
                             bias_ids=bias_ids, bias_vals=bias_vals)
            lp, top_v, top_i = _logprob_info(
                logits, sampled, model_cfg.vocab_size)
            valid = active
            # EOS emitted now is a valid token but deactivates the row.
            hit_eos = valid & (sampled == eos_id)
            # Stop-string automaton: the emitted token enters the history
            # ring; a completed suffix deactivates the row exactly like
            # EOS (the stop's tail tokens are emitted, later steps are
            # invalid).  Frozen rows keep their history untouched.
            hist = stop_hist_update(hist, sampled, valid)
            hit_stop = valid & stop_suffix_hit(hist, stop_ids, stop_lens)
            remaining = jnp.where(valid, remaining - 1, remaining)
            remaining = jnp.where(hit_eos | hit_stop, 0, remaining)
            next_tokens = jnp.where(active, sampled, tokens)
            next_positions = positions + active.astype(positions.dtype)
            if penalized:
                counts = counts.at[jnp.arange(c0), sampled].add(
                    valid.astype(jnp.int32))
            return (cache, next_tokens, next_positions, remaining, hist,
                    counts), (sampled, valid, lp, top_v, top_i)

        keys = jax.random.split(key, n_steps)
        carry, (toks, valid, lps, top_v, top_i) = (
            jax.lax.scan(one_step,
                         (cache, tokens, positions, remaining, stop_hist,
                          counts), keys)
        )
        (cache, next_tokens, next_positions, next_remaining, next_hist,
         counts) = carry
        # The token/position/budget/history carries live on device for
        # pipelined dispatch of the following block (no host round-trip).
        return (toks, valid, lps, top_v, top_i,
                next_tokens, next_positions, next_remaining, next_hist,
                counts, cache)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._running = True
        target = self._loop_pipelined if self.cfg.pipeline_decode else self._loop
        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # The loop thread is wedged (documented multi-hour failure
                # mode: a device call through the relay never returns).  It
                # still owns _pending/decode_wait/slots — sweeping them here
                # would race a live mutator and risk double-finish.  Leave
                # the state to the wedged thread; handlers hit their own
                # timeouts.
                logger.error("engine loop thread still alive after 10s join;"
                             " skipping straggler sweep (wedged device call?)")
                return
        # Anything still queued/parked/active when the loop exits would
        # leave its done Event unset forever (handlers block until their
        # own timeout).  Fail stragglers explicitly — after a drain this
        # set is empty; after an abrupt stop it is the honest outcome.
        stragglers: list[Request] = []
        if self._pending is not None:
            stragglers.append(self._pending)
            self._pending = None
        while True:
            try:
                stragglers.append(self.prefill_queue.get_nowait())
            except queue_mod.Empty:
                break
        while self.decode_wait:
            w = self.decode_wait.popleft()
            self._parked_kv_tokens -= w.k.shape[2]
            stragglers.append(w.request)
        while self._streams:
            stragglers.append(self._streams.pop().request)
        stragglers += [s.request for s in self.slots if s is not None]
        for req in stragglers:
            if not req.done.is_set():
                req.error = req.error or "engine stopped"
                self._finish(req, "error")

    def _plan_steps(self) -> int:
        """Fused decode steps for the next dispatch (the adaptive planner).

        Static mode (``adaptive_steps`` <= 0): the decode_steps_per_sync
        CLI value, unchanged.  Adaptive mode picks from the live batch:

        - **admission pressure** — queued prompts, a parked insert, or an
          in-flight chunk stream all run BETWEEN dispatches, so a fused
          block would stall their TTFT/chunk cadence: those dispatches
          drop to 1 step;
        - **remaining budget** — never fuse past the minimum remaining
          token budget across active rows (the block would spend its tail
          decoding frozen rows); pipelined mode subtracts the in-flight
          block's steps since the host record lags it;
        - **SSE cadence** — any active streaming consumer caps fusion at
          ``adaptive_stream_cap`` so perceived TPOT cannot regress.

        The choice quantizes DOWN to a power of two: ``n_steps`` is a
        static jit argument, so this bounds the compiled-variant set to
        log2(ceiling) programs per (penalized,) combination.
        """
        ceiling = self.cfg.adaptive_steps
        if ceiling <= 0:
            return max(1, self.cfg.decode_steps_per_sync)
        # Parked decode_wait entries only stall on a FREE slot (their
        # prefill already ran); a saturated pool with parked work keeps
        # fusing — the min-remaining bound below still lands the block
        # edge on the next budget-driven slot free.
        if (self._streams or self._pending is not None
                or not self.prefill_queue.empty()
                or (self.decode_wait
                    and self._free_slot_index() is not None)):
            return 1
        n = max(1, ceiling)
        inflight = (self._prev_dispatch_steps
                    if self.cfg.pipeline_decode else 0)
        for s in self.slots:
            if s is None:
                continue
            req = s.request
            if req.streaming:
                n = min(n, max(1, self.cfg.adaptive_stream_cap))
            rem = req.max_new_tokens - len(req.output_tokens) - inflight
            n = min(n, max(1, rem))
        p = 1
        while p * 2 <= n:
            p *= 2
        return p

    def _sync_stop_hist(self) -> np.ndarray:
        """The sync loop's per-dispatch stop-history input: each
        stop-lane row's last STOP_LEN emitted tokens (right-aligned, -1
        padded), rebuilt from the request's own output record — the host
        record IS the history, so a fused block never sees a stale ring.
        Stop-free batches skip the rebuild and pass the all--1 scratch."""
        hist = self._slot_stop_hist
        if not self._stops_active:
            return hist  # all -1 by construction: nothing can match
        hist[:] = -1
        for i, s in enumerate(self.slots):
            if s is None or not self._slot_stop_lens[i].any():
                continue
            tail = s.request.output_tokens[-STOP_LEN:]
            if tail:
                hist[i, STOP_LEN - len(tail):] = tail
        return hist

    def _penalty_dispatch_args(self):
        """(counts, penalized) for a decode dispatch: the real buffer only
        when some active row carries a penalty (static flag -> two compiled
        variants); otherwise a [B, 1] dummy so penalty-free serving never
        allocates or streams the [B, V] counts."""
        penalized = bool(self._slot_presence.any()
                         or self._slot_frequency.any())
        if penalized:
            return self._counts(), True
        return jnp.zeros((self.cfg.decode_slots, 1), jnp.int32), False

    def _count_first_token(self, slot_idx: int, tok) -> None:
        """Penalty rows count their prefill-sampled first token too (vLLM
        generated-token semantics: penalties apply from the first decode
        step).  ``tok`` may be a host int or a device scalar."""
        if self._slot_presence[slot_idx] or self._slot_frequency[slot_idx]:
            self._dev_counts = self._counts().at[slot_idx, tok].add(1)

    def _counts(self):
        """[B, V_padded] generated-token counts, created on first need
        (penalty-free serving never pays the HBM)."""
        if self._dev_counts is None:
            if self.model_cfg.tie_embeddings:
                v = self.params["embed"].shape[0]
            else:
                head = self.params["lm_head"]
                if isinstance(head, dict):  # weight-only int8 leaf
                    head = head["q"]
                v = head.shape[-1]
            self._dev_counts = jnp.zeros(
                (self.cfg.decode_slots, int(v)), jnp.int32)
        return self._dev_counts

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful-termination half of the pod lifecycle: stop ADMITTING
        (submit raises; the /health flip pulls the replica out of the
        EPP's routable set) while the loop keeps decoding until every
        queued/parked/running request reaches a terminal state.  Returns
        True when fully drained, False on timeout — either way the caller
        then calls ``stop()`` (stragglers fail as the loop exits; k8s
        would be at the end of terminationGracePeriod anyway)."""
        self._draining = True
        if self.event_sink is not None:
            try:
                # "role_change" in the flight recorder's shared kind
                # namespace: the replica is leaving the routable set.
                self.event_sink("role_change", role=self.cfg.role,
                                draining=True)
            except Exception:
                logger.exception("event sink failed on drain")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            snap = self.metrics_snapshot()
            if (snap["num_requests_running"] == 0
                    and snap["num_requests_waiting"] == 0):
                return True
            time.sleep(0.02)
        return False

    @property
    def draining(self) -> bool:
        return self._draining

    def _validate_sampling(self, request: Request) -> None:
        """Sampling-parameter gates shared by submit() and
        attach_prefilled() — a handoff's sampling carry crosses a trust
        boundary and must clear the same bars as a direct submission."""
        sp = request.sampling
        if self._spec and (sp.presence_penalty or sp.frequency_penalty
                           or sp.logit_bias):
            raise ValueError(
                "presence/frequency penalties and logit_bias are not "
                "supported on a speculative engine (the verify block's "
                "greedy pick bypasses the sampling seam); disable "
                "speculative_k or the parameter")
        if sp.logit_bias:
            if len(sp.logit_bias) > MAX_LOGIT_BIAS:
                raise ValueError(
                    f"logit_bias supports at most {MAX_LOGIT_BIAS} entries")
            for tid in sp.logit_bias:
                if not 0 <= tid < self.model_cfg.vocab_size:
                    # Out-of-vocab ids would clip onto token V-1 in the
                    # device scatter and mis-bias a real token.
                    raise ValueError(
                        f"logit_bias token id {tid} is outside the "
                        f"vocabulary [0, {self.model_cfg.vocab_size})")
        for seq in request.stop_sequences:
            if not seq:
                raise ValueError("stop_sequences entries must be non-empty")
            for tid in seq:
                if not 0 <= int(tid) < self.model_cfg.vocab_size:
                    # A negative id would alias the device automaton's -1
                    # padding lane and make a short history false-match.
                    raise ValueError(
                        f"stop sequence token id {tid} is outside the "
                        f"vocabulary [0, {self.model_cfg.vocab_size})")

    def submit(self, request: Request) -> Request:
        """Enqueue; raises queue.Full when saturated (gateway sees the depth)."""
        if self._draining:
            raise EngineDraining("engine is draining (graceful termination)")
        self._validate_sampling(request)
        if len(request.prompt_tokens) >= self.cfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(request.prompt_tokens)} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}"
            )
        if self.paged and self._paged_needed(
                len(request.prompt_tokens) + 1) > self._n_blocks:
            # Larger than the ENTIRE pool: admission could never succeed and
            # the request would head-of-line block the engine forever.
            raise ValueError(
                f"prompt needs {self._paged_needed(len(request.prompt_tokens) + 1)} "
                f"KV blocks but the pool has {self._n_blocks}"
            )
        # Prompts beyond the largest bucket stream through chunked prefill;
        # within a bucket, validate the bucket fit here rather than mid-batch.
        if self._max_bucket() <= 0:
            raise ValueError(
                f"no usable prefill bucket <= max_seq_len "
                f"{self.cfg.max_seq_len}: {self.cfg.prefill_buckets}"
            )
        if len(request.prompt_tokens) <= self._max_bucket():
            self._bucket(len(request.prompt_tokens))
        request.t_submit = time.time()
        if request.adapter is not None and self.lora is not None:
            # Resolve eagerly so unknown adapters fail fast (404, not
            # mid-batch) — and PIN the slot so unload/reload can't swap the
            # buffers out from under this request mid-generation.  Released
            # in _finish (or right here if admission is refused).
            self.lora.acquire(request.adapter)
        try:
            self.prefill_queue.put_nowait(request)
        except queue_mod.Full:
            if request.adapter is not None and self.lora is not None:
                self.lora.release(request.adapter)
            raise
        with self._lock:
            self.total_requests += 1
            self._live[request.request_id] = request
        with self._work:
            self._work.notify()
        return request

    def generate(self, request: Request, timeout_s: float = 600.0) -> Request:
        """Submit and block until completion (HTTP layer calls this)."""
        self.submit(request)
        if not request.done.wait(timeout_s):
            request.error = "generation timed out"
            request.cancelled.set()  # release the slot; nobody is waiting
        return request

    # ------------------------------------------------------------------
    # cross-engine prefill/decode disaggregation (server/kv_transfer.py)
    # ------------------------------------------------------------------

    def prefill_only(self, request: Request, timeout_s: float = 600.0,
                     quantize: str | None = None):
        """Run ONLY the prefill and return a ``PrefillHandoff`` (hop 1 of
        disaggregated serving).  No decode slot, no cache lane, no pool
        block is touched — a prefill-role replica serves these regardless
        of decode occupancy.

        The wire lane defaults to int8 on an int8-KV engine (the decode
        side re-quantizes to the identical values — see kv_transfer's
        parity note) and the raw compute dtype otherwise.  Prompts beyond
        the largest bucket are refused: the chunk-stream and ring paths
        write into THIS engine's cache, which is exactly what a handoff
        exists to avoid.
        """
        n = len(request.prompt_tokens)
        if self._max_bucket() <= 0 or n > self._max_bucket():
            raise ValueError(
                f"prefill_only supports prompts within the largest bucket "
                f"({self._max_bucket()}); got {n} tokens")
        request._handoff_only = True
        request._handoff_quantize = (
            quantize if quantize is not None
            else ("int8" if self._kv_quant else None))
        self.submit(request)
        if not request.done.wait(timeout_s):
            request.error = "prefill timed out"
            request.cancelled.set()
        if request.error:
            raise RuntimeError(request.error)
        return request.handoff

    def attach_prefilled(self, handoff) -> Request:
        """Admit a ``PrefillHandoff`` straight into decode (hop 2): the KV
        imports into this engine's cache and the request decodes from its
        carried first token — prefill is skipped entirely.  Returns the
        live ``Request`` (already submitted); callers wait on ``done`` /
        ``stream_event`` exactly like after ``submit``.
        """
        from llm_instance_gateway_tpu.server import kv_transfer

        request = kv_transfer.make_request(handoff)
        if self._draining:
            raise EngineDraining("engine is draining (graceful termination)")
        if handoff.n != len(request.prompt_tokens) or handoff.n <= 0:
            raise ValueError("handoff length/prompt mismatch")
        self._validate_sampling(request)
        expect = (self.model_cfg.n_layers, handoff.n,
                  self.model_cfg.n_kv_heads,
                  self.model_cfg.resolved_head_dim)
        if (tuple(handoff.k.shape) != expect
                or tuple(handoff.v.shape) != expect):
            # Fail at admission (caller-visible), not as a 500 inside the
            # engine loop: the handoff was produced for a DIFFERENT model.
            raise ValueError(
                f"handoff KV shape {tuple(handoff.k.shape)} does not match "
                f"this engine's cache layout {expect}")
        if len(request.prompt_tokens) >= self.cfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(request.prompt_tokens)} exceeds "
                f"max_seq_len {self.cfg.max_seq_len}")
        if self.paged and self._paged_needed(
                len(request.prompt_tokens) + 1) > self._n_blocks:
            raise ValueError(
                f"prompt needs "
                f"{self._paged_needed(len(request.prompt_tokens) + 1)} KV "
                f"blocks but the pool has {self._n_blocks}")
        request.t_submit = time.time()
        if request.adapter is not None and self.lora is not None:
            # Same pin discipline as submit(): unknown adapters fail fast,
            # resident ones can't swap out mid-generation.
            self.lora.acquire(request.adapter)
        request._attach_handoff = handoff
        try:
            self.prefill_queue.put_nowait(request)
        except queue_mod.Full:
            if request.adapter is not None and self.lora is not None:
                self.lora.release(request.adapter)
            raise
        with self._lock:
            self.total_requests += 1
            self._live[request.request_id] = request
        with self._work:
            self._work.notify()
        return request

    def release_request(self, request_id: str) -> bool:
        """Best-effort cancel of a live request by id (the gateway's
        abandon path: a decode hop whose response was lost after the
        handoff was posted — ``POST /v1/prefill/release``).

        Marks the request cancelled and wakes the loop; the existing
        cancel seams finish it wherever it sits (queued, parked in
        ``decode_wait`` — freeing the parked KV accounting — or active in
        a slot, where the decode block sweep clears it).  Returns whether
        a live request with that id existed.  Idempotent; unknown ids are
        a no-op (the request may have finished, or never arrived).
        """
        with self._lock:
            req = self._live.get(request_id)
        if req is None or req.done.is_set():
            return False
        req.cancelled.set()
        if self.event_sink is not None:
            self.event_sink("kv_release", request_id=request_id)
        with self._work:
            self._work.notify()
        return True

    # ------------------------------------------------------------------
    # metrics snapshot (the scrape contract, gateway/metrics_client.py)
    # ------------------------------------------------------------------

    def _adapter_activity(self) -> tuple[list[str], list[str]]:
        """(running, waiting) LoRA adapter name lists for the
        ``tpu:lora_requests_info`` gauge — vLLM reference semantics:
        *running* = adapters with a slotted (actively decoding) request or
        the in-flight chunk stream; *waiting* = adapters whose requests are
        prefilled-but-parked in ``decode_wait`` (or pulled-but-unadmitted).
        A request parked without a slot is NOT running — counting it as
        such made the gateway's affinity scorer steer traffic toward the
        replica least able to take it.  Scrape-thread safe: a deque walk
        raced by the engine thread degrades to an empty waiting set for
        one scrape rather than corrupting anything."""
        running: set[str] = set()
        waiting: set[str] = set()
        for s in self.slots:
            if s is not None and s.request.adapter:
                running.add(s.request.adapter)
        for stream in list(self._streams):
            if stream.request.adapter:
                running.add(stream.request.adapter)
        try:
            for w in list(self.decode_wait):
                if w.request.adapter:
                    waiting.add(w.request.adapter)
        except RuntimeError:  # deque mutated during the scrape-side walk
            pass
        pending = self._pending
        if pending is not None and pending.adapter:
            waiting.add(pending.adapter)
        return sorted(running), sorted(waiting)

    def metrics_snapshot(self) -> dict:
        active = sum(1 for s in self.slots if s is not None)
        if self.paged:
            # vLLM gpu_cache_usage_perc semantics: allocated / total blocks.
            # Zero-ref cached prefix blocks are reclaimable on demand, so
            # they count as free for routing pressure.
            capacity = self._n_blocks * self._block
            used_tokens = (self._n_blocks - len(self._free_blocks)
                           - len(self._evictable)) * self._block
        else:
            used_tokens = sum(
                (s.position if s is not None else 0) for s in self.slots
            ) + sum(st.next_start for st in list(self._streams))
            capacity = self.cfg.decode_slots * self.cfg.max_seq_len
        # decode_wait KV is real allocated HBM held OUTSIDE the cache/pool;
        # vLLM's counter (the semantics the 0.8 threshold was tuned against,
        # backend/vllm/metrics.go:30) reflects ALL allocated blocks, so fold
        # the parked rows into usage/headroom.  The percent can transiently
        # exceed 1.0 when every slot is full AND prefill-ahead is parked —
        # that is honest extra pressure, and the scheduler's `<= threshold`
        # comparisons only get more conservative.
        parked = self._parked_kv_tokens
        used_tokens += parked
        with self._lock:
            tps = self.decode_tps_ema
            phase_hist = {k: h.state() for k, h in self.phase_hist.items()}
            steps_hist = self.dispatch_steps_hist.state()
        running_adapters, waiting_adapters = self._adapter_activity()
        max_lora = self.lora.max_slots if self.lora else 0
        # In-flight chunk streams count as prefilling: invisible, the
        # gateway would route MORE traffic to the replica busiest streaming.
        prefill_depth = self.prefill_queue.qsize() + (
            1 if self._pending is not None else 0) + (
            len(self._streams)) + self._admitting
        decode_depth = len(self.decode_wait)
        return {
            "pool_role": self.cfg.role,
            "prefill_queue_size": prefill_depth,
            "decode_queue_size": decode_depth,  # prefilled, awaiting a slot
            "num_requests_running": active,
            "num_requests_waiting": prefill_depth + decode_depth,
            "kv_cache_usage_perc": used_tokens / capacity if capacity else 0.0,
            "kv_tokens_capacity": capacity,
            "kv_tokens_free": max(0, capacity - used_tokens),
            "kv_parked_tokens": parked,
            "decode_tokens_per_sec": tps,
            "running_lora_adapters": running_adapters,
            "waiting_lora_adapters": waiting_adapters,
            "max_lora": max_lora,
            # Resident adapter -> LoRA rank: the heterogeneity signal the
            # gateway's rank-aware fair-share weighting (fairness plane)
            # consumes — a rank-64 flood must not starve rank-8 tenants.
            "adapter_ranks": (self.lora.adapter_ranks() if self.lora
                              else {}),
            # Residency ladder (placement plane): tier -> adapter names,
            # slot<->host<->disk transition counters, per-tier load
            # latency — rendered as tpu:adapter_residency_info /
            # tpu:adapter_tier_transitions_total / tpu:adapter_load_*
            # plus the resident_tiers label on tpu:lora_requests_info.
            **(self._residency_keys() if self.lora else {}),
            # Fused steps per dispatch (adaptive planner decision record)
            # + chunk-stream lane occupancy — the decode fast-path
            # observables (tpu:dispatch_steps / tpu:stream_lanes*).
            "dispatch_steps_hist": steps_hist,
            "stream_lanes": max(1, self.cfg.stream_lanes),
            "stream_lanes_active": len(self._streams),
            # Phase-latency histogram states (server/metrics.py renders
            # these as the tpu:*_seconds histogram families).
            "phase_hist": phase_hist,
            # Per-adapter capacity attribution (server/usage.py) — the
            # tpu:adapter_*_total / pool-waste families.
            **({"usage": self.usage.snapshot()}
               if self.usage is not None else {}),
            # Step-timeline profiler histogram states (server/profiler.py)
            # — the tpu:dispatch_wall_seconds / tpu:dispatch_gap_seconds
            # families; the full per-dispatch ring rides /debug/profile.
            **({"profile": self.profiler.hist_state()}
               if self.profiler is not None else {}),
            # KV economy ledger (server/kv_ledger.py) — the tpu:kv_*
            # block-lifecycle families; the full payload (event ring,
            # prefix heatmap) rides /debug/kv.  Re-synced here so a
            # scrape between dispatches still sees current block states.
            **(self._kv_ledger_snapshot_key()),
            **({"prefix_reused_tokens": self.prefix_reused_tokens}
               if self._prefix_enabled else {}),
            **({
                "spec_cycles": self.spec_cycles,
                # Accepted tokens per verify cycle vs the K+1 ceiling: THE
                # health signal for draft quality.
                "spec_tokens_per_cycle": round(
                    self.spec_emitted / self.spec_cycles, 3)
                if self.spec_cycles else 0.0,
            } if self._spec else {}),
        }

    def _kv_ledger_snapshot_key(self) -> dict:
        """``{"kv_ledger": snapshot}`` for ``metrics_snapshot`` (empty
        when the ledger is off).  The recount reads the allocator's
        host-side structures lock-free like the paged math above — the
        same single-writer tolerance, and the ledger's own lock makes
        the stored counts internally consistent."""
        if self.kv_ledger is None:
            return {}
        self._kv_ledger_sync()
        return {"kv_ledger": self.kv_ledger.snapshot()}

    def _residency_keys(self) -> dict:
        transitions, load_seconds = self.lora.residency_counters()
        return {
            "residency": self.lora.residency_snapshot(),
            "tier_transitions": transitions,
            "adapter_load_seconds": load_seconds,
        }

    # ------------------------------------------------------------------
    # engine loop
    # ------------------------------------------------------------------

    def _free_slot_index(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s is None and i not in self._reserved_slots:
                return i
        return None

    def _clear_slot(self, i: int) -> None:
        """Release a decode slot row (and, when paged, its pool blocks)."""
        self.slots[i] = None
        if self._spec:
            self._spec_ok[i] = False
            self._spec_has_extra[i] = False
        self._slot_lora[i] = -1
        self._slot_remaining[i] = 0
        if self._slot_stop_lens[i].any():
            self._slot_stop_ids[i] = -1
            self._slot_stop_lens[i] = 0
            self._slot_stop_hist[i] = -1
            self._stops_active = int(
                (self._slot_stop_lens.sum(axis=1) > 0).sum())
        self._slot_seed[i] = -1
        self._slot_presence[i] = 0.0
        self._slot_frequency[i] = 0.0
        self._slot_bias_ids[i] = -1
        self._slot_bias_vals[i] = 0.0
        if self.paged:
            self._paged_free_row(i)
        # NO usage KV sync here: _clear_slot runs inside the decode result
        # loops (k finished slots would rebuild the holdings list k times
        # per dispatch); every dispatch syncs once at its end, and the
        # rarer non-dispatch clears are off by at most one dispatch
        # interval.

    # -- paged-pool allocator (host side; device sees only table contents) --

    def _paged_needed(self, upto_len: int) -> int:
        return min(-(-upto_len // self._block), self._max_blocks_per_seq)

    def _paged_can_admit(self, n_prompt: int,
                         prompt: list[int] | None = None,
                         adapter: str | None = None,
                         hashes: list[bytes] | None = None) -> bool:
        """Capacity gate.  With ``prompt`` given, cached-prefix blocks that
        would map at zero cost are subtracted from the need — otherwise a
        shared system prompt held by a live request would spuriously
        backpressure the very workload prefix caching targets."""
        if not self.paged:
            return True
        avail = len(self._free_blocks) + (
            len(self._evictable) if self._prefix_enabled else 0)
        needed = self._paged_needed(n_prompt + 1)
        if needed <= avail:
            return True  # plain path fits (evicting zero-ref blocks if need be)
        if prompt is None:
            return False
        # Reuse feasibility: matched blocks come free — but a matched block
        # currently sitting zero-ref in the evictable LRU gets PINNED by the
        # map (it stops being reclaimable), so it can't count toward avail
        # too.  Without this, the admit passed on the double-count, then
        # _paged_ensure found the pool dry and errored the request instead
        # of backpressuring it.  Live-held matched blocks (refs > 0, not in
        # either pool) are the zero-cost case this clause exists for.
        matched = self._prefix_match_blocks(prompt, adapter, hashes)
        reuse_needed = needed - min(len(matched), needed)
        reuse_avail = avail - sum(1 for b in matched if b in self._evictable)
        return reuse_needed <= reuse_avail

    def _paged_alloc_block(self) -> int:
        """One free physical block, evicting the LRU zero-ref cached block
        if the free list is dry.  Raises ``PagedPoolExhausted``."""
        if self._free_blocks:
            blk = self._free_blocks.pop()
            if self.kv_ledger is not None:
                self.kv_ledger.note_alloc()
            return blk
        if self._prefix_enabled and self._evictable:
            blk, h = self._evictable.popitem(last=False)  # LRU
            self._prefix_table.pop(h, None)
            self._block_hash.pop(blk, None)
            self._block_refs.pop(blk, None)
            if self.kv_ledger is not None:
                self.kv_ledger.note_evict(h.hex()[:16])
                self.kv_ledger.note_alloc()
                self._kv_evicts_pending += 1
            return blk
        raise PagedPoolExhausted(
            f"kv pool exhausted: {self._n_blocks} blocks of "
            f"{self._block} tokens all allocated"
        )

    def _paged_ensure(self, row: int, upto_len: int) -> None:
        """Grow ``row``'s table to cover positions < upto_len.

        Raises ``PagedPoolExhausted`` (leaving the row's existing blocks
        intact — the caller decides between backpressure and failing the
        request)."""
        blocks = self._row_blocks[row]
        needed = self._paged_needed(upto_len)
        while len(blocks) < needed:
            blk = self._paged_alloc_block()
            blocks.append(blk)
            self._tables_host[row, len(blocks) - 1] = blk
            self._tables_dirty = True

    def _paged_free_row(self, row: int) -> None:
        blocks = self._row_blocks[row]
        if blocks:
            freed = cached = 0
            for blk in blocks:
                h = self._block_hash.get(blk)
                if h is None:
                    self._free_blocks.append(blk)
                    freed += 1
                else:
                    # Cached prefix block: drop this row's reference; at
                    # zero it parks in the evictable LRU (content kept).
                    self._block_refs[blk] -= 1
                    if self._block_refs[blk] == 0:
                        self._evictable[blk] = h  # fresh key -> MRU end
                        cached += 1
            if self.kv_ledger is not None:
                self.kv_ledger.note_release(freed, cached)
            self._row_blocks[row] = []
            self._tables_host[row, :] = paged_lib.TRASH_BLOCK
            self._tables_dirty = True

    # -- prefix cache (content-addressed full prompt blocks, vLLM-style) --

    def _prefix_hashes(self, prompt: list[int], max_blocks: int,
                       adapter: str | None) -> list[bytes]:
        """Chained SHA-256 digests of the first ``max_blocks`` full prompt
        blocks.  The chain is seeded with the LoRA adapter identity — KV
        depends on the adapter's wk/wv deltas, so the same tokens under a
        different adapter are DIFFERENT content.  Cryptographic hashing,
        not ``hash()``: Python's tuple hash is adversarially collidable,
        and a collision here maps another prompt's KV into this request
        (the vLLM CVE-2025-25183 failure mode)."""
        bs = self._block
        h = hashlib.sha256(repr(adapter).encode()).digest()
        out = []
        for i in range(max_blocks):
            h = hashlib.sha256(
                h + np.asarray(prompt[i * bs:(i + 1) * bs],
                               np.int64).tobytes()
            ).digest()
            out.append(h)
        return out

    def _prefix_hashes_for(self, req: Request) -> list[bytes]:
        """Memoized hash chain for a request's prompt: the digests depend
        only on (prompt, adapter), but backpressured admission re-checks
        the SAME pending request every engine cycle (~20Hz) — without the
        memo a long prompt recomputes hundreds of SHA-256 calls per spin
        on the thread that also drives decode dispatch."""
        memo = getattr(req, "_prefix_hash_memo", None)
        if memo is None:
            memo = self._prefix_hashes(
                req.prompt_tokens,
                (len(req.prompt_tokens) - 1) // self._block, req.adapter)
            req._prefix_hash_memo = memo
        return memo

    def _prefix_match_blocks(self, prompt: list[int], adapter: str | None,
                             hashes: list[bytes] | None = None) -> list[int]:
        """Dry-run of the hash walk: the physical blocks that would map
        (no incref)."""
        if not self._prefix_enabled:
            return []
        if hashes is None:
            hashes = self._prefix_hashes(
                prompt, (len(prompt) - 1) // self._block, adapter)
        out = []
        for h in hashes:
            blk = self._prefix_table.get(h)
            if blk is None:
                break
            out.append(blk)
        return out

    def _prefix_match_and_map(self, row: int, prompt: list[int],
                              adapter: str | None,
                              hashes: list[bytes] | None = None) -> int:
        """Map the longest cached prefix into ``row``'s table (increfs).
        Returns the number of reused TOKENS (multiple of the block size).
        At least the prompt's last token always recomputes, so the request
        still produces fresh logits."""
        if not self._prefix_enabled:
            return 0
        max_blocks = (len(prompt) - 1) // self._block
        blocks = self._row_blocks[row]
        assert not blocks, "prefix map must precede suffix allocation"
        if hashes is None:
            hashes = self._prefix_hashes(prompt, max_blocks, adapter)
        for h in hashes:
            blk = self._prefix_table.get(h)
            if blk is None:
                break
            self._block_refs[blk] += 1
            self._evictable.pop(blk, None)  # in use again
            blocks.append(blk)
            self._tables_host[row, len(blocks) - 1] = blk
            self._tables_dirty = True
        reused = len(blocks) * self._block
        self.prefix_reused_tokens += reused
        if reused and self.kv_ledger is not None:
            # Prefix identity = hex of the DEEPEST matched chain hash:
            # content-addressed and adapter-seeded, so the same shared
            # prompt yields the same id on every replica — the join key
            # for the gateway's fleet duplication index.
            self.kv_ledger.note_reuse_hit(
                hashes[len(blocks) - 1].hex()[:16], len(blocks), reused)
        return reused

    def _prefix_register_row(self, row: int, prompt: list[int],
                             adapter: str | None,
                             hashes: list[bytes] | None = None) -> None:
        """After a prompt is fully in the row's blocks, publish its full
        blocks to the prefix table so later prompts can share them."""
        if not self._prefix_enabled:
            return
        max_blocks = (len(prompt) - 1) // self._block
        blocks = self._row_blocks[row]
        if hashes is None:
            hashes = self._prefix_hashes(prompt, max_blocks, adapter)
        for i, h in enumerate(hashes):
            blk = blocks[i]
            if self._block_hash.get(blk) is not None:
                continue  # already a cached block (mapped via reuse)
            if h in self._prefix_table:
                continue  # another live block already serves this content
            self._block_hash[blk] = h
            self._prefix_table[h] = blk
            self._block_refs[blk] = 1
        if hashes and self.kv_ledger is not None:
            self.kv_ledger.note_register(hashes[-1].hex()[:16], len(hashes))

    def _kv_note_reuse_unwind(self, req: Request, reused: int) -> None:
        """Ledger mirror of a reuse unwind — called exactly where
        ``prefix_reused_tokens`` is decremented, so the ledger's
        tokens-saved attribution tracks the engine counter."""
        if self.kv_ledger is None or not reused:
            return
        blocks = reused // self._block
        hashes = self._prefix_hashes_for(req)
        self.kv_ledger.note_reuse_unwind(
            hashes[blocks - 1].hex()[:16], blocks, reused)

    def _sync_tables(self) -> None:
        """Push host-side table changes to the device copy in the cache.

        COPY, never alias: the cache is DONATED to every decode/insert/
        chunk program, and on the CPU backend ``jnp.asarray`` of a numpy
        array can share its buffer — donation would then let XLA write a
        program OUTPUT over ``_tables_host`` behind the allocator's back
        (observed: the [B, STOP_LEN] stop-history output landing in the
        same-shaped donated tables buffer, trashing every row's mapping).
        """
        if self.paged and self._tables_dirty:
            self.cache = dict(self.cache,
                              tables=jnp.array(self._tables_host, copy=True))
            self._tables_dirty = False

    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b and b <= self.cfg.max_seq_len:
                return b
        raise ValueError(f"prompt length {n} exceeds largest prefill bucket")

    def _max_bucket(self) -> int:
        return max(
            (b for b in self.cfg.prefill_buckets if b <= self.cfg.max_seq_len),
            default=0,
        )

    def _next_key(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _lora_buffers(self):
        return self.lora.buffers if self.lora is not None else None

    def _loop(self) -> None:
        while self._running:
            # 1) Drain admissions: fill EVERY free slot before decoding (a
            # slot left empty idles for a whole K-step block), then prefill
            # AHEAD into decode_wait while slots are busy.
            did_work = self._admit_and_insert(pipelined=False)
            # 1b) One chunk of ONE in-flight long-prompt stream (fair
            # round-robin across lanes): decode blocks run between chunks,
            # so streaming a 32k prompt no longer freezes every active
            # slot's TPOT — and N lanes advance interleaved instead of a
            # second long prompt head-of-line blocking behind the first.
            if self._streams:
                self._stream_step(pipelined=False)
                did_work = True
            # 2) One fused decode block for all active slots.
            if any(s is not None for s in self.slots):
                try:
                    # Stop-automaton rows exclude speculative dispatch:
                    # the spec block does not evaluate the suffix automata,
                    # so its history carry would go stale — plain fused
                    # blocks serve the batch until those rows finish.
                    if self._spec and not self._stops_active and any(
                        s is not None and self._spec_ok[i]
                        and self._slot_temp[i] <= 0.0
                        for i, s in enumerate(self.slots)
                    ):
                        self._do_spec_step()
                    else:
                        # No row can accept proposals (all sampled or
                        # stream-admitted): speculation would only add the
                        # draft+verify overhead per token.
                        self._do_decode_step()
                except Exception as e:  # engine must survive; fail the batch
                    logger.exception("decode step failed")
                    self._fail_all_slots(e)
                did_work = True
            if not did_work:
                if self.profiler is not None:
                    self.profiler.note_idle()
                with self._work:
                    self._work.wait(timeout=0.05)

    def _admit_and_insert(self, pipelined: bool) -> bool:
        """Admission for both loops: drain decode_wait into freed slots,
        direct-prefill into free slots, prefill AHEAD when slots are full.

        FIFO holds: decode_wait drains before the raw queue, and a direct
        prefill only happens when nothing is parked — so it can never jump
        an older waiting request, whether the head waits on a slot or on
        paged-pool blocks.  Chunked prompts (beyond the largest bucket)
        stream straight into a cache lane, so with no lane free they
        head-of-line block as ``_pending``.
        """
        did = self._drain_decode_wait(pipelined)
        cap = (self.cfg.decode_wait_cap if self.cfg.decode_wait_cap is not None
               else self.cfg.decode_slots)
        while True:
            if self._pending is None:
                try:
                    self._pending = self.prefill_queue.get_nowait()
                except queue_mod.Empty:
                    break
            req = self._pending
            if req.cancelled.is_set():
                self._pending = None
                self._finish(req, "cancelled")
                did = True
                continue
            if getattr(req, "_handoff_only", False):
                # Disaggregation hop 1: prefill with NO slot and NO cache
                # write — the KV leaves as a handoff, so this admits even
                # when every slot is busy and the pool is dry.
                self._pending = None
                self._admitting += 1
                try:
                    self._do_prefill_handoff(req)
                finally:
                    self._admitting -= 1
                did = True
                continue
            if getattr(req, "_attach_handoff", None) is not None:
                # Disaggregation hop 2: the KV is already computed; park it
                # in decode_wait (the same seam prefill-ahead uses) and let
                # the normal drain admit it into a slot.  The cap bounds
                # imported-but-unslotted KV exactly like prefill-ahead KV.
                if len(self.decode_wait) >= cap:
                    break
                self._pending = None
                self._admitting += 1
                try:
                    self._do_attach(req, pipelined)
                finally:
                    self._admitting -= 1
                self._drain_decode_wait(pipelined)
                did = True
                continue
            if self._free_slot_index() is not None:
                if self.decode_wait:
                    # The parked head couldn't take this slot (pool
                    # backpressure): strict FIFO — don't let a newer request
                    # steal the blocks it is waiting for.
                    break
                n_req = len(req.prompt_tokens)
                # Ring-path prompts (sequence-parallel prefill) never map
                # cached prefix blocks — the ring computes the whole prompt
                # and inserts into FRESH blocks — so their admission gate
                # must not assume hash-based reuse, or _paged_ensure would
                # exhaust the pool the gate said was sufficient.
                takes_ring = (n_req > self._max_bucket()
                              and self._ring_usable(n_req))
                if not self._paged_can_admit(
                        n_req, req.prompt_tokens,
                        req.adapter,
                        hashes=(self._prefix_hashes_for(req)
                                if self._prefix_enabled and not takes_ring
                                else None)):
                    break  # pool backpressure: wait for block frees
                if (len(req.prompt_tokens) > self._max_bucket()
                        and not self._ring_usable(len(req.prompt_tokens))):
                    if not self._lane_available(n_req):
                        break  # no lane (count or KV pressure); FIFO head waits
                    self._pending = None
                    self._admitting += 1
                    try:
                        if not self._start_stream(req):
                            break  # reparked for backpressure; stop cycle
                    finally:
                        self._admitting -= 1
                    did = True
                    continue
                self._pending = None
                self._admitting += 1
                try:
                    if (self.cfg.prefill_batch > 1
                            and len(req.prompt_tokens) <= self._max_bucket()
                            and not (self.paged and self._prefix_enabled)):
                        # Prefix-cache engines stay per-request: the grouped
                        # program computes full-prompt KV, so a cached-prefix
                        # row would pay the compute reuse exists to skip.
                        self._do_prefill_group(
                            self._collect_prefill_group(req), pipelined)
                    elif pipelined:
                        self._do_prefill_pipelined(req)
                    else:
                        self._do_prefill(req)
                finally:
                    self._admitting -= 1
                did = True
                continue
            if (len(req.prompt_tokens) <= self._max_bucket()
                    and len(self.decode_wait) < cap):
                self._pending = None
                self._admitting += 1
                try:
                    if self.cfg.prefill_batch > 1:
                        # Paged included: prefill-ahead KV parks OFF-cache,
                        # so no pool blocks are touched until the drain,
                        # which gates per row on _paged_can_admit.
                        self._do_prefill_ahead_group(
                            self._collect_ahead_group(req, cap), pipelined)
                    else:
                        self._do_prefill_ahead(req, pipelined)
                finally:
                    self._admitting -= 1
                did = True
                continue
            break
        return did

    def _sweep_decode_wait(self) -> bool:
        """Drop cancelled entries ANYWHERE in decode_wait (not just the
        head: a released attach must free its parked KV even while older
        work blocks the front), plus handoff imports parked past the TTL —
        abandoned work whose gateway already rerouted."""
        now = time.time()
        ttl = self.cfg.handoff_ttl_s
        keep: list[_WaitingPrefill] = []
        swept = False
        for w in self.decode_wait:
            expired = (ttl > 0 and w.from_handoff and w.t_parked
                       and now - w.t_parked > ttl)
            if w.request.cancelled.is_set() or expired:
                self._parked_kv_tokens -= w.k.shape[2]
                if self.kv_ledger is not None:
                    self.kv_ledger.note_sweep(
                        int(w.k.shape[2]),
                        "ttl" if expired
                        and not w.request.cancelled.is_set()
                        else "cancelled")
                if expired and not w.request.cancelled.is_set():
                    logger.warning(
                        "handoff %s parked %.1fs > ttl %.1fs; releasing",
                        w.request.request_id, now - w.t_parked, ttl)
                    if self.event_sink is not None:
                        self.event_sink("kv_release",
                                        request_id=w.request.request_id,
                                        reason="ttl")
                self._finish(w.request, "cancelled")
                swept = True
            else:
                keep.append(w)
        if swept:
            self.decode_wait = collections.deque(keep)
            self._usage_sync_kv()  # parked holdings changed
        return swept

    def _drain_decode_wait(self, pipelined: bool) -> bool:
        did = self._sweep_decode_wait()
        while self.decode_wait:
            w = self.decode_wait[0]
            if w.request.cancelled.is_set():
                self.decode_wait.popleft()
                self._parked_kv_tokens -= w.k.shape[2]
                if self.kv_ledger is not None:
                    self.kv_ledger.note_sweep(int(w.k.shape[2]), "cancelled")
                self._usage_sync_kv()
                self._finish(w.request, "cancelled")
                did = True
                continue
            slot_idx = self._free_slot_index()
            if slot_idx is None:
                break
            if not self._paged_can_admit(w.n):
                break  # pool backpressure: KV stays parked off-cache
            self.decode_wait.popleft()
            self._parked_kv_tokens -= w.k.shape[2]
            if self.kv_ledger is not None:
                self.kv_ledger.note_unpark(int(w.k.shape[2]))
            # Mid-admission guard: between the pop (decode_queue -> 0) and
            # _register_slot (running -> 1) the insert runs a device op —
            # without this count a drain()/scrape polling that window sees
            # a phantom-quiescent engine and declares victory with the
            # request still in flight.
            self._admitting += 1
            try:
                self._insert_waiting(slot_idx, w, pipelined)
            finally:
                self._admitting -= 1
            did = True
        return did

    def _do_prefill_ahead(self, req: Request, pipelined: bool) -> None:
        """Prefill with NO slot: prompt KV parks in decode_wait.

        In sync mode the first token is emitted immediately — TTFT is
        prefill-bound, not slot-bound, which is the point of the
        disaggregated design.  Pipelined mode keeps the token on device
        (async-copied) and stamps TTFT at materialization like its other
        admissions.
        """
        try:
            self._stamp_prefill_start(req)
            n = len(req.prompt_tokens)
            lora_slot = (self.lora.slot_for(req.adapter)
                         if self.lora is not None else -1)
            first_token, k, v, lp_info = self._bucket_prefill(
                req, n, lora_slot)
            if pipelined:
                try:
                    first_token.copy_to_host_async()
                except AttributeError:
                    pass
            self._park_waiting(req, first_token, lp_info, k, v, n, lora_slot,
                               pipelined)
        except Exception as e:  # engine must survive a poison request
            logger.exception("prefill-ahead failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")

    def _do_prefill_handoff(self, req: Request) -> None:
        """Prefill with NO slot and NO cache write: the prompt KV leaves
        the engine as a serializable ``PrefillHandoff`` (hop 1 of
        cross-engine disaggregation).  TTFT here measures pure prefill
        latency — the token itself is emitted by the decode engine."""
        from llm_instance_gateway_tpu.server import kv_transfer

        if req.cancelled.is_set():
            self._finish(req, "cancelled")
            return
        try:
            self._stamp_prefill_start(req)
            n = len(req.prompt_tokens)
            lora_slot = (self.lora.slot_for(req.adapter)
                         if self.lora is not None else -1)
            first_token, k, v, lp_info = self._bucket_prefill(
                req, n, lora_slot)
            req.handoff = kv_transfer.export_handoff(
                req, k, v, n, int(first_token),
                lp_info=tuple(np.asarray(a) for a in lp_info),
                quantize=getattr(req, "_handoff_quantize", None))
            req.t_first_token = time.time()
            self._record_ttft(req)
            self._finish(req, "handoff")
        except Exception as e:  # engine must survive a poison request
            logger.exception("handoff prefill failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")

    def _handoff_device_kv(self, handoff):
        """Handoff KV -> device arrays shaped like a bucketed prefill's
        output (``[L, 1, pad_to, Kh, hd]``), so the existing insert seams
        (lane dynamic-slice / paged block scatter, quantizing variants
        included) consume it unchanged.  Padding to the engine's own bucket
        set keeps the insert's compiled-shape set bounded."""
        k_np, v_np = handoff.kv_arrays()
        n = handoff.n
        if n <= self._max_bucket():
            pad_to = self._bucket(n)
        else:
            step = max(self._max_bucket(), 1)
            pad_to = min(-(-n // step) * step, self.cfg.max_seq_len)
        lyr, _, kh, hd = k_np.shape
        kp = np.zeros((lyr, 1, pad_to, kh, hd), k_np.dtype)
        vp = np.zeros((lyr, 1, pad_to, kh, hd), v_np.dtype)
        kp[:, 0, :n] = k_np
        vp[:, 0, :n] = v_np
        return jnp.asarray(kp), jnp.asarray(vp)

    def _do_attach(self, req: Request, pipelined: bool) -> None:
        """Import a handoff's KV and park it in ``decode_wait`` — from
        there the normal drain inserts it into a freed slot (allocating
        pool blocks, registering the prefix-cache chain) and decode starts
        at the carried position.  The first token is emitted HERE, like a
        sync prefill-ahead park: TTFT on this engine is attach latency,
        and a one-token request finishes without ever taking a slot."""
        handoff = req._attach_handoff
        if req.cancelled.is_set():
            self._finish(req, "cancelled")
            return
        try:
            lora_slot = (self.lora.slot_for(req.adapter)
                         if self.lora is not None else -1)
            k, v = self._handoff_device_kv(handoff)
            if self._emit_first_token(req, handoff.first_token,
                                      handoff.first_lp_info()):
                return  # finished at attach; never needed a slot
            w = _WaitingPrefill(
                request=req,
                first_token=jnp.asarray(handoff.first_token, jnp.int32),
                k=k, v=v, n=handoff.n, lora_slot=lora_slot,
                first_token_host=handoff.first_token,
                lp_info=None, first_emitted=True, from_handoff=True,
                t_parked=time.time())
            self.decode_wait.append(w)
            self._parked_kv_tokens += w.k.shape[2]
            if self.kv_ledger is not None:
                self.kv_ledger.note_park(int(w.k.shape[2]), "handoff")
            self._usage_sync_kv()
        except Exception as e:  # engine must survive a poison handoff
            logger.exception("attach failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")

    def _activate_slot_pipelined(self, slot_idx: int, req: Request,
                                 lora_slot: int, n: int, first_token,
                                 lp_info) -> None:
        """Pipelined-mode slot activation: scatter the device-resident first
        token/position/budget into the carry arrays and register the slot
        with its pending first token (materialized at block processing).
        Shared by decode_wait inserts and chunk-stream activation — the
        device-state bookkeeping must stay identical."""
        self._pending_budget_zero = [
            i for i in self._pending_budget_zero if i != slot_idx
        ]
        # Grouped-prefill rows carry their device slice so this scatter
        # never forces a host sync mid-pipeline.
        tok_dev = (first_token.dev if isinstance(first_token, _Row)
                   and first_token.dev is not None else first_token)
        self._dev_tokens = self._dev_tokens.at[slot_idx].set(tok_dev)
        self._dev_positions = self._dev_positions.at[slot_idx].set(n)
        self._dev_remaining = self._dev_remaining.at[slot_idx].set(
            max(0, req.max_new_tokens - 1))
        slot = _Slot(request=req, lora_slot=lora_slot, position=n)
        slot.pending_first = (first_token, lp_info)
        self._register_slot(slot_idx, slot)
        if self._slot_stop_lens[slot_idx].any():
            # Re-seed the row's device stop history: emitted tokens from
            # the host record (attach / sync-parked admissions), else the
            # device-resident first token — no host sync either way.
            row = np.full((STOP_LEN,), -1, np.int32)
            tail = req.output_tokens[-STOP_LEN:]
            if tail:
                row[STOP_LEN - len(tail):] = tail
                self._dev_stop_hist = self._dev_stop_hist.at[slot_idx].set(
                    jnp.asarray(row))
            else:
                self._dev_stop_hist = self._dev_stop_hist.at[slot_idx].set(
                    jnp.asarray(row)).at[slot_idx, STOP_LEN - 1].set(tok_dev)
        self._count_first_token(slot_idx, tok_dev)
        if self._spec:
            # _register_slot set the row's sampling params _draft_admit
            # gates on; the device extra flag resets for the new occupant.
            self._dev_has_extra = self._dev_has_extra.at[slot_idx].set(False)
            self._draft_admit(slot_idx, req.prompt_tokens)

    def _insert_waiting(self, slot_idx: int, w: _WaitingPrefill,
                        pipelined: bool) -> None:
        """Insert a parked prefill's KV into a freed cache lane."""
        req = w.request
        try:
            skip_blocks = 0
            if w.from_handoff and self._prefix_enabled:
                # Handoff composing with prefix reuse: whole blocks this
                # engine already caches for the prompt's prefix MAP into
                # the row (refcounted table repoint, zero writes) and the
                # insert scatter routes their positions to the trash block
                # — a repeated attach re-writes only the suffix, and a
                # shared live block is never re-scattered (identical
                # content in theory, but another row may be mid-read).
                reused = self._prefix_match_and_map(
                    slot_idx, req.prompt_tokens, req.adapter)
                skip_blocks = reused // self._block
            self._insert_prompt_kv(w.k, w.v, slot_idx, w.n,
                                   skip_leading_blocks=skip_blocks)
            self._prefix_register_row(slot_idx, req.prompt_tokens,
                                      req.adapter)
            if pipelined:
                self._activate_slot_pipelined(
                    slot_idx, req, w.lora_slot, w.n, w.first_token, w.lp_info)
                if w.first_emitted:
                    # Attach path: the first token already reached the
                    # request at admission — materializing pending_first
                    # would emit it twice.  The device carry scatter above
                    # still used it (correct: decode continues from it).
                    self.slots[slot_idx].pending_first = None
            else:
                self._register_slot(slot_idx, _Slot(
                    request=req, lora_slot=w.lora_slot, position=w.n))
                self._slot_tokens[slot_idx] = w.first_token_host
                self._slot_positions[slot_idx] = w.n
                self._count_first_token(slot_idx, w.first_token_host)
                self._draft_admit(slot_idx, req.prompt_tokens)
        except Exception as e:
            logger.exception("decode-wait insert failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")
            if self.paged:
                # A failure after _insert_prompt_kv would otherwise strand
                # the row's blocks (and pin any freshly registered ones at
                # refs=1 forever): no slot was registered, so no
                # _clear_slot will ever free them.
                self._paged_free_row(slot_idx)

    # ------------------------------------------------------------------
    # speculative decoding (draft proposes, target verifies in one pass)
    # ------------------------------------------------------------------

    @staticmethod
    def _spec_block_impl(model_cfg, draft_cfg, params, draft_params,
                         lora_bufs, cache, draft_cache, tokens, positions,
                         remaining, extra_tok, extra_pos, has_extra, spec_ok,
                         temp, topk, topp, key, slot_ids, eos_id, seeds,
                         n_cycles: int, k_steps: int):
        """``n_cycles`` fused speculative cycles, entirely device-side.

        Each cycle: the draft ingests the <=2 context tokens it hasn't seen,
        proposes ``k_steps`` greedy tokens autoregressively, and the target
        scores [cur, d_1..d_K] in ONE multi-token forward (extend_step).
        Greedy rows accept the longest matching prefix plus the target's
        bonus token — EXACT greedy parity; sampled / non-speculating rows
        emit one token from the first position's logits.  Acceptance,
        budget, and EOS truncation are all mask arithmetic, so the whole
        block is one jitted program: the same dispatch/readback shape as
        ``_decode_impl``, which is what lets speculation compose with the
        pipelined loop and ``decode_steps_per_sync > 1``.

        Stale-KV safety: cycle writes at positions p..p+K may leave garbage
        beyond the accepted prefix, but the NEXT cycle's K+1 writes start at
        the corrected position and always cover the stale range — the same
        invariant the single-cycle version relied on.  It holds for the
        paged target too: the physical address of a logical position is
        stable within a block's lifetime, and the engine pre-allocates the
        whole block span a dispatch can write (``_paged_ensure_decode``).

        Returns flattened [T=n_cycles*(K+1), B] token/valid/logprob arrays —
        the exact layout ``_decode_impl`` produces — plus the device carries
        (next token/position/budget, draft-extra triple) and both caches.
        """
        b = tokens.shape[0]
        paged = "tables" in cache
        if paged:
            s_max = cache["tables"].shape[1] * cache["k"].shape[2]
            target_extend = functools.partial(paged_lib.extend_step_paged,
                                              model_cfg, params)
        else:
            s_max = cache["k"].shape[2]
            target_extend = functools.partial(transformer.extend_step,
                                              model_cfg, params)
        kp1 = k_steps + 1

        def greedy_pick(lg, vocab):
            # Mask the zero-logit vocab-PADDING columns (lm_head pads to a
            # multiple of 128) or argmax can emit ids the tokenizer lacks.
            masked = jnp.where(jnp.arange(lg.shape[-1]) < vocab, lg, -jnp.inf)
            return jnp.argmax(masked, axis=-1).astype(jnp.int32)

        def one_cycle(carry, cycle_key):
            (cache, draft_cache, tokens, positions, remaining,
             extra_tok, extra_pos, has_extra) = carry
            active = remaining > 0
            safe_pos = jnp.minimum(positions, s_max - 1)
            # --- draft catch-up + propose ---
            ctx_tokens = jnp.stack(
                [jnp.where(has_extra, extra_tok, tokens), tokens], axis=1)
            ctx_positions = jnp.stack(
                [jnp.where(has_extra, jnp.minimum(extra_pos, s_max - 1),
                           safe_pos),
                 jnp.where(has_extra, safe_pos,
                           jnp.minimum(positions + 1, s_max - 1))], axis=1)
            idx = jnp.where(has_extra, 1, 0)  # last REAL ctx index per row
            logits2, draft_cache = transformer.extend_step(
                draft_cfg, draft_params, draft_cache, ctx_tokens,
                ctx_positions)
            last = logits2[jnp.arange(b), idx]  # [B, V]
            cur_pos = ctx_positions[jnp.arange(b), idx]
            d1 = greedy_pick(last, draft_cfg.vocab_size)

            def body(c, _):
                tok, pos, dcache = c
                lg, dcache = transformer.decode_step(
                    draft_cfg, draft_params, dcache, tok, pos)
                nxt = greedy_pick(lg, draft_cfg.vocab_size)
                return (nxt, jnp.minimum(pos + 1, s_max - 1), dcache), nxt

            if k_steps > 1:
                (_, _, draft_cache), rest = jax.lax.scan(
                    body,
                    (d1, jnp.minimum(cur_pos + 1, s_max - 1), draft_cache),
                    None, length=k_steps - 1)
                draft = jnp.concatenate([d1[None], rest], axis=0).T  # [B, K]
            else:
                draft = d1[:, None]

            # --- target verify: [cur, d_1..d_K] in one forward ---
            vtokens = jnp.concatenate([tokens[:, None], draft], axis=1)
            # Clamp like decode: overflow rows finish on the host's max_seq
            # check; the clamped scatter writes garbage the mask hides.
            vpos = jnp.minimum(
                positions[:, None] + jnp.arange(kp1)[None], s_max - 1)
            # active gates the verify WRITES: frozen/empty rows must not
            # stomp a reserved lane mid-chunk-stream (same contract as
            # _decode_impl's step_fn call).
            logits, cache = target_extend(
                cache, vtokens, vpos, lora_bufs=lora_bufs, slot_ids=slot_ids,
                active=active)
            greedy = greedy_pick(logits, model_cfg.vocab_size)  # [B, K+1]
            first_sampled = sample(
                logits[:, 0], cycle_key, temp, topk, topp,
                valid_vocab=model_cfg.vocab_size,
                seeds=seeds, positions=safe_pos)
            greedy_row = spec_ok & (temp <= 0.0)
            e0 = jnp.where(greedy_row, greedy[:, 0], first_sampled)
            # d_{i+1} must equal the target's greedy continuation g_i.
            match = (draft == greedy[:, :-1]) & greedy_row[:, None]
            m = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
            count = jnp.where(greedy_row, m + 1, 1)
            emitted = greedy.at[:, 0].set(e0)
            lp, top_v, top_i = _logprob_info(
                logits, emitted, model_cfg.vocab_size)

            # --- device-side truncation: frozen rows, budget, EOS ---
            count = jnp.where(active, jnp.minimum(count, remaining), 0)
            in_count = jnp.arange(kp1)[None] < count[:, None]
            iseos = emitted == eos_id
            ex_eos = (jnp.cumsum(iseos.astype(jnp.int32), axis=1)
                      - iseos.astype(jnp.int32))  # EOS strictly before j
            valid = in_count & (ex_eos == 0)
            eff = jnp.sum(valid.astype(jnp.int32), axis=1)
            hit_eos = jnp.any(valid & iseos, axis=1)

            # --- carry updates ---
            new_tok = jnp.where(
                eff > 0, emitted[jnp.arange(b), jnp.maximum(eff - 1, 0)],
                tokens)
            new_positions = positions + eff
            new_remaining = jnp.where(hit_eos, 0, remaining - eff)
            # Fully-accepted cycle: d_K's kv is missing from the draft lane;
            # hand it to the next cycle's catch-up.
            full = greedy_row & active & (eff == kp1) & ~hit_eos
            new_extra_tok = jnp.where(full, draft[:, k_steps - 1], extra_tok)
            new_extra_pos = jnp.where(full, positions + k_steps, extra_pos)
            return ((cache, draft_cache, new_tok, new_positions,
                     new_remaining, new_extra_tok, new_extra_pos, full),
                    (emitted, valid, lp, top_v, top_i))

        keys = jax.random.split(key, n_cycles)
        carry, (emitted, valid, lps, top_v, top_i) = jax.lax.scan(
            one_cycle,
            (cache, draft_cache, tokens, positions, remaining,
             extra_tok, extra_pos, has_extra), keys)
        (cache, draft_cache, next_tokens, next_positions, next_remaining,
         next_extra_tok, next_extra_pos, next_has_extra) = carry
        # Flatten [C, B, K+1] -> [C*(K+1), B]: cycle-major, within-cycle
        # order preserved — the host walks it exactly like decode steps.
        t = n_cycles * kp1
        flat = lambda a: jnp.swapaxes(a, 1, 2).reshape((t,) + a.shape[1:2])
        top_flat = lambda a: jnp.swapaxes(a, 1, 2).reshape(
            (t, b) + a.shape[3:])
        return (flat(emitted), flat(valid), flat(lps), top_flat(top_v),
                top_flat(top_i), next_tokens, next_positions, next_remaining,
                next_extra_tok, next_extra_pos, next_has_extra,
                cache, draft_cache)

    def _draft_admit(self, slot_idx: int, prompt_tokens: list[int]) -> None:
        """Mirror a freshly admitted prompt into the draft model's lane so
        the slot can speculate.  Rows admitted through paths the draft
        can't mirror (chunk stream, ring) simply don't speculate."""
        if not self._spec:
            return
        n = len(prompt_tokens)
        req = self.slots[slot_idx].request if self.slots[slot_idx] else None
        if (n > self._max_bucket() or self._slot_temp[slot_idx] > 0.0
                or (req is not None and req.stop_sequences)):
            # Sampled rows never accept proposals — mirroring their prompt
            # into the draft would be a wasted prefill per admission.
            # Stop-sequence rows are excluded too: their automata only run
            # in plain blocks, so they never speculate.
            self._spec_ok[slot_idx] = False
            return
        try:
            bucket = self._bucket(n)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :n] = prompt_tokens
            positions = np.zeros((1, bucket), np.int32)
            positions[0, :n] = np.arange(n)
            k, v = self._jit_draft_prefill(
                self.draft_params, jnp.asarray(tokens), jnp.asarray(positions))
            self.draft_cache = self._jit_draft_insert(
                self.draft_cache, k, v, jnp.int32(slot_idx), jnp.int32(n))
            self._spec_ok[slot_idx] = True
            self._spec_has_extra[slot_idx] = False
            if self.cfg.pipeline_decode and hasattr(self, "_dev_has_extra"):
                self._dev_has_extra = self._dev_has_extra.at[slot_idx].set(
                    False)
        except Exception:
            logger.exception("draft admit failed; slot %d decodes "
                             "non-speculatively", slot_idx)
            self._spec_ok[slot_idx] = False

    def _spec_cycles_per_sync(self) -> int:
        """Speculative cycles per dispatch.

        All-greedy batches: ceil(steps/(K+1)) cycles keeps the per-dispatch
        token cadence comparable to ``decode_steps_per_sync`` plain steps
        (each cycle emits up to K+1 tokens).  Mixed batches: sampled rows
        advance only ONE token per cycle, so the short schedule would
        throttle them (K+1)x per dispatch — run a full ``steps`` cycles
        instead, which restores sampled-row cadence and lets greedy rows
        run ahead (budget masks cap them).  Two schedules = two compiled
        block variants, both cached after first use.  Under the adaptive
        planner, ``steps`` is the planned per-dispatch budget (admission
        pressure and SSE cadence throttle speculative fusion exactly like
        plain fusion)."""
        steps = self._plan_steps()
        mixed = any(
            s is not None
            and not (self._spec_ok[i] and self._slot_temp[i] <= 0.0)
            for i, s in enumerate(self.slots))
        if mixed:
            return steps
        k1 = self.cfg.speculative_k + 1
        return max(1, -(-steps // k1))

    def _spec_row_steps(self, n_cycles: int, k: int) -> list[int]:
        """Per-row paged write-frontier for one spec block: a speculating
        row can advance K+1 per cycle; a sampled/non-spec row advances one
        per cycle but each verify still writes a K-token rejected tail."""
        return [
            n_cycles * (k + 1)
            if self._spec_ok[i] and self._slot_temp[i] <= 0.0
            else n_cycles + k + 1
            for i in range(self.cfg.decode_slots)
        ]

    def _do_spec_step(self) -> None:
        """Sync-loop speculative dispatch: one fused block of cycles."""
        k = self.cfg.speculative_k
        n_cycles = self._spec_cycles_per_sync()
        # Paged: every position a cycle can write (accepted or rejected)
        # must have a real block before dispatch.
        self._paged_ensure_decode(
            n_cycles * (k + 1), pipelined=False,
            per_row_steps=self._spec_row_steps(n_cycles, k))
        t0 = time.perf_counter()
        (toks, valid, lps, top_v, top_i, _next_tok, _next_pos, _next_rem,
         next_etok, next_epos, next_has, self.cache, self.draft_cache) = (
            self._jit_spec_block(
                self.params, self.draft_params, self._lora_buffers(),
                self.cache, self.draft_cache,
                jnp.asarray(self._slot_tokens),
                jnp.asarray(self._slot_positions),
                jnp.asarray(self._slot_remaining),
                jnp.asarray(self._spec_extra_tok),
                jnp.asarray(self._spec_extra_pos),
                jnp.asarray(self._spec_has_extra),
                jnp.asarray(self._spec_ok),
                jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
                jnp.asarray(self._slot_topp), self._next_key(),
                jnp.asarray(self._slot_lora), self._eos_for_device,
                jnp.asarray(self._slot_seed),
                n_cycles=n_cycles, k_steps=k))
        toks_np = np.asarray(toks)  # [T, B]
        valid_np = np.asarray(valid)
        lps_np = np.asarray(lps)
        top_v_np = np.asarray(top_v)
        top_i_np = np.asarray(top_i)
        etok_np = np.asarray(next_etok)
        epos_np = np.asarray(next_epos)
        ehas_np = np.asarray(next_has)
        step_s = time.perf_counter() - t0
        n_tokens = 0
        self.spec_cycles += n_cycles
        t_steps = toks_np.shape[0]
        owners = [s.request.adapter for s in self.slots if s is not None]
        tok_by_owner: dict[str, int] = {}
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            req = slot.request
            if req.cancelled.is_set():
                self._finish(req, "cancelled")
                self._clear_slot(i)
                continue
            finished = False
            row_start = n_tokens
            for j in range(t_steps):
                if not valid_np[j, i]:
                    continue  # rejected / frozen / past-EOS entry
                tok = int(toks_np[j, i])
                req.output_tokens.append(tok)
                self._store_logprobs(req, lps_np[j, i], top_v_np[j, i],
                                     top_i_np[j, i])
                req.stream_event.set()  # per-step emission (see decode walk)
                n_tokens += 1
                slot.position += 1
                self._slot_tokens[i] = tok
                self._slot_remaining[i] = max(0, self._slot_remaining[i] - 1)
                if (self._is_finished(req, tok)
                        or slot.position >= self.cfg.max_seq_len - 1):
                    self._finish(req, "stop" if self._is_stop(req, tok)
                                 else "length")
                    self._clear_slot(i)
                    finished = True
                    break
            if n_tokens > row_start:
                key = owner_key(req.adapter)
                tok_by_owner[key] = (tok_by_owner.get(key, 0)
                                     + n_tokens - row_start)
            req.stream_event.set()
            if finished:
                continue
            self._slot_positions[i] = slot.position
            # Draft catch-up state from the device carry.  A host-only stop
            # (custom ids) above cleared the slot instead; its lane resets
            # on reuse via _draft_admit.
            self._spec_extra_tok[i] = etok_np[i]
            self._spec_extra_pos[i] = epos_np[i]
            self._spec_has_extra[i] = bool(ehas_np[i])
        self.spec_emitted += n_tokens
        if self.usage is not None:
            self.usage.charge_decode(step_s, owners, tok_by_owner)
            self._usage_sync_kv()
        if self.profiler is not None:
            self.profiler.note_dispatch(
                "spec", t0, step_s, active=len(owners),
                total_slots=self.cfg.decode_slots, n_steps=t_steps)
        with self._lock:
            self.total_generated += n_tokens
            inst = n_tokens / step_s if step_s > 0 else 0.0
            a = self.cfg.tps_ema_alpha
            self.decode_tps_ema = (1 - a) * self.decode_tps_ema + a * inst
            # Per-cycle cadence (each verify cycle emits >= 1 token/row).
            # No dispatch_steps observation: that histogram records the
            # PLANNER's power-of-two choices, and a spec block's token-row
            # count (cycles x (K+1)) is not one of them.
            self.phase_hist["decode_step"].observe(step_s / max(1, n_cycles))

    def _prefill_common(self, req: Request):
        """Shared admission path: bucketed (or ring sequence-parallel)
        prefill + insert.  Long prompts only reach here when ``_ring_usable``
        — otherwise ``_admit_and_insert`` diverts them to the interleaved
        chunk stream (``_start_stream``/``_stream_step``).
        Returns (slot_idx, first_token_device, n, lora_slot, lp_info)."""
        self._stamp_prefill_start(req)
        slot_idx = self._free_slot_index()
        n = len(req.prompt_tokens)
        lora_slot = self.lora.slot_for(req.adapter) if self.lora is not None else -1
        if self._prefix_enabled and n <= self._max_bucket():
            # Cached shared prefix: skip the full-prompt program entirely
            # and prefill only the suffix (VERDICT r2 #6 — the shared
            # system prompt below the largest bucket is the common case).
            # None = nothing cached matched; fall through (one hash walk).
            res = self._prefix_bucket_prefill(req, slot_idx, n, lora_slot)
            if res is not None:
                return res
        if n > self._max_bucket():
            first_token, k, v, lp_info = self._ring_prefill(req, n, lora_slot)
        else:
            first_token, k, v, lp_info = self._bucket_prefill(req, n, lora_slot)
        # Insert prompt KV (trim to bucket; cache rows are max_seq_len).
        self._insert_prompt_kv(k, v, slot_idx, n)
        if self._prefix_enabled and n <= self._max_bucket():
            self._prefix_register_row(slot_idx, req.prompt_tokens,
                                      req.adapter)
        return slot_idx, first_token, n, lora_slot, lp_info

    def _prefix_bucket_prefill(self, req: Request, slot_idx: int, n: int,
                               lora_slot: int):
        """Bucketed admission over a cached prefix: map the cached blocks
        into the row's table (zero compute), then run ONE chunk program
        over the suffix — padded to the suffix's own bucket, attending to
        the mapped prefix KV through the page table.  A 256-token shared
        system prompt with a 32-token question prefills 32 tokens, not 288.
        Returns the ``_prefill_common`` tuple, or None when nothing cached
        matched (caller falls through to the plain bucketed program)."""
        reused = self._prefix_match_and_map(
            slot_idx, req.prompt_tokens, req.adapter,
            hashes=self._prefix_hashes_for(req))
        if reused == 0:
            return None
        try:
            self._paged_ensure(slot_idx, n)
        except PagedPoolExhausted:
            # The gate may have admitted on PLAIN-path feasibility (reuse
            # pinned the matched evictables and came up short).  Unwind the
            # map — the blocks return to the evictable LRU — and fall back
            # to the full-prompt program, which can evict them.
            self._paged_free_row(slot_idx)
            self.prefix_reused_tokens -= reused  # nothing was reused
            self._kv_note_reuse_unwind(req, reused)
            return None
        try:
            self._sync_tables()
            c = n - reused
            bucket = self._bucket(c)
            self._note_padding(bucket - c)
            tokens = np.zeros((bucket,), np.int32)
            tokens[:c] = req.prompt_tokens[reused:]
            positions = reused + np.arange(bucket, dtype=np.int32)
            last_logits, self.cache = self._jit_chunk(
                self.params, self.cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.int32(slot_idx), jnp.int32(n), jnp.int32(c - 1),
                lora_bufs=self._lora_buffers(),
                lora_slot=jnp.int32(lora_slot),
            )
            self._prefix_register_row(slot_idx, req.prompt_tokens,
                                      req.adapter,
                                      hashes=self._prefix_hashes_for(req))
            sp = req.sampling
            first_token, lp_info = self._jit_sample_one(
                last_logits, self._next_key(), jnp.float32(sp.temperature),
                jnp.int32(sp.top_k), jnp.float32(sp.top_p),
                jnp.int32(_seed_i32(sp.seed)),
                jnp.int32(n - 1), *map(jnp.asarray, _bias_arrays(sp)))
        except BaseException:
            # Defensive: _paged_can_admit gated this admission (matched
            # blocks excluded from avail when pinned out of the evictable
            # LRU), so exhaustion here should not happen — but any failure
            # must not strand the mapped prefix refs or fresh suffix blocks
            # (the caller's cleanup only fires once it knows slot_idx).
            self._paged_free_row(slot_idx)
            self.prefix_reused_tokens -= reused  # nothing was reused
            self._kv_note_reuse_unwind(req, reused)
            raise
        return slot_idx, first_token, n, lora_slot, lp_info

    def _ring_usable(self, n: int) -> bool:
        """True when the sequence-parallel prefill path can take this prompt."""
        if self._ring is None:
            return False
        padded = -(-n // self._ring_pad) * self._ring_pad
        return padded <= self.cfg.max_seq_len

    def _ring_prefill(self, req: Request, n: int, lora_slot: int):
        """One sequence-parallel prefill program over the mesh ring.

        The prompt pads right to a multiple of ``_ring_pad`` (a bounded set
        of compiled shapes, like buckets); pad rows sit after the real
        tokens, so causal ring attention keeps real positions exact and the
        garbage tail is trimmed by the length-``n`` insert.
        """
        from llm_instance_gateway_tpu.parallel import long_context

        sp = req.sampling
        padded = -(-n // self._ring_pad) * self._ring_pad
        self._note_padding(padded - n)
        tokens = np.zeros((1, padded), np.int32)
        tokens[0, :n] = req.prompt_tokens
        positions = np.broadcast_to(
            np.arange(padded, dtype=np.int32), (1, padded))
        tok_d, pos_d = long_context.shard_inputs(
            self.mesh, jnp.asarray(tokens), jnp.asarray(positions))
        logits, k, v = self._ring(
            self.params, tok_d, pos_d,
            lora_bufs=self._lora_buffers(),
            slot_ids=jnp.full((1,), lora_slot, jnp.int32),
        )
        first_token, lp_info = self._jit_sample_one(
            logits[0, n - 1], self._next_key(),
            jnp.float32(sp.temperature), jnp.int32(sp.top_k),
            jnp.float32(sp.top_p),
            jnp.int32(_seed_i32(sp.seed)),
            jnp.int32(n - 1), *map(jnp.asarray, _bias_arrays(sp)),
        )
        return first_token, k, v, lp_info

    def _bucket_prefill(self, req: Request, n: int, lora_slot: int):
        """Pad a bucketable prompt and run the jitted prefill.
        Returns (first_token device scalar, k, v, lp_info)."""
        sp = req.sampling
        bucket = self._bucket(n)
        self._note_padding(bucket - n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = req.prompt_tokens
        positions = np.zeros((1, bucket), np.int32)
        positions[0, :n] = np.arange(n)
        return self._jit_prefill(
            self.params, self._lora_buffers(),
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.int32(n), jnp.int32(lora_slot),
            jnp.float32(sp.temperature), jnp.int32(sp.top_k),
            jnp.float32(sp.top_p), self._next_key(),
            jnp.int32(_seed_i32(sp.seed)),
            *map(jnp.asarray, _bias_arrays(sp)),
        )

    def _bucket_prefill_many(self, reqs, ns, lora_slots):
        """One [P, bucket] prefill over same-bucket prompts.
        Returns (first_tokens [P] device, k [L,P,S,...], v, lp_infos)."""
        bucket = self._bucket(max(ns))
        p = len(reqs)
        self._note_padding(sum(bucket - n for n in ns))
        tokens = np.zeros((p, bucket), np.int32)
        positions = np.zeros((p, bucket), np.int32)
        for i, (req, n) in enumerate(zip(reqs, ns)):
            tokens[i, :n] = req.prompt_tokens
            positions[i, :n] = np.arange(n)
        sps = [r.sampling for r in reqs]
        return self._jit_prefill_many(
            self.params, self._lora_buffers(),
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(ns, jnp.int32), jnp.asarray(lora_slots, jnp.int32),
            jnp.asarray([sp.temperature for sp in sps], jnp.float32),
            jnp.asarray([sp.top_k for sp in sps], jnp.int32),
            jnp.asarray([sp.top_p for sp in sps], jnp.float32),
            self._next_key(),
            jnp.asarray([_seed_i32(sp.seed) for sp in sps],
                        jnp.int32),
            *(jnp.asarray(np.stack(arrs))
              for arrs in zip(*(_bias_arrays(sp) for sp in sps))),
        )

    def _collect_followers(self, first_req, limit: int) -> list:
        """Pull same-bucket followers of ``first_req`` for one batched
        prefill, up to ``limit`` total.  The first non-groupable pull parks
        as ``_pending`` — FIFO order holds."""
        group = [first_req]
        bucket = self._bucket(len(first_req.prompt_tokens))
        while len(group) < limit and self._pending is None:
            try:
                nxt = self.prefill_queue.get_nowait()
            except queue_mod.Empty:
                break
            if nxt.cancelled.is_set():
                self._finish(nxt, "cancelled")
                continue
            n = len(nxt.prompt_tokens)
            if n <= self._max_bucket() and self._bucket(n) == bucket:
                group.append(nxt)
            else:
                self._pending = nxt  # different bucket/long: next cycle
        return group

    def _collect_prefill_group(self, first_req) -> list:
        """Direct-admission grouping: bounded by free unreserved slots.

        Only the direct branch calls this (decode_wait empty, a free slot
        for the head), so every grouped request admits under exactly the
        checks the one-at-a-time path applied."""
        return self._collect_followers(first_req, min(
            self.cfg.prefill_batch,
            sum(1 for i, s in enumerate(self.slots)
                if s is None and i not in self._reserved_slots)))

    def _collect_ahead_group(self, first_req, cap: int) -> list:
        """Prefill-ahead grouping: bounded by decode_wait headroom."""
        return self._collect_followers(first_req, min(
            self.cfg.prefill_batch, max(1, cap - len(self.decode_wait))))

    def _park_waiting(self, req, first_token, lp_info, k, v, n: int,
                      lora_slot: int, pipelined: bool) -> None:
        """Park one prefilled row in decode_wait (the prefill-ahead
        contract: sync mode emits the first token NOW — TTFT is
        prefill-bound, not slot-bound; pipelined keeps it device-side)."""
        w = _WaitingPrefill(request=req, first_token=first_token,
                            lp_info=lp_info, k=k, v=v, n=n,
                            lora_slot=lora_slot)
        if not pipelined:
            tok = int(first_token)
            w.first_token_host = tok
            if self._emit_first_token(req, tok, w.lp_info):
                w.lp_info = None
                return  # done at prefill; never needed a slot
        self.decode_wait.append(w)
        # Parked prompt KV pins real HBM ([L, 1, bucket, Kh, hd] per entry)
        # outside the decode cache — count the padded rows so the routing
        # signal sees the pressure (metrics_snapshot).
        self._parked_kv_tokens += w.k.shape[2]
        if self.kv_ledger is not None:
            self.kv_ledger.note_park(int(w.k.shape[2]), "prefill_ahead")
        self._usage_sync_kv()

    def _do_prefill_ahead_group(self, reqs, pipelined: bool) -> None:
        """Batched prefill-ahead: one program, every row parks in
        decode_wait (mirrors ``_do_prefill_ahead`` per row)."""
        batch = self._grouped_batch(
            reqs, pipelined,
            lambda req: self._do_prefill_ahead(req, pipelined))
        if batch is None:
            return
        live, ns, lora_slots, k, v, tok_rows, lp_rows = batch
        for i, req in enumerate(live):
            try:
                self._park_waiting(
                    req, tok_rows[i], lp_rows[i],
                    k[:, i:i + 1], v[:, i:i + 1], ns[i], lora_slots[i],
                    pipelined)
            except Exception as e:
                logger.exception("grouped parking failed for %s",
                                 req.request_id)
                req.error = str(e)
                self._finish(req, "error")

    def _grouped_batch(self, reqs, pipelined: bool, single_fn):
        """Shared grouped-prefill preamble: filter cancelled/bad-adapter
        rows (each fails alone), fall back to ``single_fn`` for a group of
        one, run ONE batched prefill (a failure there fails the whole
        group — the engine-survives posture of the single path).

        Returns None when the caller has nothing left to do, else
        ``(live, ns, lora_slots, k, v, tok_rows, lp_rows)`` where the
        per-row token/logprob views are already in the right place for the
        mode: sync mode fetched them host-side in ONE transfer each
        (P scalar syncs would re-pay the round-trips batching removed);
        pipelined mode holds device scalars with the async copy issued on
        the exact slices later materialized.
        """
        live, ns, lora_slots = [], [], []
        for req in reqs:
            if req.cancelled.is_set():
                self._finish(req, "cancelled")
                continue
            try:
                lora_slots.append(
                    self.lora.slot_for(req.adapter)
                    if self.lora is not None else -1)
            except Exception as e:  # unknown adapter fails only this row
                req.error = str(e)
                self._finish(req, "error")
                continue
            live.append(req)
            ns.append(len(req.prompt_tokens))
        if not live:
            return None
        if len(live) == 1:
            single_fn(live[0])
            return None
        self._stamp_prefill_start(*live)
        try:
            first_tokens, k, v, (lps, top_vs, top_is) = (
                self._bucket_prefill_many(live, ns, lora_slots))
            if pipelined:
                # One async DMA per ARRAY (not per row); rows keep their
                # device slice for the carry scatter and materialize
                # host-side from the shared bulk transfer.
                hb = _HostBatch(first_tokens, lps, top_vs, top_is)
                tok_rows = [_Row(hb, 0, i, dev=first_tokens[i])
                            for i in range(len(live))]
                lp_rows = [(_Row(hb, 1, i), _Row(hb, 2, i), _Row(hb, 3, i))
                           for i in range(len(live))]
            else:
                toks = np.asarray(first_tokens)
                lps_h, top_vs_h, top_is_h = (
                    np.asarray(lps), np.asarray(top_vs), np.asarray(top_is))
                tok_rows = [int(t) for t in toks]
                lp_rows = [(lps_h[i], top_vs_h[i], top_is_h[i])
                           for i in range(len(live))]
        except Exception as e:
            logger.exception("grouped prefill failed (%d reqs)", len(live))
            for req in live:
                req.error = str(e)
                self._finish(req, "error")
            return None
        return live, ns, lora_slots, k, v, tok_rows, lp_rows

    def _do_prefill_group(self, reqs, pipelined: bool) -> None:
        """Batched admission: one prefill program fills len(reqs) slots.
        Per-row post-processing mirrors ``_do_prefill`` /
        ``_do_prefill_pipelined``; a row that fails after the batched call
        fails alone."""
        batch = self._grouped_batch(
            reqs, pipelined,
            self._do_prefill_pipelined if pipelined else self._do_prefill)
        if batch is None:
            return
        live, ns, lora_slots, k, v, tok_rows, lp_rows = batch
        pool_starved = False  # once a row parks on exhaustion, FIFO holds
        for i, req in enumerate(live):
            try:
                slot_idx = self._free_slot_index()
                if pool_starved:
                    slot_idx = None  # later rows must not overtake the parked one
                if slot_idx is None:
                    # Defensive: the free-slot count is taken at collection
                    # and the engine loop is single-threaded, so this should
                    # not happen — but a computed prefill must never be
                    # dropped.  Park it exactly like a prefill-ahead.
                    self._park_waiting(
                        req, tok_rows[i], lp_rows[i],
                        k[:, i:i + 1], v[:, i:i + 1], ns[i], lora_slots[i],
                        pipelined)
                    continue
                try:
                    self._insert_prompt_kv(
                        k[:, i:i + 1], v[:, i:i + 1], slot_idx, ns[i])
                except PagedPoolExhausted:
                    # The group outran the pool: this row (and, for FIFO,
                    # every later row) parks off-cache like a prefill-ahead
                    # and inserts when blocks free (_drain_decode_wait
                    # gates on _paged_can_admit).  _insert_prompt_kv
                    # already freed the partial row.
                    pool_starved = True
                    self._park_waiting(
                        req, tok_rows[i], lp_rows[i],
                        k[:, i:i + 1], v[:, i:i + 1], ns[i], lora_slots[i],
                        pipelined)
                    continue
                if pipelined:
                    self._activate_slot_pipelined(
                        slot_idx, req, lora_slots[i], ns[i],
                        tok_rows[i], lp_rows[i])
                else:
                    if self._emit_first_token(req, tok_rows[i], lp_rows[i]):
                        continue  # finished at prefill
                    self._register_slot(slot_idx, _Slot(
                        request=req, lora_slot=lora_slots[i],
                        position=ns[i]))
                    self._slot_tokens[slot_idx] = int(req.output_tokens[-1])
                    self._slot_positions[slot_idx] = ns[i]
                    self._count_first_token(
                        slot_idx, int(req.output_tokens[-1]))
                    self._draft_admit(slot_idx, req.prompt_tokens)
            except Exception as e:
                logger.exception("grouped admission failed for %s",
                                 req.request_id)
                req.error = str(e)
                self._finish(req, "error")

    def _insert_prompt_kv(self, k, v, slot_idx: int, n: int,
                          skip_leading_blocks: int = 0) -> None:
        """Write a bucketed prefill's KV into the cache (lane or paged).

        ``skip_leading_blocks`` (paged only) diverts that many leading
        blocks' positions to the trash block — the attach path's
        prefix-reuse composition, where those blocks are already mapped
        from the cache and must not be re-scattered."""
        if not self.paged:
            self.cache = self._jit_insert(
                self.cache, k, v, jnp.int32(slot_idx), jnp.int32(n)
            )
            return
        try:
            self._paged_ensure(slot_idx, n)
        except PagedPoolExhausted:
            self._paged_free_row(slot_idx)
            raise
        bucket = k.shape[2]
        nb_bucket = -(-bucket // self._block)
        row_bl = self._row_blocks[slot_idx]
        # Wholly-padding bucket blocks scatter into the trash block.
        phys = row_bl + [paged_lib.TRASH_BLOCK] * (nb_bucket - len(row_bl))
        if skip_leading_blocks:
            phys = ([paged_lib.TRASH_BLOCK] * skip_leading_blocks
                    + phys[skip_leading_blocks:])
        self._sync_tables()
        self.cache = self._jit_insert(
            self.cache, k, v, jnp.int32(slot_idx),
            jnp.asarray(phys, jnp.int32),
            jnp.asarray(self._tables_host[slot_idx]),
            jnp.int32(n),
        )

    # ------------------------------------------------------------------
    # interleaved long-prompt streaming (one chunk per engine cycle)
    # ------------------------------------------------------------------

    def _lane_available(self, n_prompt: int) -> bool:
        """KV-pressure-aware lane admission: may a new chunk stream take a
        reserved lane now?  Bounded by ``stream_lanes``; lanes beyond the
        first additionally require the paged pool to keep a growth block
        per active decode row AFTER the stream's atomic whole-prompt
        allocation — a second 32k stream must unblock head-of-line waits,
        not starve running decode of its next block."""
        if len(self._streams) >= max(1, self.cfg.stream_lanes):
            return False
        if not self._streams or not self.paged:
            return True
        active = sum(1 for s in self.slots if s is not None)
        avail = len(self._free_blocks) + (
            len(self._evictable) if self._prefix_enabled else 0)
        return avail - self._paged_needed(n_prompt + 1) >= active

    def _start_stream(self, req: Request) -> bool:
        """Reserve a free lane and begin streaming a long prompt into it.

        The lane is held out of ``_free_slot_index`` (not a live slot, so
        decode steps skip it) and receives one chunk per ``_stream_step``
        pick (fair round-robin across up to ``stream_lanes`` concurrent
        streams).  Returns False only when the request was reparked for
        backpressure (caller must stop admitting this cycle).
        """
        if req.cancelled.is_set():
            self._finish(req, "cancelled")
            return True
        self._stamp_prefill_start(req)
        try:
            slot_idx = self._free_slot_index()
            lora_slot = (self.lora.slot_for(req.adapter)
                         if self.lora is not None else -1)
        except Exception as e:
            logger.exception("stream admission failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")
            return True
        self._reserved_slots.add(slot_idx)
        reused = 0
        if self.paged:
            # Allocate the WHOLE prompt's blocks now, atomically with the
            # _paged_can_admit gate the caller just passed (same engine
            # cycle, single thread): interleaved short-request admissions
            # and decode growth between chunks can no longer drain the pool
            # out from under a stream mid-flight.
            try:
                # Cached-prefix blocks map in first (refcounted, zero
                # compute); only the suffix gets fresh blocks and chunks.
                reused = self._prefix_match_and_map(
                    slot_idx, req.prompt_tokens, req.adapter)
                self._paged_ensure(slot_idx, len(req.prompt_tokens))
                self._sync_tables()
            except PagedPoolExhausted:
                # Defensive (the gate should prevent this): repark as the
                # head-of-line pending request and retry when blocks free.
                self._paged_free_row(slot_idx)
                self._reserved_slots.discard(slot_idx)
                self._pending = req
                return False
        self._streams.append(_ChunkStream(request=req, slot_idx=slot_idx,
                                          lora_slot=lora_slot,
                                          next_start=reused))
        return True

    def _abort_stream(self, st: _ChunkStream, reason: str) -> None:
        if st in self._streams:
            self._streams.remove(st)
        self._reserved_slots.discard(st.slot_idx)
        if self.paged:
            self._paged_free_row(st.slot_idx)
        self._finish(st.request, reason)

    def _stream_step(self, pipelined: bool) -> None:
        """Dispatch ONE chunk of ONE in-flight stream — the round-robin
        cursor rotates across lanes, so N concurrent long prompts advance
        fairly interleaved (one chunk per engine cycle total keeps the
        decode cadence unchanged versus a single lane).  On a stream's
        final chunk, sample the first token and activate its lane as a
        live decode slot."""
        if not self._streams:
            return
        self._stream_rr %= len(self._streams)
        st = self._streams[self._stream_rr]
        self._stream_rr += 1
        req = st.request
        if req.cancelled.is_set():
            self._abort_stream(st, "cancelled")
            return
        chunk = self._max_bucket()
        prompt = req.prompt_tokens
        n = len(prompt)
        start = st.next_start
        piece = prompt[start:start + chunk]
        c = len(piece)
        tokens = np.zeros((chunk,), np.int32)
        tokens[:c] = piece
        positions = start + np.arange(chunk, dtype=np.int32)
        try:
            if self.paged:
                self._paged_ensure(st.slot_idx, start + c)
                self._sync_tables()
            st.last_logits, self.cache = self._jit_chunk(
                self.params, self.cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.int32(st.slot_idx), jnp.int32(start + c), jnp.int32(c - 1),
                lora_bufs=self._lora_buffers(),
                lora_slot=jnp.int32(st.lora_slot),
            )
        except Exception as e:  # engine must survive a poison request
            logger.exception("chunk stream failed for %s", req.request_id)
            req.error = str(e)
            self._abort_stream(st, "error")
            return
        st.next_start = start + c
        if st.next_start < n:
            return  # more chunks; the loop decodes before the next one
        # Final chunk: publish the prompt's full blocks for prefix reuse,
        # sample the first token, then activate the lane as a live slot.
        if self.paged:
            self._prefix_register_row(st.slot_idx, prompt, req.adapter)
        self._streams.remove(st)
        self._reserved_slots.discard(st.slot_idx)
        slot_idx = st.slot_idx
        sp = req.sampling
        try:
            first_token, lp_info = self._jit_sample_one(
                st.last_logits, self._next_key(),
                jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                jnp.float32(sp.top_p),
                jnp.int32(_seed_i32(sp.seed)),
                jnp.int32(n - 1), *map(jnp.asarray, _bias_arrays(sp)),
            )
            if pipelined:
                try:
                    first_token.copy_to_host_async()
                except AttributeError:
                    pass
                self._activate_slot_pipelined(
                    slot_idx, req, st.lora_slot, n, first_token, lp_info)
                return
            if self._emit_first_token(req, int(first_token), lp_info):
                if self.paged:  # finished at prefill; free the lane's blocks
                    self._paged_free_row(slot_idx)
                return
            self._register_slot(
                slot_idx, _Slot(request=req, lora_slot=st.lora_slot,
                                position=n))
            self._slot_tokens[slot_idx] = int(req.output_tokens[-1])
            self._slot_positions[slot_idx] = n
            self._count_first_token(slot_idx, int(req.output_tokens[-1]))
        except Exception as e:
            logger.exception("stream activation failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")
            if self.paged and self.slots[slot_idx] is None:
                self._paged_free_row(slot_idx)

    def _register_slot(self, slot_idx: int, slot: _Slot) -> None:
        sp = slot.request.sampling
        self.slots[slot_idx] = slot
        self._slot_lora[slot_idx] = slot.lora_slot
        self._slot_temp[slot_idx] = sp.temperature
        self._slot_topk[slot_idx] = sp.top_k
        self._slot_topp[slot_idx] = sp.top_p
        self._slot_seed[slot_idx] = _seed_i32(sp.seed)
        self._slot_presence[slot_idx] = sp.presence_penalty
        self._slot_frequency[slot_idx] = sp.frequency_penalty
        (self._slot_bias_ids[slot_idx],
         self._slot_bias_vals[slot_idx]) = _bias_arrays(sp)
        if sp.presence_penalty or sp.frequency_penalty:
            # Materialize + zero the row; the first-token count follows via
            # _count_first_token once the prefill's token is known.
            self._dev_counts = self._counts().at[slot_idx].set(0)
        # Budget for device-side stop: the prefill already produced token 1.
        self._slot_remaining[slot_idx] = max(0, slot.request.max_new_tokens - 1)
        self._program_stop_lanes(slot_idx, slot.request)
        self._usage_sync_kv()

    def _program_stop_lanes(self, slot_idx: int, req: Request) -> None:
        """Compile the request's stop suffixes into the row's device
        automaton lanes.  Custom single-token stop ids fold in as length-1
        sequences; anything that does not fit the static lanes — or
        ``device_stops`` off — leaves the lanes empty, and the host oracle
        in the result walk stays authoritative either way."""
        self._slot_stop_ids[slot_idx] = -1
        self._slot_stop_lens[slot_idx] = 0
        self._slot_stop_hist[slot_idx] = -1
        # Speculative engines: custom single-token stop ids keep the old
        # host-side seam (programming them would pause spec dispatch via
        # _stops_active for no freeze win — spec blocks host-trim anyway);
        # multi-token sequences DO program and exclude speculation.
        fold_ids = not self._spec
        if self.cfg.device_stops and (req.stop_sequences
                                      or (fold_ids and req.stop_token_ids)):
            enc = encode_stop_rows(
                [tuple(s) for s in req.stop_sequences]
                + ([(int(t),) for t in req.stop_token_ids]
                   if fold_ids else []))
            if enc is not None:
                ids, lens = enc
                self._slot_stop_ids[slot_idx] = ids
                self._slot_stop_lens[slot_idx] = lens
        self._stops_active = int(
            (self._slot_stop_lens.sum(axis=1) > 0).sum())

    def _record_ttft(self, req: Request) -> None:
        with self._lock:
            self.ttft_history.append(req.ttft_s)
            if len(self.ttft_history) > 1000:
                del self.ttft_history[:500]
            if req.t_prefill_start and req.t_first_token:
                # Pure prefill compute (queue wait excluded) — the
                # tpu:prefill_seconds exposition family.
                self.phase_hist["prefill"].observe(
                    max(0.0, req.t_first_token - req.t_prefill_start))
        if (self.usage is not None
                and req.t_prefill_start and req.t_first_token):
            # Attribution: the prefill's wall charged whole to its owner
            # (grouped prefills charge each rider the shared program wall
            # — per-request compute-seconds, the same accounting the
            # engine-total conservation denominator accumulates), prompt
            # tokens counted as phase=prefill.
            self.usage.charge_step(
                "prefill",
                max(0.0, req.t_first_token - req.t_prefill_start),
                [req.adapter],
                tokens={owner_key(req.adapter): len(req.prompt_tokens)})
        if (self.profiler is not None
                and req.t_prefill_start and req.t_first_token):
            # t0=None: the prefill wall is time.time-stamped, so it can't
            # anchor the perf_counter gap chain — the profiler records
            # the wall and subtracts it from the next gap instead.
            self.profiler.note_dispatch(
                "prefill", None,
                max(0.0, req.t_first_token - req.t_prefill_start),
                active=1, total_slots=self.cfg.decode_slots,
                n_steps=len(req.prompt_tokens))

    def _note_padding(self, pad_tokens: int) -> None:
        """Bucket/ring padding tokens prefilled and thrown away: counted
        by the usage tracker's pool-waste counter AND the step profiler's
        snapshot (both optional)."""
        if self.usage is not None:
            self.usage.charge_padding(pad_tokens)
        if self.profiler is not None:
            self.profiler.note_padding(pad_tokens)

    def _kv_ledger_sync(self) -> None:
        """Recount the KV ledger's block states from allocator ground
        truth (free list, DISTINCT blocks across row tables — a shared
        prefix block mapped into several rows is one block — and the
        evictable LRU).  Recounting rather than deriving is what makes
        the ledger's conservation sum a leak detector.  Rides the same
        sites as ``_usage_sync_kv``; ``metrics_snapshot`` also calls it
        so a scrape is never staler than the last dispatch."""
        led = self.kv_ledger
        if led is None:
            return
        distinct: set[int] = set()
        for blocks in self._row_blocks:
            distinct.update(blocks)
        led.sync_states(self._free_blocks, len(distinct),
                        len(self._evictable), self._parked_kv_tokens)
        if self._kv_evicts_pending:
            n, self._kv_evicts_pending = self._kv_evicts_pending, 0
            if self.event_sink is not None:
                self.event_sink("kv_evict", n=n)

    def _usage_sync_kv(self) -> None:
        """Refresh the attribution tracker's KV-holdings integral (engine
        thread): active slot rows at their current position, parked
        ``decode_wait`` KV at its padded size (the same HBM the
        ``kv_parked_tokens`` gauge counts), and the in-flight chunk
        stream's filled prefix.  The KV ledger's state recount rides the
        same call sites (it has its own off switch)."""
        self._kv_ledger_sync()
        if self.usage is None:
            return
        holdings: list[tuple[str | None, int]] = [
            (s.request.adapter, s.position)
            for s in self.slots if s is not None]
        holdings += [(w.request.adapter, w.k.shape[2])
                     for w in self.decode_wait]
        holdings += [(st.request.adapter, st.next_start)
                     for st in self._streams if st.next_start > 0]
        self.usage.sync_kv(holdings)

    def observe_handoff(self, seconds: float) -> None:
        """Record one handoff-plane operation (serialize on the prefill
        side; deserialize+attach admission on the decode side) into
        tpu:handoff_seconds.  Called from the HTTP layer."""
        with self._lock:
            self.phase_hist["handoff"].observe(max(0.0, seconds))

    def _stamp_prefill_start(self, *reqs: Request) -> None:
        """Queue wait ends / prefill compute begins (first stamp wins)."""
        now = time.time()
        for r in reqs:
            if not r.t_prefill_start:
                r.t_prefill_start = now

    def _store_logprobs(self, req: Request, lp, top_v, top_i) -> None:
        """Record a token's logprob info iff the request asked for it."""
        if req.logprobs is None:
            return
        req.output_logprobs.append(float(lp))
        if req.logprobs > 0:
            kk = min(req.logprobs, len(top_i))
            req.output_top_logprobs.append(
                {int(top_i[j]): float(top_v[j]) for j in range(kk)})

    def _emit_first_token(self, req: Request, tok: int,
                          lp_info=None) -> bool:
        """Record the prefill's first sampled token (TTFT, stream, counters);
        True if that token already finishes the request."""
        req.t_first_token = time.time()
        req.output_tokens.append(tok)
        if lp_info is not None:
            lp, top_v, top_i = lp_info
            self._store_logprobs(req, np.asarray(lp),
                                 np.asarray(top_v), np.asarray(top_i))
        req.stream_event.set()
        with self._lock:
            self.total_generated += 1
        self._record_ttft(req)
        if self._is_finished(req, tok):
            self._finish(req, "stop" if self._is_stop(req, tok) else "length")
            return True
        return False

    def _do_prefill(self, req: Request) -> None:
        if req.cancelled.is_set():  # died while queued: skip the prefill
            self._finish(req, "cancelled")
            return
        slot_idx = None
        registered = False
        try:
            slot_idx, first_token, n, lora_slot, lp_info = (
                self._prefill_common(req))
            if self._emit_first_token(req, int(first_token), lp_info):
                return  # finished at prefill; the finally frees its blocks
            self._register_slot(
                slot_idx, _Slot(request=req, lora_slot=lora_slot, position=n)
            )
            registered = True
            self._slot_tokens[slot_idx] = int(req.output_tokens[-1])
            self._slot_positions[slot_idx] = n
            self._count_first_token(slot_idx, int(req.output_tokens[-1]))
            self._draft_admit(slot_idx, req.prompt_tokens)
        except Exception as e:  # engine must survive a poison request
            logger.exception("prefill failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")
        finally:
            if self.paged and slot_idx is not None and not registered:
                # Early finish or failure after blocks were allocated: a
                # slot-less row would strand them forever (no _clear_slot
                # will ever run for it).
                self._paged_free_row(slot_idx)

    def _paged_ensure_decode(self, n_steps: int, pipelined: bool,
                             per_row_steps: list[int] | None = None) -> None:
        """Pre-dispatch block growth for every active row.

        Pipelined mode's host position lags the device by the IN-FLIGHT
        dispatch, so the reservation is previous-dispatch-steps + this
        dispatch's steps — dispatch sizes vary when speculative blocks
        (cycles x (K+1) writes, including rejected tails) interleave with
        plain blocks, so a flat 2*n_steps would under-reserve after a
        larger block and route in-flight KV writes to the trash block.
        ``per_row_steps`` narrows the reservation per row (a sampled row
        in a speculative block advances one token per cycle, so its write
        frontier is cycles+K, not cycles*(K+1) — reserving the worst case
        for every row would make tight pools fail requests speculation-off
        would serve).  Over-reservation is returned at free.  A row the
        exhausted pool cannot grow fails with "kv pool exhausted" (the
        documented oversubscription tradeoff) without touching the batch.
        """
        prev = self._prev_dispatch_steps if pipelined else 0
        if pipelined:
            # Recorded for BOTH cache layouts: the paged reservation below
            # needs it, and _plan_steps subtracts it from the host-lagged
            # remaining budgets on non-paged pipelined engines too.
            self._prev_dispatch_steps = n_steps
        if not self.paged:
            return
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            row_steps = (per_row_steps[i] if per_row_steps is not None
                         else n_steps)
            target = min(slot.position + row_steps + prev + 1,
                         self.cfg.max_seq_len)
            try:
                self._paged_ensure(i, target)
            except PagedPoolExhausted as e:
                req = slot.request
                logger.warning("kv pool exhausted; failing %s", req.request_id)
                req.error = str(e)
                self._finish(req, "error")
                self._clear_slot(i)
                if pipelined:
                    self._pending_budget_zero.append(i)
        self._sync_tables()

    def _do_decode_step(self) -> None:
        n_steps = self._plan_steps()
        self._paged_ensure_decode(n_steps, pipelined=False)
        t0 = time.perf_counter()
        counts_arg, penalized = self._penalty_dispatch_args()
        (step_tokens, step_valid, step_lps, step_top_v, step_top_i,
         _, _, _, _, counts_out, self.cache) = self._jit_decode(
            self.params, self._lora_buffers(), self.cache,
            jnp.asarray(self._slot_tokens), jnp.asarray(self._slot_positions),
            jnp.asarray(self._slot_lora),
            jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
            jnp.asarray(self._slot_topp), self._next_key(),
            jnp.asarray(self._slot_remaining), self._eos_for_device,
            jnp.asarray(self._slot_seed),
            jnp.asarray(self._slot_presence),
            jnp.asarray(self._slot_frequency), counts_arg,
            jnp.asarray(self._slot_bias_ids),
            jnp.asarray(self._slot_bias_vals),
            jnp.asarray(self._slot_stop_ids),
            jnp.asarray(self._slot_stop_lens),
            jnp.asarray(self._sync_stop_hist()),
            n_steps=n_steps, penalized=penalized,
        )
        if penalized:
            self._dev_counts = counts_out
        toks_np = np.asarray(step_tokens)  # [n_steps, B]
        valid_np = np.asarray(step_valid)
        lps_np = np.asarray(step_lps)
        top_v_np = np.asarray(step_top_v)
        top_i_np = np.asarray(step_top_i)
        step_s = time.perf_counter() - t0
        n_tokens = 0
        # Attribution: owners captured BEFORE the loop clears finished
        # slots (they were all resident for this dispatch's wall).
        owners = [s.request.adapter for s in self.slots if s is not None]
        tok_by_owner: dict[str, int] = {}
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            req = slot.request
            if req.cancelled.is_set():
                self._finish(req, "cancelled")
                self._clear_slot(i)
                continue
            finished = False
            slot_tokens = 0
            for k in range(n_steps):
                if not valid_np[k, i]:
                    continue  # device froze this row (budget/EOS)
                tok = int(toks_np[k, i])
                req.output_tokens.append(tok)
                self._store_logprobs(req, lps_np[k, i], top_v_np[k, i],
                                     top_i_np[k, i])
                # Per-step emission: each token of the fused block is
                # published to the stream consumer as it lands in the
                # trim walk, not once per dispatch — an SSE reader wakes
                # per token instead of per burst.
                req.stream_event.set()
                n_tokens += 1
                slot_tokens += 1
                slot.position += 1
                self._slot_tokens[i] = tok
                self._slot_remaining[i] = max(0, self._slot_remaining[i] - 1)
                if self._is_finished(req, tok) or slot.position >= self.cfg.max_seq_len - 1:
                    self._finish(req, "stop" if self._is_stop(req, tok) else "length")
                    self._clear_slot(i)
                    finished = True
                    break  # tokens past the stop condition are trimmed
            if slot_tokens:
                key = owner_key(req.adapter)
                tok_by_owner[key] = tok_by_owner.get(key, 0) + slot_tokens
            req.stream_event.set()
            if not finished:
                self._slot_positions[i] = slot.position
        if self.usage is not None:
            self.usage.charge_decode(step_s, owners, tok_by_owner)
            self._usage_sync_kv()
        if self.profiler is not None:
            self.profiler.note_dispatch(
                "decode", t0, step_s, active=len(owners),
                total_slots=self.cfg.decode_slots, n_steps=n_steps)
        with self._lock:
            self.total_generated += n_tokens
            inst = n_tokens / step_s if step_s > 0 else 0.0
            a = self.cfg.tps_ema_alpha
            self.decode_tps_ema = (1 - a) * self.decode_tps_ema + a * inst
            # Steady-state cadence: wall per decode step (one token per
            # active slot per step) — tpu:decode_step_seconds.
            self.phase_hist["decode_step"].observe(step_s / n_steps)
            self.dispatch_steps_hist.observe(n_steps)

    # ------------------------------------------------------------------
    # pipelined decode: overlap host readback with the next device block
    # ------------------------------------------------------------------

    def _loop_pipelined(self) -> None:
        """Two-deep pipeline: dispatch block N+1 from the device-resident
        token/position/budget carry BEFORE materializing block N's tokens, so
        the (expensive, relay-bound) device->host readback overlaps compute.

        Consequences handled here:
        - stop detection is device-side (budget/EOS freeze rows and emit
          invalid steps), so a finishing slot wastes no trimmed tokens; rows
          freed for host-only reasons (custom stop ids) get their device
          budget zeroed before the next dispatch;
        - prefill first-tokens stay on device (async-copied) and materialize
          when their slot's first block is processed.
        """
        b = self.cfg.decode_slots
        self._dev_tokens = jnp.zeros((b,), jnp.int32)
        self._dev_positions = jnp.zeros((b,), jnp.int32)
        self._dev_remaining = jnp.zeros((b,), jnp.int32)
        # Stop-automaton history rides the device carry (the pipelined
        # loop's no-host-round-trip contract); rows re-seed at activation.
        self._dev_stop_hist = jnp.full((b, STOP_LEN), -1, jnp.int32)
        if self._spec:
            # Draft catch-up triple lives on device: spec blocks update it
            # in their carry, no host round-trip.
            self._dev_extra_tok = jnp.zeros((b,), jnp.int32)
            self._dev_extra_pos = jnp.zeros((b,), jnp.int32)
            self._dev_has_extra = jnp.zeros((b,), bool)
        self._pending_budget_zero: list[int] = []
        # Write span of the dispatch currently in flight (paged reservation).
        self._prev_dispatch_steps = 0
        inflight: dict | None = None
        while self._running:
            did_work = self._admit_and_insert(pipelined=True)
            if self._streams:
                self._stream_step(pipelined=True)
                did_work = True
            block = None
            if any(s is not None for s in self.slots):
                try:
                    block = self._dispatch_block()
                except Exception as e:
                    logger.exception("pipelined decode dispatch failed")
                    self._fail_all_slots(e)
                did_work = True
            if inflight is not None:
                try:
                    self._process_block(inflight, current=block)
                except Exception as e:
                    # Async JAX errors surface at materialization, not at
                    # dispatch — the sync loop's "engine must survive; fail
                    # the batch" invariant applies here too.
                    logger.exception("pipelined block materialization failed")
                    self._fail_all_slots(e)
                    block = None
                did_work = True
            inflight = block
            if not did_work:
                if self.profiler is not None:
                    self.profiler.note_idle()
                with self._work:
                    self._work.wait(timeout=0.05)
        if inflight is not None:
            try:
                self._process_block(inflight, current=None)
            except Exception as e:
                logger.exception("final block materialization failed")
                self._fail_all_slots(e)

    def _fail_all_slots(self, e: Exception) -> None:
        for i, slot in enumerate(self.slots):
            if slot is not None:
                slot.request.error = str(e)
                self._finish(slot.request, "error")
                self._clear_slot(i)

    def _do_prefill_pipelined(self, req: Request) -> None:
        """Prefill + insert with NO synchronous readback: the first token is
        scattered into the device carry and async-copied for later use."""
        if req.cancelled.is_set():  # died while queued: skip the prefill
            self._finish(req, "cancelled")
            return
        slot_idx = None
        registered = False
        try:
            slot_idx, first_token, n, lora_slot, lp_info = (
                self._prefill_common(req))
            try:
                first_token.copy_to_host_async()
            except AttributeError:
                pass
            # t_first_token is stamped when the token MATERIALIZES in
            # _process_block — stamping here would understate TTFT by a block.
            # (_activate_slot_pipelined also drops any queued budget-zero
            # belonging to this lane's PREVIOUS occupant.)
            self._activate_slot_pipelined(
                slot_idx, req, lora_slot, n, first_token, lp_info)
            registered = True
        except Exception as e:
            logger.exception("pipelined prefill failed for %s", req.request_id)
            req.error = str(e)
            self._finish(req, "error")
        finally:
            if self.paged and slot_idx is not None and not registered:
                self._paged_free_row(slot_idx)  # don't strand a slot-less row

    def _dispatch_block(self) -> dict:
        # _stops_active: same speculative exclusion as the sync loop —
        # only plain blocks evaluate the stop automata.
        if self._spec and not self._stops_active and any(
            s is not None and self._spec_ok[i] and self._slot_temp[i] <= 0.0
            for i, s in enumerate(self.slots)
        ):
            return self._dispatch_spec_block()
        n_steps = self._plan_steps()
        self._paged_ensure_decode(n_steps, pipelined=True)
        if self._pending_budget_zero:
            idxs = jnp.asarray(self._pending_budget_zero, jnp.int32)
            self._dev_remaining = self._dev_remaining.at[idxs].set(0)
            self._pending_budget_zero.clear()
        counts_arg, penalized = self._penalty_dispatch_args()
        (toks, valid, lps, top_v, top_i, next_tokens, next_positions,
         next_remaining, next_hist, counts_out, self.cache) = (
            self._jit_decode(
                self.params, self._lora_buffers(), self.cache,
                self._dev_tokens, self._dev_positions,
                jnp.asarray(self._slot_lora),
                jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
                jnp.asarray(self._slot_topp), self._next_key(),
                self._dev_remaining, self._eos_for_device,
                jnp.asarray(self._slot_seed),
                jnp.asarray(self._slot_presence),
                jnp.asarray(self._slot_frequency), counts_arg,
                jnp.asarray(self._slot_bias_ids),
                jnp.asarray(self._slot_bias_vals),
                jnp.asarray(self._slot_stop_ids),
                jnp.asarray(self._slot_stop_lens),
                self._dev_stop_hist,
                n_steps=n_steps, penalized=penalized,
            )
        )
        if penalized:
            self._dev_counts = counts_out
        self._dev_tokens = next_tokens
        self._dev_positions = next_positions
        self._dev_remaining = next_remaining
        self._dev_stop_hist = next_hist
        for arr in (toks, valid, lps, top_v, top_i):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        return {
            "toks": toks,
            "valid": valid,
            "lps": lps,
            "top_v": top_v,
            "top_i": top_i,
            "rows": list(self.slots),  # request refs valid at dispatch time
            "n_steps": n_steps,
            "t0": time.perf_counter(),
        }

    def _dispatch_spec_block(self) -> dict:
        """Pipelined speculative dispatch: same block contract as the plain
        path — flattened [T, B] outputs plus device carries — so
        ``_process_block`` consumes it unchanged.  The draft-extra triple
        rides the device carry; between spec and plain blocks (e.g. the
        only greedy row finished) extras may go stale, which degrades
        proposal quality for a cycle but never correctness: the target's
        verify is exact regardless of what the draft proposes."""
        k = self.cfg.speculative_k
        n_cycles = self._spec_cycles_per_sync()
        self._paged_ensure_decode(
            n_cycles * (k + 1), pipelined=True,
            per_row_steps=self._spec_row_steps(n_cycles, k))
        if self._pending_budget_zero:
            idxs = jnp.asarray(self._pending_budget_zero, jnp.int32)
            self._dev_remaining = self._dev_remaining.at[idxs].set(0)
            self._pending_budget_zero.clear()
        (toks, valid, lps, top_v, top_i, next_tokens, next_positions,
         next_remaining, next_etok, next_epos, next_has,
         self.cache, self.draft_cache) = self._jit_spec_block(
            self.params, self.draft_params, self._lora_buffers(),
            self.cache, self.draft_cache,
            self._dev_tokens, self._dev_positions, self._dev_remaining,
            self._dev_extra_tok, self._dev_extra_pos, self._dev_has_extra,
            jnp.asarray(self._spec_ok),
            jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
            jnp.asarray(self._slot_topp), self._next_key(),
            jnp.asarray(self._slot_lora), self._eos_for_device,
            jnp.asarray(self._slot_seed),
            n_cycles=n_cycles, k_steps=k)
        self._dev_tokens = next_tokens
        self._dev_positions = next_positions
        self._dev_remaining = next_remaining
        self._dev_extra_tok = next_etok
        self._dev_extra_pos = next_epos
        self._dev_has_extra = next_has
        self.spec_cycles += n_cycles
        for arr in (toks, valid, lps, top_v, top_i):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        return {
            "toks": toks,
            "valid": valid,
            "lps": lps,
            "top_v": top_v,
            "top_i": top_i,
            "rows": list(self.slots),
            "n_steps": n_cycles * (k + 1),
            "t0": time.perf_counter(),
            "spec": True,
        }

    def _process_block(self, blk: dict, current: dict | None) -> None:
        toks_np = np.asarray(blk["toks"])  # overlaps with `current` computing
        valid_np = np.asarray(blk["valid"])
        lps_np = np.asarray(blk["lps"])
        top_v_np = np.asarray(blk["top_v"])
        top_i_np = np.asarray(blk["top_i"])
        n_tokens = 0
        n_pending = 0  # prefill first-tokens materialized in this block
        # Attribution owners = every row resident at DISPATCH time (they
        # all shared this block's wall); tokens counted per owner below
        # (pending-first tokens are prefill products, excluded).
        owners = [s.request.adapter for s in blk["rows"] if s is not None]
        tok_by_owner: dict[str, int] = {}
        for i, slot in enumerate(blk["rows"]):
            if slot is None:
                continue
            req = slot.request
            if req.done.is_set():
                continue
            if req.cancelled.is_set():
                self._finish(req, "cancelled")
                if self.slots[i] is slot:
                    self._clear_slot(i)
                    self._pending_budget_zero.append(i)
                if current is not None and current["rows"][i] is slot:
                    current["rows"][i] = None
                continue
            finished = False
            pending = getattr(slot, "pending_first", None)
            if pending is not None:
                pending_tok, pending_lp = pending
                tok0 = int(np.asarray(pending_tok))
                slot.pending_first = None
                req.t_first_token = time.time()
                req.output_tokens.append(tok0)
                if pending_lp is not None:
                    lp0, tv0, ti0 = pending_lp
                    self._store_logprobs(req, np.asarray(lp0),
                                         np.asarray(tv0), np.asarray(ti0))
                n_tokens += 1
                n_pending += 1
                self._record_ttft(req)
                if self._is_finished(req, tok0):
                    finished = True
            if not finished:
                row_tokens = 0
                for k in range(blk["n_steps"]):
                    if not valid_np[k, i]:
                        continue  # device froze this row (budget/EOS)
                    tok = int(toks_np[k, i])
                    req.output_tokens.append(tok)
                    self._store_logprobs(req, lps_np[k, i], top_v_np[k, i],
                                         top_i_np[k, i])
                    req.stream_event.set()  # per-step emission (see decode walk)
                    n_tokens += 1
                    row_tokens += 1
                    slot.position += 1
                    if (
                        self._is_finished(req, tok)
                        or slot.position >= self.cfg.max_seq_len - 1
                    ):
                        finished = True
                        break
                if row_tokens:
                    key = owner_key(req.adapter)
                    tok_by_owner[key] = tok_by_owner.get(key, 0) + row_tokens
            req.stream_event.set()
            if finished:
                self._finish(req, "stop" if self._is_stop(req, req.output_tokens[-1])
                             else "length")
                if self.slots[i] is slot:
                    self._clear_slot(i)
                    # Host-only stop reasons (custom ids, length cap) leave a
                    # positive device budget — zero it before the next dispatch.
                    self._pending_budget_zero.append(i)
                if current is not None and current["rows"][i] is slot:
                    current["rows"][i] = None  # its lane in-flight is garbage
        step_s = time.perf_counter() - blk["t0"]
        if blk.get("spec"):
            # First tokens come from prefill, not speculation.
            self.spec_emitted += n_tokens - n_pending
        if self.usage is not None:
            self.usage.charge_decode(step_s, owners, tok_by_owner)
            self._usage_sync_kv()
        if self.profiler is not None:
            # Pipelined blocks overlap: block N+1's dispatch stamp
            # predates block N's process end, so the profiler's gap math
            # clamps to ~0 host-sync — exactly what the pipeline buys.
            self.profiler.note_dispatch(
                "spec" if blk.get("spec") else "decode", blk["t0"], step_s,
                active=len(owners), total_slots=self.cfg.decode_slots,
                n_steps=blk["n_steps"])
        with self._lock:
            self.total_generated += n_tokens
            inst = n_tokens / step_s if step_s > 0 else 0.0
            a = self.cfg.tps_ema_alpha
            self.decode_tps_ema = (1 - a) * self.decode_tps_ema + a * inst
            # Pipelined blocks overlap compute with readback, so step_s is
            # the block's WALL (dispatch-to-process) — still the honest
            # per-step cadence the gateway compares across replicas.
            self.phase_hist["decode_step"].observe(
                step_s / max(1, blk["n_steps"]))
            if not blk.get("spec"):
                # Planner decision record only — spec blocks' token-row
                # counts are not power-of-two planner choices.
                self.dispatch_steps_hist.observe(blk["n_steps"])

    def _is_stop(self, req: Request, tok: int) -> bool:
        """Host stop oracle, evaluated once per emitted token in the
        post-dispatch walk: EOS / custom stop ids / multi-token stop
        sequences against the output tail.  The device automaton freezes
        rows by the SAME rule mid-block; this check is what actually
        finishes the request, so device/host agreement is structural."""
        if tok == self.eos_id or tok in req.stop_token_ids:
            return True
        if req.stop_sequences:
            out = req.output_tokens
            for seq in req.stop_sequences:
                n = len(seq)
                if n and len(out) >= n and tuple(out[-n:]) == tuple(seq):
                    return True
        return False

    def _is_finished(self, req: Request, tok: int) -> bool:
        return self._is_stop(req, tok) or len(req.output_tokens) >= req.max_new_tokens

    def _finish(self, req: Request, reason: str) -> None:
        if req.done.is_set():
            return  # idempotent: a request finishes (and releases) once
        req.finish_reason = reason
        req.t_done = time.time()
        with self._lock:
            self._live.pop(req.request_id, None)
        # Release BEFORE signalling done: a caller that wakes on done and
        # immediately unloads the adapter must not see a stale pin.
        if req.adapter is not None and self.lora is not None:
            self.lora.release(req.adapter)
        req.stream_event.set()
        req.done.set()
