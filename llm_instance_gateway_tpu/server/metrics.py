"""Prometheus exposition for the model server — the gateway's scrape contract.

Exports exactly the ``tpu:*`` families ``gateway/metrics_client.py`` consumes
(the TPU equivalent of vLLM's ``vllm:*`` names, ``backend/vllm/metrics.go:19-32``),
including the labeled LoRA info gauge whose value is a unix timestamp so the
gateway's latest-series selection works unchanged.
"""

from __future__ import annotations

import time

# Shared with the gateway render path (gateway/telemetry.py): ONE escaping
# implementation for every exposition surface.  Adapter names are validated
# at load time, but escape anyway — one bad label must not poison the whole
# exposition the gateway scrapes.
from llm_instance_gateway_tpu.tracing import escape_label, render_histogram

__all__ = ["escape_label", "render", "render_histogram"]

# Phase-latency histogram families (tracing tentpole): snapshot key ->
# exposition family.  Labeled by model and pool role so disaggregated and
# collocated paths compare directly on one dashboard.
PHASE_FAMILIES = (
    ("prefill", "tpu:prefill_seconds"),
    ("handoff", "tpu:handoff_seconds"),
    ("decode_step", "tpu:decode_step_seconds"),
)


def render(snapshot: dict, extra: dict | None = None) -> str:
    """Render an ``Engine.metrics_snapshot()`` dict to exposition text."""
    lines = [
        "# TYPE tpu:prefill_queue_size gauge",
        f"tpu:prefill_queue_size {snapshot['prefill_queue_size']}",
        "# TYPE tpu:decode_queue_size gauge",
        f"tpu:decode_queue_size {snapshot['decode_queue_size']}",
        "# TYPE tpu:num_requests_running gauge",
        f"tpu:num_requests_running {snapshot['num_requests_running']}",
        "# TYPE tpu:num_requests_waiting gauge",
        f"tpu:num_requests_waiting {snapshot['num_requests_waiting']}",
        "# TYPE tpu:kv_cache_usage_perc gauge",
        f"tpu:kv_cache_usage_perc {snapshot['kv_cache_usage_perc']:.6f}",
        "# TYPE tpu:kv_tokens_capacity gauge",
        f"tpu:kv_tokens_capacity {snapshot['kv_tokens_capacity']}",
        "# TYPE tpu:kv_tokens_free gauge",
        f"tpu:kv_tokens_free {snapshot['kv_tokens_free']}",
        "# TYPE tpu:kv_parked_tokens gauge",
        f"tpu:kv_parked_tokens {snapshot.get('kv_parked_tokens', 0)}",
        "# TYPE tpu:decode_tokens_per_sec gauge",
        f"tpu:decode_tokens_per_sec {snapshot['decode_tokens_per_sec']:.3f}",
        "# TYPE tpu:lora_requests_info gauge",
        # Running vs waiting adapters are DISTINCT labels (vLLM reference
        # semantics): a request parked in decode_wait is waiting, not
        # running — the gateway unions both into its affinity set but an
        # operator must see which replica is actually decoding a tenant.
        # ``adapter_ranks`` (name:rank CSV) carries the LoRA-rank
        # heterogeneity signal the gateway's rank-aware fair-share
        # weighting consumes (gateway/fairness.py); ``resident_tiers``
        # (name:tier CSV over the slot+host RAM tiers) is the residency
        # summary lig-top and /debug/usage render alongside usage shares.
        'tpu:lora_requests_info{running_lora_adapters="%s",'
        'waiting_lora_adapters="%s",max_lora="%d",adapter_ranks="%s",'
        'resident_tiers="%s"} %f'
        % (
            escape_label(",".join(snapshot.get("running_lora_adapters", []))),
            escape_label(",".join(snapshot.get("waiting_lora_adapters", []))),
            snapshot.get("max_lora", 0),
            escape_label(",".join(
                f"{name}:{rank}" for name, rank in sorted(
                    snapshot.get("adapter_ranks", {}).items()))),
            escape_label(",".join(
                f"{name}:{tier}"
                for tier, names in sorted(
                    (snapshot.get("residency") or {}).items())
                for name in names)),
            time.time(),
        ),
    ]
    if "residency" in snapshot:
        # Residency ladder (placement plane, server/lora_manager.py): one
        # info line per tier (value = unix ts, latest-series semantics like
        # lora_requests_info), tier-transition counters, per-tier load
        # latency sums/counts (mean = _total / loads).
        lines.append("# TYPE tpu:adapter_residency_info gauge")
        now = time.time()
        for tier in sorted(snapshot["residency"]):
            names = snapshot["residency"][tier]
            lines.append(
                'tpu:adapter_residency_info{tier="%s",adapters="%s"} %f'
                % (escape_label(tier),
                   escape_label(",".join(names)), now))
        transitions = snapshot.get("tier_transitions") or {}
        lines.append("# TYPE tpu:adapter_tier_transitions_total counter")
        if transitions:
            for (frm, to) in sorted(transitions):
                lines.append(
                    'tpu:adapter_tier_transitions_total{from="%s",to="%s"} %d'
                    % (escape_label(frm), escape_label(to),
                       transitions[(frm, to)]))
        else:
            lines.append("tpu:adapter_tier_transitions_total 0")
        load_seconds = snapshot.get("adapter_load_seconds") or {}
        lines.append("# TYPE tpu:adapter_load_seconds_total counter")
        lines.append("# TYPE tpu:adapter_loads_total counter")
        for tier in sorted(load_seconds):
            total_s, count = load_seconds[tier]
            lines.append('tpu:adapter_load_seconds_total{tier="%s"} %.6f'
                         % (escape_label(tier), total_s))
            lines.append('tpu:adapter_loads_total{tier="%s"} %d'
                         % (escape_label(tier), count))
    if snapshot.get("pool_role"):
        # Disaggregation role as a labeled info gauge (operators / future
        # role-from-scrape discovery; the gateway's routing roles come from
        # its own pod config).
        lines += [
            "# TYPE tpu:pool_role gauge",
            'tpu:pool_role{role="%s"} 1'
            % escape_label(snapshot["pool_role"]),
        ]
    if "prefix_reused_tokens" in snapshot:
        lines += [
            "# TYPE tpu:prefix_reused_tokens counter",
            f"tpu:prefix_reused_tokens {snapshot['prefix_reused_tokens']}",
        ]
    if "spec_cycles" in snapshot:
        lines += [
            "# TYPE tpu:spec_cycles counter",
            f"tpu:spec_cycles {snapshot['spec_cycles']}",
            "# TYPE tpu:spec_tokens_per_cycle gauge",
            f"tpu:spec_tokens_per_cycle {snapshot['spec_tokens_per_cycle']}",
        ]
    if "stream_lanes" in snapshot:
        # Concurrent chunk-stream lanes (engine decode fast path): the
        # configured lane count and how many long prompts are streaming
        # into reserved lanes right now.
        lines += [
            "# TYPE tpu:stream_lanes gauge",
            f"tpu:stream_lanes {snapshot['stream_lanes']}",
            "# TYPE tpu:stream_lanes_active gauge",
            f"tpu:stream_lanes_active {snapshot.get('stream_lanes_active', 0)}",
        ]
    if snapshot.get("dispatch_steps_hist"):
        # Fused steps per decode/spec dispatch — the adaptive planner's
        # decision record (tpu:dispatch_steps buckets land exactly on the
        # planner's power-of-two choices).
        lines += render_histogram("tpu:dispatch_steps",
                                  snapshot["dispatch_steps_hist"], {})
    phase_hist = snapshot.get("phase_hist") or {}
    if phase_hist:
        labels = {"model": snapshot.get("model_name", ""),
                  "role": snapshot.get("pool_role", "") or "collocated"}
        for key, family in PHASE_FAMILIES:
            if key in phase_hist:
                lines += render_histogram(family, phase_hist[key], labels)
    usage = snapshot.get("usage")
    if usage:
        # Capacity attribution (server/usage.py): per-{adapter,phase}
        # step-seconds/tokens, KV block-seconds, the engine-wall
        # conservation denominator, and the pool-waste observables.
        from llm_instance_gateway_tpu.server.usage import render_usage

        lines += render_usage(usage, snapshot.get("model_name", ""))
    kv_ledger = snapshot.get("kv_ledger")
    if kv_ledger:
        # KV economy ledger (server/kv_ledger.py): block-state
        # accounting, per-prefix reuse heatmap, fragmentation/headroom
        # histograms — the tpu:kv_blocks* / tpu:kv_prefix_* families
        # (tools/kv_report.py renders the operator view from /debug/kv).
        from llm_instance_gateway_tpu.server.kv_ledger import render_kv

        lines += render_kv(kv_ledger)
    profile = snapshot.get("profile")
    if profile:
        # Step-timeline profiler (server/profiler.py): per-phase dispatch
        # wall + host-sync/idle gap histograms — the dispatch-bound
        # evidence families (tools/profile_report.py renders the
        # attribution table from /debug/profile).
        from llm_instance_gateway_tpu.server.profiler import render_profile

        lines += render_profile(profile)
    for name, value in (extra or {}).items():
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"
