"""Convert HuggingFace checkpoints into this framework's param layout.

The serving path restores Orbax pytrees (``api_http --checkpoint``); real
deployments start from HF-format weights.  This module maps Llama-2/3
(incl. Llama-3.1 ``rope_scaling``), Gemma, and Mixtral state dicts onto
``transformer.init_params``'s stacked-layer layout — other model types are
rejected loudly until their mappings land — and numerics tests
(tests/test_convert.py) hold our decoder to the canonical implementations'
logits for every supported family.

Conventions verified by that test:
- RoPE: split-halves (rotate_half) convention, matching HF Llama.
- GQA: q [d, H*hd], k/v [d, K*hd] column layouts transpose from HF's
  [out, in] Linear weights.
- RMSNorm pre-norm placement, f32 accumulation.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from llm_instance_gateway_tpu.models.configs import LLAMA3_8B, ModelConfig


def config_from_hf(hf_config) -> ModelConfig:
    """ModelConfig from a transformers Llama/Gemma/Mixtral config object.

    Mapped: Llama (incl. llama3-type rope_scaling), Gemma (embedding scale,
    (1+w) norm, tanh-GeLU), Mixtral (expert stacks + router).  Loud
    rejections instead of silent wrong math for everything else: unknown
    model types, non-llama3 rope_scaling types, and sliding-window attention
    (our decoder attends the full causal context).
    """
    model_type = getattr(hf_config, "model_type", "llama")
    if model_type not in ("llama", "gemma", "mixtral", "qwen2"):
        raise NotImplementedError(
            f"HF model_type {model_type!r} not supported by the converter "
            "(llama, gemma, mixtral, qwen2 are)"
        )
    scaling_kwargs = {}
    rope_scaling = getattr(hf_config, "rope_scaling", None)
    if rope_scaling:
        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type"))
        if rope_type != "llama3":
            raise NotImplementedError(
                f"rope_scaling type {rope_type!r} is not implemented "
                "(only 'llama3'); converting would silently change "
                "long-context frequencies"
            )
        scaling_kwargs = {
            "rope_scaling_factor": float(rope_scaling["factor"]),
            "rope_low_freq_factor": float(rope_scaling["low_freq_factor"]),
            "rope_high_freq_factor": float(rope_scaling["high_freq_factor"]),
            "rope_original_max_len": int(
                rope_scaling["original_max_position_embeddings"]),
        }
    sliding = getattr(hf_config, "sliding_window", None)
    max_pos = getattr(hf_config, "max_position_embeddings", 8192)
    # Qwen2-style configs carry sliding_window but gate it behind
    # use_sliding_window (default False = full causal attention, which our
    # decoder matches exactly); only an ACTIVE window is a real divergence.
    sliding_active = bool(getattr(hf_config, "use_sliding_window", True))
    if sliding and sliding_active and sliding < max_pos:
        raise NotImplementedError(
            f"sliding_window={sliding} < max_position_embeddings={max_pos}: "
            "our decoder attends the full causal context; converting would "
            "produce divergent long-context logits"
        )
    if model_type != "qwen2" and getattr(hf_config, "attention_bias", False):
        # HF llama-style attention_bias puts biases on q/k/v AND o_proj;
        # our bias support covers the Qwen2 layout (q/k/v only).  Loud
        # rejection beats silently dropping the o bias.
        raise NotImplementedError(
            f"attention_bias on model_type {model_type!r} is not supported "
            "(q/k/v/o biases; only the qwen2 q/k/v layout is implemented)"
        )
    gemma = model_type == "gemma"
    return dataclasses.replace(
        LLAMA3_8B,
        name=getattr(hf_config, "name_or_path", "") or f"hf-{model_type}",
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        d_ff=hf_config.intermediate_size,
        head_dim=getattr(hf_config, "head_dim", 0)
        or hf_config.hidden_size // hf_config.num_attention_heads,
        rope_theta=getattr(hf_config, "rope_theta", 10_000.0),
        norm_eps=hf_config.rms_norm_eps,
        max_seq_len=getattr(hf_config, "max_position_embeddings", 8192),
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        # Gemma conventions (parity-tested against GemmaForCausalLM):
        # sqrt(d_model) embedding normalizer, (1+w) RMSNorm, tanh-GeLU gate.
        embedding_scale=gemma,
        norm_plus_one=gemma,
        gelu_mlp=gemma,
        # Mixtral MoE (parity-tested against MixtralForCausalLM; top-k
        # routing normalizations are algebraically identical).
        n_experts=getattr(hf_config, "num_local_experts", 0)
        if model_type == "mixtral" else 0,
        n_experts_per_token=getattr(hf_config, "num_experts_per_tok", 2),
        # Qwen2-family: learned Q/K/V biases (parity-tested against
        # Qwen2ForCausalLM; Qwen2 puts NO bias on o_proj).
        attention_bias=(model_type == "qwen2"),
        **scaling_kwargs,
    )


def params_from_hf_state_dict(cfg: ModelConfig, state_dict, dtype=jnp.bfloat16):
    """Map an HF Llama state dict onto our stacked-layer pytree.

    HF Linear weights are [out, in]; ours are [in, out] — transposed here.
    The embedding row space is padded to ``cfg.padded_vocab``.
    """

    # Per-tensor dtype cast at stack time: staging whole stacked layers in
    # f32 would triple peak host memory on an 8B conversion.
    def t(name):  # tensor -> [in, out] in the target dtype
        return jnp.asarray(np.asarray(state_dict[name]).T, dtype)

    def stack(fmt):
        return jnp.stack([t(fmt.format(i)) for i in range(cfg.n_layers)])

    def stack_raw(fmt):  # norms: 1-D, no transpose
        return jnp.stack(
            [jnp.asarray(np.asarray(state_dict[fmt.format(i)]), dtype)
             for i in range(cfg.n_layers)]
        )

    embed = np.asarray(state_dict["model.embed_tokens.weight"])
    padded = jnp.zeros((cfg.padded_vocab, cfg.d_model), dtype)
    padded = padded.at[: embed.shape[0]].set(jnp.asarray(embed, dtype))

    layers = {
        "attn_norm": stack_raw("model.layers.{}.input_layernorm.weight"),
        "mlp_norm": stack_raw("model.layers.{}.post_attention_layernorm.weight"),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
    }
    if cfg.attention_bias:
        # Qwen2-family Q/K/V biases (1-D, no transpose).
        layers["wq_b"] = stack_raw("model.layers.{}.self_attn.q_proj.bias")
        layers["wk_b"] = stack_raw("model.layers.{}.self_attn.k_proj.bias")
        layers["wv_b"] = stack_raw("model.layers.{}.self_attn.v_proj.bias")
    if cfg.n_experts:
        # Mixtral expert naming: w1=gate, w3=up, w2=down (each [f, d] or
        # [d, f] in HF's [out, in]); stacked here as [L, E, in, out].
        def stack_experts(w_name):
            return jnp.stack([
                jnp.stack([
                    t(f"model.layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight")
                    for e in range(cfg.n_experts)
                ])
                for i in range(cfg.n_layers)
            ])

        layers["router"] = stack("model.layers.{}.block_sparse_moe.gate.weight")
        layers["w_gate"] = stack_experts("w1")
        layers["w_up"] = stack_experts("w3")
        layers["w_down"] = stack_experts("w2")
    else:
        layers["w_gate"] = stack("model.layers.{}.mlp.gate_proj.weight")
        layers["w_up"] = stack("model.layers.{}.mlp.up_proj.weight")
        layers["w_down"] = stack("model.layers.{}.mlp.down_proj.weight")
    params = {
        "embed": padded,
        "layers": layers,
        "final_norm": jnp.asarray(
            np.asarray(state_dict["model.norm.weight"]), dtype),
    }
    if not cfg.tie_embeddings:
        head = jnp.asarray(np.asarray(state_dict["lm_head.weight"]).T, dtype)
        padded_head = jnp.zeros((cfg.d_model, cfg.padded_vocab), dtype)
        params["lm_head"] = padded_head.at[:, : head.shape[1]].set(head)
    return params


def from_hf_llama(model, dtype=jnp.bfloat16):
    """(cfg, params) from a loaded transformers LlamaForCausalLM."""
    cfg = config_from_hf(model.config)
    state = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    return cfg, params_from_hf_state_dict(cfg, state, dtype=dtype)


def save_hf_as_orbax(model, path: str, dtype=jnp.bfloat16) -> ModelConfig:
    """Convert + write the serving checkpoint (api_http --checkpoint).

    The params pytree goes to ``<path>/params`` and the architecture to
    ``<path>/model_config.json`` so the server reconstructs the exact
    ModelConfig without a preset (--model is ignored when present).
    """
    import json
    import os

    import orbax.checkpoint as ocp

    cfg, params = from_hf_llama(model, dtype=dtype)
    os.makedirs(path, exist_ok=True)
    ocp.PyTreeCheckpointer().save(os.path.join(path, "params"), params)
    with open(os.path.join(path, "model_config.json"), "w") as f:
        json.dump(dataclasses.asdict(cfg), f, indent=1)
    return cfg


def load_serving_checkpoint(path: str):
    """(cfg_or_None, params) from a checkpoint directory.

    Accepts both layouts: a bare Orbax params tree (preset-config servers)
    or the ``params`` + ``model_config.json`` pair save_hf_as_orbax writes.
    """
    import json
    import os

    import orbax.checkpoint as ocp

    cfg = None
    params_path = path
    cfg_file = os.path.join(path, "model_config.json")
    if os.path.exists(cfg_file):
        with open(cfg_file) as f:
            cfg = ModelConfig(**json.load(f))
        params_path = os.path.join(path, "params")
    params = ocp.PyTreeCheckpointer().restore(params_path)
    return cfg, params
