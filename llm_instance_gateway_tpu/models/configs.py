"""Model architecture configs for the families the pools serve.

The reference's PoC pools serve Llama-2-7b + LoRA on vLLM
(``examples/poc/manifests/vllm/vllm-lora-deployment.yaml:23-60``); the
BASELINE.json milestone configs call for Gemma-2B, Llama-3-8B and a
Mixtral-8x7B + Gemma-7B mixed pool.  These dataclasses cover all of them with
one decoder family (RoPE + GQA + RMSNorm + gated MLP, optionally MoE).

All dims are chosen/padded TPU-first: head_dim and d_model multiples of 128
(MXU lane width), d_ff multiples of 128, vocab padded to 128 so the final
projection tiles cleanly onto the systolic array.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


def pad_to(x: int, multiple: int) -> int:
    return ((x + multiple - 1) // multiple) * multiple


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    head_dim: int = 0  # 0 -> d_model // n_heads
    rope_theta: float = 500_000.0
    # Llama-3.1 long-context rope scaling (factor 0 = disabled).
    rope_scaling_factor: float = 0.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_len: int = 8192
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    # Gemma-style differences.
    tie_embeddings: bool = False
    embedding_scale: bool = False  # Gemma multiplies embeddings by sqrt(d_model)
    norm_plus_one: bool = False  # Gemma RMSNorm uses (1 + w) weighting
    gelu_mlp: bool = False  # Gemma uses GeLU gating; Llama uses SiLU
    # Qwen2-family difference: learned biases on the Q/K/V projections
    # (attention only — o and the MLP stay bias-free).
    attention_bias: bool = False
    # MoE (Mixtral): 0 experts = dense.
    n_experts: int = 0
    n_experts_per_token: int = 2
    # Grouped MoE dispatch (GShard-style capacity scatter) runs whenever it
    # beats dense all-experts on expert-rows — prefill AND batched decode;
    # expert capacity = tokens*k/E * this factor (large tiles round to a
    # multiple of 8; decode-sized tiles keep the exact ceiling).  With
    # ``moe_exact_fallback`` a batch whose routing overflows any expert's
    # capacity recomputes via the dense all-experts path inside a lax.cond
    # — bit-exact results always, at grouped+dense cost for that batch, so
    # exact mode enforces >= 2.0x headroom at every tile size to keep the
    # double-pay rare (a 16-slot Mixtral decode then computes ~2x the
    # dropless-ideal t*k expert-rows — still half the dense path's E/k=4x).
    # Set False for GShard token-dropping (overflowed assignments
    # contribute zero): the standard serving trade, where this factor
    # applies as-is and the same decode computes ~1.25x dropless-ideal.
    moe_capacity_factor: float = 1.25
    moe_exact_fallback: bool = True
    # LoRA serving slots (compile-time constants: resizing reshapes buffers
    # and recompiles, so they mirror vLLM's --max-loras / max rank flags).
    max_lora_slots: int = 4
    max_lora_rank: int = 16
    # Pallas flash-attention for prefill (right-padded batches only).  On by
    # default: the dispatcher falls back to the XLA reference on CPU or when
    # shapes miss the tiling constraints.  Validated compiled on v5e —
    # bf16-tolerance parity, 19-22x over XLA at S=8192 (tools/
    # onchip_pallas_check.py).
    use_flash_attention: bool = True
    # Pallas cached-decode attention kernel (ops/pallas_decode_attention),
    # same auto-fallback.  Validated compiled on v5e: parity at bf16
    # tolerance; DMA-clamping skips cache blocks past each row's length
    # (2.1x over XLA at S_max=8192, half-full cache).
    use_pallas_decode: bool = True

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    @property
    def padded_vocab(self) -> int:
        return pad_to(self.vocab_size, 128)

    @property
    def rope_scaling(self) -> tuple | None:
        """(factor, low_ff, high_ff, original_max) or None when disabled."""
        if not self.rope_scaling_factor:
            return None
        return (
            self.rope_scaling_factor,
            self.rope_low_freq_factor,
            self.rope_high_freq_factor,
            self.rope_original_max_len,
        )

    def tiny(self) -> "ModelConfig":
        """Shrink to test size, keeping structure (ratios, GQA, MoE-ness)."""
        return replace(
            self,
            name=self.name + "-tiny",
            # Covers the byte-level tokenizer (259 ids) so tiny models serve
            # real text end-to-end.
            vocab_size=320,
            d_model=64,
            n_layers=2,
            n_heads=4,
            n_kv_heads=max(1, self.n_kv_heads * 4 // self.n_heads),
            d_ff=128,
            head_dim=16,
            max_seq_len=128,
            max_lora_rank=4,
        )


# The reference PoC's model (vllm-lora-deployment.yaml:33-39 serves
# meta-llama/Llama-2-7b-hf): MHA (no GQA), theta 1e4, 4k context.
LLAMA2_7B = ModelConfig(
    name="llama2-7b",
    vocab_size=32_000,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=32,
    d_ff=11_008,
    rope_theta=10_000.0,
    max_seq_len=4096,
)

LLAMA3_8B = ModelConfig(
    name="llama3-8b",
    vocab_size=128_256,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    d_ff=14_336,
    rope_theta=500_000.0,
    max_seq_len=8192,
)

GEMMA_2B = ModelConfig(
    name="gemma-2b",
    vocab_size=256_128,
    d_model=2048,
    n_layers=18,
    n_heads=8,
    n_kv_heads=1,
    d_ff=16_384,
    head_dim=256,
    rope_theta=10_000.0,
    tie_embeddings=True,
    embedding_scale=True,
    norm_plus_one=True,
    gelu_mlp=True,
    max_seq_len=8192,
)

GEMMA_7B = ModelConfig(
    name="gemma-7b",
    vocab_size=256_128,
    d_model=3072,
    n_layers=28,
    n_heads=16,
    n_kv_heads=16,
    head_dim=256,
    d_ff=24_576,
    rope_theta=10_000.0,
    tie_embeddings=True,
    embedding_scale=True,
    norm_plus_one=True,
    gelu_mlp=True,
    max_seq_len=8192,
)

MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32_000,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    d_ff=14_336,
    rope_theta=1_000_000.0,
    n_experts=8,
    n_experts_per_token=2,
    max_seq_len=32_768,
)

QWEN2_5_7B = ModelConfig(
    name="qwen2.5-7b",
    vocab_size=152_064,
    d_model=3584,
    n_layers=28,
    n_heads=28,
    n_kv_heads=4,
    d_ff=18_944,
    head_dim=128,
    rope_theta=1_000_000.0,
    norm_eps=1e-6,
    attention_bias=True,
    max_seq_len=32_768,
)

TINY_TEST = LLAMA3_8B.tiny()
TINY_MOE_TEST = MIXTRAL_8X7B.tiny()
TINY_QWEN_TEST = QWEN2_5_7B.tiny()
