"""JAX model definitions: pytree params, functional forwards, LoRA slots.

The reference gateway routes to external vLLM servers and contains no model
code (SURVEY.md §2); this package is the TPU-native equivalent of that
delegated layer — the models the pool's replicas serve.  Pure-JAX pytrees
(no framework modules) so pjit/GSPMD sharding specs apply directly.
"""

from llm_instance_gateway_tpu.models.configs import (
    ModelConfig,
    GEMMA_2B,
    LLAMA3_8B,
    TINY_TEST,
    MIXTRAL_8X7B,
)

__all__ = ["ModelConfig", "GEMMA_2B", "LLAMA3_8B", "TINY_TEST", "MIXTRAL_8X7B"]
