"""Qwen2-family entry points (Qwen2 / Qwen2.5): Llama-style decoder with
learned Q/K/V biases (``ModelConfig.attention_bias`` — the one
architectural delta; RoPE/GQA/RMSNorm/SwiGLU are shared with Llama-3).
A common switcher family: the reference fronts vLLM, which serves Qwen
checkpoints; ``models.convert`` imports the HF layout (q_proj.bias etc.).
"""

from __future__ import annotations

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import QWEN2_5_7B, TINY_QWEN_TEST

CONFIGS = {"qwen2.5-7b": QWEN2_5_7B, "qwen-tiny": TINY_QWEN_TEST}

init_params = transformer.init_params
init_decode_cache = transformer.init_decode_cache
insert_prefill = transformer.insert_prefill
prefill = transformer.prefill
decode_step = transformer.decode_step
