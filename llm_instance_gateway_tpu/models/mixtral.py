"""Mixtral family entry points (8x7B MoE): top-2 routed experts per token.

BASELINE.json's criticality-tiered mixed pool pairs Mixtral-8x7B with
Gemma-7B on v5e-32.  The MoE MLP lives in ``transformer._moe_mlp``; expert
weights carry a leading expert axis that ``parallel.sharding`` maps onto the
mesh's expert/tensor axes.
"""

from __future__ import annotations

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import MIXTRAL_8X7B, TINY_MOE_TEST

CONFIGS = {"mixtral-8x7b": MIXTRAL_8X7B, "mixtral-tiny": TINY_MOE_TEST}

init_params = transformer.init_params
init_decode_cache = transformer.init_decode_cache
insert_prefill = transformer.insert_prefill
prefill = transformer.prefill
decode_step = transformer.decode_step
