"""Llama-3 family entry points.

The flagship family: the reference PoC pool serves Llama-2-7b + LoRA
(``examples/poc/manifests/vllm/vllm-lora-deployment.yaml:23-37``), and the
BASELINE.json scale-out configs are 4x Llama-3-8B.  All heavy lifting lives
in ``models.transformer``; this module binds the config family.
"""

from __future__ import annotations

import functools

import jax

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import (
    LLAMA2_7B,
    LLAMA3_8B,
    TINY_TEST,
    ModelConfig,
)

CONFIGS = {
    "llama2-7b": LLAMA2_7B,
    "llama2-tiny": LLAMA2_7B.tiny(),
    "llama3-8b": LLAMA3_8B,
    "llama3-tiny": TINY_TEST,
}


def init_params(cfg: ModelConfig, key: jax.Array, dtype=None):
    kwargs = {} if dtype is None else {"dtype": dtype}
    return transformer.init_params(cfg, key, **kwargs)


init_decode_cache = transformer.init_decode_cache
insert_prefill = transformer.insert_prefill


def prefill_fn(cfg: ModelConfig):
    """Jittable prefill closure (config static)."""
    return functools.partial(transformer.prefill, cfg)


def decode_fn(cfg: ModelConfig):
    return functools.partial(transformer.decode_step, cfg)
