"""Multi-LoRA serving slots: padded adapter buffers with no-recompile swap.

The reference multiplexes LoRA adapters on vLLM via HTTP hot-load
(``tools/dynamic-lora-sidecar/sidecar/sidecar.py:177-213``) with CUDA-side
slot limits (``--max-loras 4``).  On TPU the equivalent must dodge XLA's
recompile-on-shape-change (SURVEY.md §7 "hard parts"): adapters live in
PRE-ALLOCATED buffers of compile-time shape ``[n_layers, n_slots, d,
r_max]`` — loading an adapter is a pure device-buffer donation
(``buffers.at[:, slot].set(...)``), never a new program.

Adapters with rank r < r_max are zero-padded: padded lanes contribute exactly
0 to the delta, so correctness is rank-independent.  Per-slot ``scale`` holds
alpha/r.  Slot id -1 means "no adapter" (one_hot(-1) == 0 vector -> delta 0),
which lets base-model and adapter requests share one decode batch — the
multiplexing the gateway's LoRA-affinity routing assumes.

Targets: the attention projections q/k/v/o and the MLP gate/up/down, matching
what vLLM serves for Llama-family adapters.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

TARGETS = ("q", "k", "v", "o", "gate", "up", "down")


def target_dims(cfg) -> dict[str, tuple[int, int]]:
    """(d_in, d_out) per LoRA target for this architecture."""
    hd = cfg.resolved_head_dim
    d = cfg.d_model
    return {
        "q": (d, cfg.n_heads * hd),
        "k": (d, cfg.n_kv_heads * hd),
        "v": (d, cfg.n_kv_heads * hd),
        "o": (cfg.n_heads * hd, d),
        "gate": (d, cfg.d_ff),
        "up": (d, cfg.d_ff),
        "down": (cfg.d_ff, d),
    }


def init_lora_buffers(cfg, dtype=jnp.bfloat16) -> dict[str, Any]:
    """All-zero slot buffers (zero delta == base model for every slot)."""
    dims = target_dims(cfg)
    bufs: dict[str, Any] = {"scale": jnp.zeros((cfg.max_lora_slots,), jnp.float32)}
    for t in TARGETS:
        d_in, d_out = dims[t]
        bufs[f"{t}_a"] = jnp.zeros(
            (cfg.n_layers, cfg.max_lora_slots, d_in, cfg.max_lora_rank), dtype
        )
        bufs[f"{t}_b"] = jnp.zeros(
            (cfg.n_layers, cfg.max_lora_slots, cfg.max_lora_rank, d_out), dtype
        )
    return bufs


def load_adapter(
    bufs: dict[str, Any],
    cfg,
    slot: int,
    adapter: dict[str, Any],
    alpha: float,
    rank: int,
) -> dict[str, Any]:
    """Write an adapter into ``slot`` (host-side; returns updated buffers).

    ``adapter`` maps target -> {"a": [n_layers, d_in, r], "b": [n_layers, r,
    d_out]} with r <= max_lora_rank; missing targets stay zero.  Scale
    alpha/r is folded into the per-slot scale vector.
    """
    if not 0 <= slot < cfg.max_lora_slots:
        raise ValueError(f"slot {slot} out of range [0, {cfg.max_lora_slots})")
    if rank > cfg.max_lora_rank:
        raise ValueError(f"rank {rank} exceeds max_lora_rank {cfg.max_lora_rank}")
    dims = target_dims(cfg)
    out = dict(bufs)
    for t in TARGETS:
        d_in, d_out = dims[t]
        a_buf = np.zeros((cfg.n_layers, d_in, cfg.max_lora_rank), np.float32)
        b_buf = np.zeros((cfg.n_layers, cfg.max_lora_rank, d_out), np.float32)
        if t in adapter:
            a = np.asarray(adapter[t]["a"], np.float32)
            b = np.asarray(adapter[t]["b"], np.float32)
            if a.shape != (cfg.n_layers, d_in, rank):
                raise ValueError(f"{t}.a shape {a.shape} != {(cfg.n_layers, d_in, rank)}")
            if b.shape != (cfg.n_layers, rank, d_out):
                raise ValueError(f"{t}.b shape {b.shape} != {(cfg.n_layers, rank, d_out)}")
            a_buf[:, :, :rank] = a
            b_buf[:, :rank, :] = b
        dtype = out[f"{t}_a"].dtype
        out[f"{t}_a"] = out[f"{t}_a"].at[:, slot].set(jnp.asarray(a_buf, dtype))
        out[f"{t}_b"] = out[f"{t}_b"].at[:, slot].set(jnp.asarray(b_buf, dtype))
    out["scale"] = out["scale"].at[slot].set(alpha / rank)
    return out


def unload_adapter(bufs: dict[str, Any], cfg, slot: int) -> dict[str, Any]:
    """Zero a slot (slot becomes base-model passthrough)."""
    out = dict(bufs)
    for t in TARGETS:
        out[f"{t}_a"] = out[f"{t}_a"].at[:, slot].set(0.0)
        out[f"{t}_b"] = out[f"{t}_b"].at[:, slot].set(0.0)
    out["scale"] = out["scale"].at[slot].set(0.0)
    return out


# ---------------------------------------------------------------------------
# Application inside the forward pass.  ``layer_bufs`` is the per-layer slice
# {t_a: [n_slots, d_in, r], t_b: [n_slots, r, d_out], scale: [n_slots]}.
# ---------------------------------------------------------------------------


def lora_delta(
    x: jax.Array,          # [B, S, d_in] or [B, d_in]
    a: jax.Array,          # [n_slots, d_in, r]
    b: jax.Array,          # [n_slots, r, d_out]
    scale: jax.Array,      # [n_slots]
    slot_ids: jax.Array,   # [B] int32, -1 = no adapter
) -> jax.Array:
    """Per-row multi-adapter delta: scale[s] * (x @ a[s]) @ b[s], s=slot_ids[row].

    Implemented with a one-hot mix instead of gather: ``one_hot`` rows for
    slot -1 are all-zero, giving an exact 0 delta for base-model rows, and the
    mixing contraction is a small matmul the MXU handles natively (n_slots is
    4-8; the r-rank matmuls dominate and stay tiny).
    """
    n_slots = a.shape[0]
    onehot = jax.nn.one_hot(slot_ids, n_slots, dtype=x.dtype)  # [B, n_slots]
    a_sel = jnp.einsum("bs,sir->bir", onehot, a)  # [B, d_in, r]
    b_sel = jnp.einsum("bs,sro->bro", onehot, b)  # [B, r, d_out]
    s_sel = (onehot.astype(jnp.float32) @ scale).astype(x.dtype)  # [B]
    if x.ndim == 3:
        mid = jnp.einsum("bsi,bir->bsr", x, a_sel)
        delta = jnp.einsum("bsr,bro->bso", mid, b_sel)
        return delta * s_sel[:, None, None]
    mid = jnp.einsum("bi,bir->br", x, a_sel)
    delta = jnp.einsum("br,bro->bo", mid, b_sel)
    return delta * s_sel[:, None]


def layer_slice(bufs: dict[str, Any], layer: jax.Array | int) -> dict[str, Any]:
    """Per-layer view for use inside lax.scan over layers."""
    out = {"scale": bufs["scale"]}
    for t in TARGETS:
        out[f"{t}_a"] = jax.lax.dynamic_index_in_dim(
            bufs[f"{t}_a"], layer, axis=0, keepdims=False
        )
        out[f"{t}_b"] = jax.lax.dynamic_index_in_dim(
            bufs[f"{t}_b"], layer, axis=0, keepdims=False
        )
    return out


def stack_for_scan(bufs: dict[str, Any]) -> tuple[dict[str, Any], dict[str, Any]]:
    """Split buffers into (per-layer stacked pytree, broadcast pytree) for scan."""
    per_layer = {k: v for k, v in bufs.items() if k != "scale"}
    broadcast = {"scale": bufs["scale"]}
    return per_layer, broadcast
