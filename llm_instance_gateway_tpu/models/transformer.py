"""Core decoder-only transformer: one implementation, three families.

Covers Llama-3 (RoPE+GQA+SwiGLU), Gemma (tied embeddings, sqrt(d) embedding
scale, GeLU gate, (1+w) RMSNorm, shared KV head) and Mixtral (top-k MoE MLP)
via ``ModelConfig`` flags — the families the pool configs in BASELINE.json
serve.

TPU-first structure:
- Parameters are stacked over layers (``[n_layers, ...]`` leaves) and the
  forward runs ``lax.scan`` over them: one layer gets traced/compiled once,
  not n_layers times, and pjit shards every layer identically.
- The decode path is a fixed-shape step function: batch = the engine's decode
  slots, cache = ``[n_layers, B, S_max, n_kv, hd]``; one compilation serves
  the entire serving lifetime (XLA recompile storms are the TPU-serving
  failure mode the design avoids, SURVEY.md §7).
- bfloat16 params/activations, f32 softmax/norms, f32 logits.
- Multi-LoRA deltas (``models.lora``) apply to every projection with per-row
  slot ids, so one decode batch multiplexes adapters + base model.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from llm_instance_gateway_tpu.models import lora as lora_lib
from llm_instance_gateway_tpu.models.configs import ModelConfig
from llm_instance_gateway_tpu.ops.attention import (
    decode_attention,
    prefill_attention,
    xla_chunk_attention,
)
from llm_instance_gateway_tpu.ops.layers import apply_rope, rms_norm, swiglu
from llm_instance_gateway_tpu.ops.quant import (
    expert_matmul,
    expert_mix,
    expert_mix_down,
    matmul as q_matmul,
)

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    hd = cfg.resolved_head_dim
    d, f, v = cfg.d_model, cfg.d_ff, cfg.padded_vocab
    n_l = cfg.n_layers
    keys = iter(jax.random.split(key, 16))

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(dtype)

    layers: Params = {
        "attn_norm": jnp.ones((n_l, d), dtype),
        "mlp_norm": jnp.ones((n_l, d), dtype),
        "wq": dense(next(keys), (n_l, d, cfg.n_heads * hd), d),
        "wk": dense(next(keys), (n_l, d, cfg.n_kv_heads * hd), d),
        "wv": dense(next(keys), (n_l, d, cfg.n_kv_heads * hd), d),
        "wo": dense(next(keys), (n_l, cfg.n_heads * hd, d), cfg.n_heads * hd),
    }
    if cfg.attention_bias:
        # Qwen2-family Q/K/V biases (zero init; checkpoints overwrite).
        layers["wq_b"] = jnp.zeros((n_l, cfg.n_heads * hd), dtype)
        layers["wk_b"] = jnp.zeros((n_l, cfg.n_kv_heads * hd), dtype)
        layers["wv_b"] = jnp.zeros((n_l, cfg.n_kv_heads * hd), dtype)
    if cfg.n_experts:
        e = cfg.n_experts
        layers["router"] = dense(next(keys), (n_l, d, e), d)
        layers["w_gate"] = dense(next(keys), (n_l, e, d, f), d)
        layers["w_up"] = dense(next(keys), (n_l, e, d, f), d)
        layers["w_down"] = dense(next(keys), (n_l, e, f, d), f)
    else:
        layers["w_gate"] = dense(next(keys), (n_l, d, f), d)
        layers["w_up"] = dense(next(keys), (n_l, d, f), d)
        layers["w_down"] = dense(next(keys), (n_l, f, d), f)

    params: Params = {
        "embed": (jax.random.normal(next(keys), (v, d), jnp.float32) * 0.02).astype(dtype),
        "layers": layers,
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, v), d)
    return params


def init_decode_cache(
    cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16,
    quantized: bool = False,
) -> Params:
    """Contiguous-lane decode cache.  ``quantized`` stores K/V as int8 with
    per-(position, kv-head) f32 scales — decode at long context is bound by
    streaming the KV from HBM, and int8 halves that traffic vs bf16 (the
    JetStream serving trade); the dequantize multiply fuses into the
    attention reads, so HBM sees int8 while the MXU computes in ``dtype``.
    Scale overhead is 1/(2*head_dim) of the bf16 cache."""
    hd = cfg.resolved_head_dim
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, hd)
    cache = {
        "k": jnp.zeros(shape, jnp.int8 if quantized else dtype),
        "v": jnp.zeros(shape, jnp.int8 if quantized else dtype),
        "length": jnp.zeros((batch,), jnp.int32),
    }
    if quantized:
        cache["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
    return cache


def _kv_quantize(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., hd] -> (int8 [..., hd], f32 scale [...]): symmetric per-vector
    max-abs quantization (one scale per position per kv-head)."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def _kv_dequantize(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    return q.astype(dtype) * s[..., None].astype(dtype)


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


def _project(x, w, layer_lora, target, slot_ids):
    """x @ w plus the per-row LoRA delta for ``target`` (w may be int8)."""
    out = q_matmul(x, w)
    if layer_lora is not None:
        out = out + lora_lib.lora_delta(
            x,
            layer_lora[f"{target}_a"],
            layer_lora[f"{target}_b"],
            layer_lora["scale"],
            slot_ids,
        )
    return out


def _attn_proj(lp, target, x, layer_lora, slot_ids):
    """Q/K/V projection with the optional attention bias (Qwen2-family:
    ``attention_bias`` adds learned biases to q/k/v only).  The bias keys
    exist in the layer params iff the config declares them, so bias-free
    models trace exactly the code they always did."""
    out = _project(x, lp[f"w{target}"], layer_lora, target, slot_ids)
    b = lp.get(f"w{target}_b")
    return out if b is None else out + b


def _chunk_attend(cfg: ModelConfig, quant: bool, q, lane_k, lane_v, start):
    """Chunk-vs-lane attention dispatch, shared by the lane and paged
    chunk-stream paths.  Flash-style kernel (auto XLA fallback off-TPU/odd
    shapes) unless the lane was dequantized from an int8 cache — an opaque
    kernel can't fuse the dequant into its reads and would materialize a
    bf16 copy, so quantized lanes keep the fused XLA path (same reasoning
    as the decode-path quant gate).  Returns [1, C, H*hd]."""
    c = q.shape[1]
    if cfg.use_flash_attention and not quant:
        from llm_instance_gateway_tpu.ops.pallas_attention import (
            chunk_attention,
        )

        return chunk_attention(q, lane_k[None], lane_v[None],
                               start).reshape(1, c, -1)
    return xla_chunk_attention(q, lane_k[None], lane_v[None],
                               start).reshape(1, c, -1)


def _mlp(cfg: ModelConfig, lp: Params, x, layer_lora, slot_ids):
    if cfg.n_experts:
        return _moe_mlp(cfg, lp, x)
    gate = _project(x, lp["w_gate"], layer_lora, "gate", slot_ids)
    up = _project(x, lp["w_up"], layer_lora, "up", slot_ids)
    return _project(swiglu(gate, up, cfg.gelu_mlp), lp["w_down"], layer_lora, "down", slot_ids)


def _moe_mlp(cfg: ModelConfig, lp: Params, x):
    """Top-k mixture-of-experts MLP (Mixtral style).

    Two shape-static strategies, chosen at TRACE time by token count:

    - a batch too small for the capacity tile to beat dense (cap >= T —
      single-token decode): dense all-experts mix, where the dispatch
      bookkeeping would be pure overhead and weights (not FLOPs) bound
      the step anyway;
    - everything else — batched decode included: GShard-style grouped
      capacity dispatch (``_moe_grouped``) — per-token FLOPs drop from E
      to ~k*capacity_factor expert-MLPs, with a dense lax.cond fallback
      keeping results bit-exact when routing overflows capacity.

    LoRA is not applied to expert weights (matching vLLM, which targets
    attention + dense MLP only).
    """
    t = 1
    for dim in x.shape[:-1]:
        t *= dim
    cap = _moe_capacity(cfg, t)
    if cap >= t or (cfg.moe_exact_fallback and cap < 8):
        # Dense all-experts costs t*E expert-rows; grouped costs E*cap.
        # cap >= t means no FLOP win — and at these token counts decode is
        # weight-bound anyway (each expert's weights stream from HBM once
        # either way), so the dispatch bookkeeping would be pure overhead.
        # Exact mode additionally floors at cap >= 8: a 1-4 row tile is
        # discreteness-dominated (routine routing collisions overflow it —
        # the 2x headroom's overflow-rarity argument needs a few rows of
        # mean load), and every exact-mode overflow pays grouped PLUS
        # dense, costlier than just staying dense.  Dropping mode keeps
        # grouped at any tile (overflow drops, the standard serving trade).
        return _moe_dense(cfg, lp, x)
    return _moe_grouped(cfg, lp, x)


def _moe_capacity(cfg: ModelConfig, t: int) -> int:
    """Per-expert capacity tile for ``t`` tokens.

    cap ≈ t*k/E * factor.  Dropping mode uses the configured factor as-is
    (1.25 default — the standard GShard serving trade: a 16-slot Mixtral
    decode computes ~1.25x the dropless-ideal t*k expert-rows); EXACT mode
    takes at least 2.0x at every size, because its overflow fallback pays
    grouped PLUS dense for the batch and a tight tile overflows on routine
    router imbalance (still ~2x better than the dense path it falls back
    to).  Small tiles keep the exact ceiling — rounding 5 up to 8 would
    re-inflate the small-batch win; large tiles round up to a multiple of
    8 (MXU sublane alignment).
    """
    e, k = cfg.n_experts, cfg.n_experts_per_token
    f = cfg.moe_capacity_factor
    if cfg.moe_exact_fallback:
        # The overflow fallback pays grouped PLUS dense (expert weights
        # streamed twice), so it must stay rare at EVERY tile size — a
        # 16-slot decode tile at 1.25x mean load would overflow on most
        # batches.  2.0x puts overflow ~2.7 sigma out under uniform
        # routing; dropping mode uses the configured factor as-is.
        f = max(f, 2.0)
    cap = int(-(-t * k * f // e))
    if cap >= 16:
        cap = (cap + 7) // 8 * 8
    return min(t, cap)


def _moe_dense(cfg: ModelConfig, lp: Params, x):
    """Compute every expert; mix by renormalized top-k gates."""
    router_logits = (x @ lp["router"]).astype(jnp.float32)  # [..., E]
    e = cfg.n_experts
    topv, topi = jax.lax.top_k(router_logits, cfg.n_experts_per_token)
    gates = jax.nn.softmax(topv, axis=-1)  # renormalize over selected experts
    # Scatter gate weights back to a dense [..., E] mix vector.
    dense_gates = jnp.sum(
        jax.nn.one_hot(topi, e, dtype=jnp.float32) * gates[..., None], axis=-2
    )  # [..., E]
    hidden = expert_mix(x, lp["w_gate"])
    up = expert_mix(x, lp["w_up"])
    act = swiglu(hidden, up, cfg.gelu_mlp)
    per_expert = expert_mix_down(act, lp["w_down"])
    return jnp.einsum("...ed,...e->...d", per_expert, dense_gates.astype(x.dtype))


def _moe_grouped(cfg: ModelConfig, lp: Params, x):
    """Grouped capacity dispatch: route tokens TO experts instead of running
    every expert over every token.

    Each token's k assignments scatter-add into per-expert capacity tiles
    ([E, C, D], O(T*k*D) data movement — NOT a [T,k,E,C] one-hot einsum,
    whose T*k*E*C*D cost would swamp the savings); three batched einsums
    run each expert's MLP over its C-row tile (MXU-shaped, shardable over
    the ``expert`` mesh axis); a gather + gate-weighted sum combines
    results.  Expert capacity C ≈ T*k/E * capacity_factor (``_moe_capacity``
    — exact ceiling for small tiles, multiple of 8 with exact-mode headroom
    for large ones): expert FLOPs scale with assignments made, not
    experts*tokens —
    the E/k inflation of the dense path is gone.  If any expert overflows
    C, ``moe_exact_fallback`` recomputes the batch densely inside lax.cond
    (exactness over speed for that batch).
    """
    orig_shape = x.shape
    d = orig_shape[-1]
    xf = x.reshape(-1, d)
    t = xf.shape[0]
    e, k = cfg.n_experts, cfg.n_experts_per_token

    router_logits = (xf @ lp["router"]).astype(jnp.float32)  # [T, E]
    topv, topi = jax.lax.top_k(router_logits, k)
    gates = jax.nn.softmax(topv, axis=-1)  # [T, k]

    cap = _moe_capacity(cfg, t)

    flat_expert = topi.reshape(-1)  # [T*k]
    flat_assign = jax.nn.one_hot(flat_expert, e, dtype=jnp.int32)  # [T*k, E]
    # Position of each assignment within its expert's capacity tile.
    pos = jnp.sum((jnp.cumsum(flat_assign, axis=0) - 1) * flat_assign, axis=-1)
    kept = pos < cap  # [T*k]
    # Overflowed assignments clip onto the last tile row with a zeroed
    # contribution — collisions there add 0, and the combine gather masks
    # them out the same way.
    flat_idx = flat_expert * cap + jnp.clip(pos, 0, cap - 1)  # [T*k]
    keep_col = kept[:, None].astype(xf.dtype)

    xk = jnp.repeat(xf, k, axis=0)  # [T*k, D] (token order matches topi)
    x_e = (
        jnp.zeros((e * cap, d), xf.dtype)
        .at[flat_idx].add(xk * keep_col)
        .reshape(e, cap, d)
    )
    hidden = expert_matmul(x_e, lp["w_gate"])
    up = expert_matmul(x_e, lp["w_up"])
    act = swiglu(hidden, up, cfg.gelu_mlp)
    out_e = expert_matmul(act, lp["w_down"])
    gathered = out_e.reshape(e * cap, d)[flat_idx] * keep_col  # [T*k, D]
    y = jnp.sum(
        gathered.reshape(t, k, d) * gates.astype(xf.dtype)[..., None], axis=1
    )

    if cfg.moe_exact_fallback:
        overflow = jnp.any(~kept)
        y = jax.lax.cond(
            overflow, lambda op: _moe_dense(cfg, lp, op), lambda _: y, xf
        )
    return y.reshape(orig_shape)


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------


def prefill_layer(
    cfg: ModelConfig,
    lp: Params,              # one layer's params (leaves without the L dim)
    h: jax.Array,            # [B, S, D]
    positions: jax.Array,    # [B, S] int32
    layer_lora: Params | None = None,
    slot_ids: jax.Array | None = None,  # [B] int32, -1 = base model
    attention_fn=None,
):
    """One decoder block over a full sequence.  Returns (h, (k, v)).

    The single source of truth for the prefill block: ``prefill`` scans it
    over the stacked layer params, and ``parallel.pipeline`` scans each
    stage's slice of the stack inside the pipelined schedule.
    """
    b, s, _ = h.shape
    if slot_ids is None:
        slot_ids = jnp.full((b,), -1, jnp.int32)
    hn = rms_norm(h, lp["attn_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    hd = cfg.resolved_head_dim
    q = _attn_proj(lp, "q", hn, layer_lora, slot_ids).reshape(b, s, cfg.n_heads, hd)
    k = _attn_proj(lp, "k", hn, layer_lora, slot_ids).reshape(b, s, cfg.n_kv_heads, hd)
    v = _attn_proj(lp, "v", hn, layer_lora, slot_ids).reshape(b, s, cfg.n_kv_heads, hd)
    q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    if attention_fn is not None:
        attn = attention_fn(q, k, v, positions)
    elif cfg.use_flash_attention:
        # Right-padded batches: causal tiling alone keeps real positions
        # exact (pallas_attention.flash_attention docstring).
        from llm_instance_gateway_tpu.ops.pallas_attention import flash_attention

        attn = flash_attention(q, k, v)
    else:
        attn = prefill_attention(q, k, v, positions)
    h = h + _project(attn.reshape(b, s, -1), lp["wo"], layer_lora, "o", slot_ids)
    hn2 = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    h = h + _mlp(cfg, lp, hn2, layer_lora, slot_ids)
    return h, (k, v)


def prefill(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,       # [B, S] int32
    positions: jax.Array,    # [B, S] int32 (right-padded prompts: 0..len-1)
    lora_bufs: Params | None = None,
    slot_ids: jax.Array | None = None,  # [B] int32, -1 = base model
    attention_fn=None,       # override: (q, k, v, positions) -> attn output
):
    """Full-prompt forward.  Returns (logits [B,S,V] f32, k [L,B,S,K,hd], v).

    ``attention_fn`` swaps the attention implementation — used by
    ``parallel.long_context`` to run ring attention over a sequence-sharded
    mesh for prompts that exceed one device's budget.
    """
    b, s = tokens.shape
    if slot_ids is None:
        slot_ids = jnp.full((b,), -1, jnp.int32)
    h = params["embed"][tokens]  # activation dtype follows param dtype
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)

    per_layer_lora = None
    if lora_bufs is not None:
        per_layer_lora, bcast = lora_lib.stack_for_scan(lora_bufs)

    def layer_fn(h, xs):
        lp, ll = xs
        layer_lora = None if ll is None else {**ll, "scale": lora_bufs["scale"]}
        return prefill_layer(
            cfg, lp, h, positions, layer_lora=layer_lora, slot_ids=slot_ids,
            attention_fn=attention_fn,
        )

    xs = (params["layers"], per_layer_lora)
    h, (k_all, v_all) = jax.lax.scan(layer_fn, h, xs)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = q_matmul(h, head).astype(jnp.float32)
    return logits, k_all, v_all


# ---------------------------------------------------------------------------
# Decode step
# ---------------------------------------------------------------------------


def decode_step(
    cfg: ModelConfig,
    params: Params,
    cache: Params,           # init_decode_cache layout
    tokens: jax.Array,       # [B] int32 — current token per slot
    positions: jax.Array,    # [B] int32 — position of ``tokens``
    lora_bufs: Params | None = None,
    slot_ids: jax.Array | None = None,
    attention_fn=None,       # override: (q, k_cache, v_cache, lengths) -> attn
    active: jax.Array | None = None,  # [B] bool — rows allowed to WRITE
):
    """One decode step for every slot.  Returns (logits [B,V] f32, new cache).

    Inactive slots decode garbage LOGITS (masked out by the engine) but —
    with ``active`` given — write NOTHING: their scatter index is pushed
    out of bounds, where XLA drops the update.  Without the mask a frozen
    or empty row keeps stomping its lane at a stale position, which is
    fatal once a lane can be mid-chunk-stream for a DIFFERENT request
    while decode dispatches run (the concurrent-lane engine); lockstep
    batching keeps the step shape-static either way.

    ``attention_fn`` swaps the cached-attention implementation — used by
    ``ops.sharded_attention`` to run the Pallas decode kernel shard-local
    under a GSPMD mesh.
    """
    b = tokens.shape[0]
    if slot_ids is None:
        slot_ids = jnp.full((b,), -1, jnp.int32)
    h = params["embed"][tokens]  # [B, D]; activation dtype follows param dtype
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)

    per_layer_lora = None
    if lora_bufs is not None:
        per_layer_lora, _ = lora_lib.stack_for_scan(lora_bufs)

    lengths = positions + 1
    batch_idx = jnp.arange(b)
    s_max = cache["k"].shape[2]
    # Scatter address only — rope/masks keep the true positions.  s_max is
    # out of bounds, so inactive rows' updates are dropped whole.
    write_pos = (positions if active is None
                 else jnp.where(active, positions, s_max))
    quant = "k_scale" in cache

    def layer_fn(h, xs):
        if quant:
            lp, ll, k_cache, v_cache, k_scale, v_scale = xs
        else:
            lp, ll, k_cache, v_cache = xs
            k_scale = v_scale = None
        layer_lora = None if ll is None else {**ll, "scale": lora_bufs["scale"]}
        hn = rms_norm(h, lp["attn_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        hd = cfg.resolved_head_dim
        q = _attn_proj(lp, "q", hn, layer_lora, slot_ids).reshape(b, cfg.n_heads, hd)
        k = _attn_proj(lp, "k", hn, layer_lora, slot_ids).reshape(b, cfg.n_kv_heads, hd)
        v = _attn_proj(lp, "v", hn, layer_lora, slot_ids).reshape(b, cfg.n_kv_heads, hd)
        q = apply_rope(q[:, None], positions[:, None], cfg.rope_theta, cfg.rope_scaling)[:, 0]
        k = apply_rope(k[:, None], positions[:, None], cfg.rope_theta, cfg.rope_scaling)[:, 0]
        if quant:
            kq, ks = _kv_quantize(k)
            vq, vs = _kv_quantize(v)
            k_cache = k_cache.at[batch_idx, write_pos].set(kq)
            v_cache = v_cache.at[batch_idx, write_pos].set(vq)
            k_scale = k_scale.at[batch_idx, write_pos].set(ks)
            v_scale = v_scale.at[batch_idx, write_pos].set(vs)
            if getattr(attention_fn, "quant_aware", False):
                # Quant-aware override (sharded_attention.make_cached_
                # decode_quant): raw int8 + scales go in; each shard's
                # kernel dequantizes in VMEM, so HBM streams int8 even
                # under the mesh — kernel win and bandwidth win together.
                attn = attention_fn(
                    q, k_cache, v_cache, k_scale, v_scale, lengths)
            elif attention_fn is not None:
                # Opaque override without quant awareness: hand it the
                # dequantized view.  NOTE — such an override cannot fuse
                # the dequant into its reads and materializes a full bf16
                # cache; the engine only installs quant_aware wrappers on
                # quantized lanes for exactly that reason.
                attn = attention_fn(
                    q, _kv_dequantize(k_cache, k_scale, h.dtype),
                    _kv_dequantize(v_cache, v_scale, h.dtype), lengths)
            elif cfg.use_pallas_decode:
                from llm_instance_gateway_tpu.ops.pallas_decode_attention import (
                    decode_attention_quant,
                )

                # int8-aware kernel: dequantizes in VMEM at the MXU feed,
                # so HBM streams half the bytes of the bf16 kernel (auto
                # XLA fallback off-TPU / unsupported shapes).
                attn = decode_attention_quant(
                    q, k_cache, v_cache, k_scale, v_scale, lengths)
            else:
                attn = decode_attention(
                    q, _kv_dequantize(k_cache, k_scale, h.dtype),
                    _kv_dequantize(v_cache, v_scale, h.dtype), lengths)
            carry_out = (k_cache, v_cache, k_scale, v_scale)
        else:
            k_cache = k_cache.at[batch_idx, write_pos].set(k)
            v_cache = v_cache.at[batch_idx, write_pos].set(v)
            if attention_fn is not None:
                attn = attention_fn(q, k_cache, v_cache, lengths)
            elif cfg.use_pallas_decode:
                from llm_instance_gateway_tpu.ops.pallas_decode_attention import (
                    decode_attention as pallas_decode,
                )

                attn = pallas_decode(q, k_cache, v_cache, lengths)
            else:
                attn = decode_attention(q, k_cache, v_cache, lengths)
            carry_out = (k_cache, v_cache)
        h = h + _project(attn.reshape(b, -1), lp["wo"], layer_lora, "o", slot_ids)
        hn2 = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        h = h + _mlp(cfg, lp, hn2, layer_lora, slot_ids)
        return h, carry_out

    xs = (params["layers"], per_layer_lora, cache["k"], cache["v"])
    if quant:
        xs = xs + (cache["k_scale"], cache["v_scale"])
    h, carry = jax.lax.scan(layer_fn, h, xs)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = q_matmul(h, head).astype(jnp.float32)
    new_cache = {"k": carry[0], "v": carry[1], "length": lengths}
    if quant:
        new_cache["k_scale"], new_cache["v_scale"] = carry[2], carry[3]
    return logits, new_cache


def extend_step(
    cfg: ModelConfig,
    params: Params,
    cache: Params,           # init_decode_cache layout
    tokens: jax.Array,       # [B, C] int32 — C new tokens per slot
    positions: jax.Array,    # [B, C] int32 — absolute positions of each
    lora_bufs: Params | None = None,
    slot_ids: jax.Array | None = None,
    active: jax.Array | None = None,  # [B] bool — rows allowed to WRITE
):
    """Multi-token cached decode: process C new tokens per slot in ONE
    forward (the speculative-decoding verify/catch-up primitive — decode is
    HBM-weight-bound, so scoring C tokens costs barely more than one).

    Each row's tokens scatter into its own cache lane at ``positions`` and
    attend to every cached position <= their own — causal within the new
    tokens and over the lane's history.  Rows are independent; garbage rows
    (frozen slots) decode garbage logits exactly like ``decode_step`` —
    and, with ``active`` given, write nothing (out-of-bounds scatter
    address, update dropped): a frozen row's lane may already belong to a
    mid-stream chunk prompt.  Returns (logits [B, C, V] f32, new cache) —
    logits[i] is the next-token distribution AFTER tokens[:, i].
    """
    b, c = tokens.shape
    hd = cfg.resolved_head_dim
    s_max = cache["k"].shape[2]
    if slot_ids is None:
        slot_ids = jnp.full((b,), -1, jnp.int32)
    h = params["embed"][tokens]  # [B, C, D]
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)

    per_layer_lora = None
    if lora_bufs is not None:
        per_layer_lora, _ = lora_lib.stack_for_scan(lora_bufs)

    batch_idx = jnp.arange(b)[:, None]  # [B, 1] broadcast over C
    write_pos = (positions if active is None
                 else jnp.where(active[:, None], positions, s_max))
    quant = "k_scale" in cache

    def layer_fn(h, xs):
        if quant:
            lp, ll, k_cache, v_cache, k_scale, v_scale = xs
        else:
            lp, ll, k_cache, v_cache = xs
            k_scale = v_scale = None
        layer_lora = None if ll is None else {**ll, "scale": lora_bufs["scale"]}
        hn = rms_norm(h, lp["attn_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        q = _attn_proj(lp, "q", hn, layer_lora, slot_ids).reshape(
            b, c, cfg.n_heads, hd)
        k = _attn_proj(lp, "k", hn, layer_lora, slot_ids).reshape(
            b, c, cfg.n_kv_heads, hd)
        v = _attn_proj(lp, "v", hn, layer_lora, slot_ids).reshape(
            b, c, cfg.n_kv_heads, hd)
        q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        if quant:
            kq, ks = _kv_quantize(k)
            vq, vs = _kv_quantize(v)
            k_cache = k_cache.at[batch_idx, write_pos].set(kq)
            v_cache = v_cache.at[batch_idx, write_pos].set(vq)
            k_scale = k_scale.at[batch_idx, write_pos].set(ks)
            v_scale = v_scale.at[batch_idx, write_pos].set(vs)
            k_read = _kv_dequantize(k_cache, k_scale, h.dtype)
            v_read = _kv_dequantize(v_cache, v_scale, h.dtype)
            carry_out = (k_cache, v_cache, k_scale, v_scale)
        else:
            k_cache = k_cache.at[batch_idx, write_pos].set(k)
            v_cache = v_cache.at[batch_idx, write_pos].set(v)
            k_read, v_read = k_cache, v_cache
            carry_out = (k_cache, v_cache)
        # [B,C,K,G,hd] x [B,S,K,hd] -> [B,K,G,C,S]; mask j <= position_i.
        qg = q.reshape(b, c, cfg.n_kv_heads, cfg.q_per_kv, hd)
        logits = jnp.einsum(
            "bikgh,bjkh->bkgij", qg, k_read,
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(hd).astype(jnp.float32)
        mask = jnp.arange(s_max)[None, None, :] <= positions[:, :, None]
        logits = jnp.where(mask[:, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(h.dtype)
        attn = jnp.einsum("bkgij,bjkh->bikgh", probs, v_read).reshape(b, c, -1)
        h = h + _project(attn, lp["wo"], layer_lora, "o", slot_ids)
        hn2 = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        h = h + _mlp(cfg, lp, hn2, layer_lora, slot_ids)
        return h, carry_out

    xs = (params["layers"], per_layer_lora, cache["k"], cache["v"])
    if quant:
        xs = xs + (cache["k_scale"], cache["v_scale"])
    h, carry = jax.lax.scan(layer_fn, h, xs)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = q_matmul(h, head).astype(jnp.float32)
    new_cache = {"k": carry[0], "v": carry[1],
                 "length": positions[:, -1] + 1}
    if quant:
        new_cache["k_scale"], new_cache["v_scale"] = carry[2], carry[3]
    return logits, new_cache


def prefill_with_cache(
    cfg: ModelConfig,
    params: Params,
    cache: Params,
    tokens: jax.Array,      # [C] int32 — one chunk for ONE slot
    positions: jax.Array,   # [C] int32 — absolute positions of the chunk
    slot: jax.Array,        # scalar int32 — cache lane
    lane_end: jax.Array,    # scalar int32 — valid tokens in the lane AFTER
                            # this chunk (true prompt progress, excl. padding)
    last_index: jax.Array,  # scalar int32 — chunk index of the last REAL token
    lora_bufs: Params | None = None,
    lora_slot: jax.Array | int = -1,
):
    """Chunked prefill: run one prompt chunk against an existing cache lane.

    Long prompts stream through in fixed-size chunks: each chunk's K/V are
    scattered into the slot's cache rows at their absolute positions, and the
    chunk's queries attend to EVERYTHING cached so far (previous chunks) plus
    causally within the chunk — so N chunks reproduce a monolithic prefill
    exactly (parity-tested) while compiling only one chunk-sized program.

    A padded final chunk passes pad positions CONTINUING past the prompt
    (start+i): pads scatter into unused cells beyond ``lane_end`` (masked by
    the cache length) instead of overwriting real tokens, and ``last_index``
    selects the true final token's logits.

    Returns (last_logits [V] f32, new cache).
    """
    c = tokens.shape[0]
    hd = cfg.resolved_head_dim
    s_max = cache["k"].shape[2]
    slot_ids = jnp.full((1,), lora_slot, jnp.int32)

    per_layer_lora = None
    if lora_bufs is not None:
        per_layer_lora, _ = lora_lib.stack_for_scan(lora_bufs)

    h = params["embed"][tokens][None]  # [1, C, D]
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)
    pos2d = positions[None]  # [1, C]
    quant = "k_scale" in cache

    def layer_fn(h, xs):
        if quant:
            lp, ll, k_cache, v_cache, k_scale, v_scale = xs
        else:
            lp, ll, k_cache, v_cache = xs  # caches: [B, S, K, hd] (layer)
            k_scale = v_scale = None
        layer_lora = None if ll is None else {**ll, "scale": lora_bufs["scale"]}
        hn = rms_norm(h, lp["attn_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        q = _attn_proj(lp, "q", hn, layer_lora, slot_ids).reshape(1, c, cfg.n_heads, hd)
        k = _attn_proj(lp, "k", hn, layer_lora, slot_ids).reshape(1, c, cfg.n_kv_heads, hd)
        v = _attn_proj(lp, "v", hn, layer_lora, slot_ids).reshape(1, c, cfg.n_kv_heads, hd)
        q = apply_rope(q, pos2d, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, pos2d, cfg.rope_theta, cfg.rope_scaling)
        # Scatter the chunk's K/V into the slot's lane at absolute positions.
        if quant:
            kq, ks = _kv_quantize(k[0])
            vq, vs = _kv_quantize(v[0])
            k_cache = k_cache.at[slot, positions].set(kq)
            v_cache = v_cache.at[slot, positions].set(vq)
            k_scale = k_scale.at[slot, positions].set(ks)
            v_scale = v_scale.at[slot, positions].set(vs)
            lane_k = _kv_dequantize(
                jax.lax.dynamic_index_in_dim(k_cache, slot, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(k_scale, slot, 0, keepdims=False),
                h.dtype)
            lane_v = _kv_dequantize(
                jax.lax.dynamic_index_in_dim(v_cache, slot, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(v_scale, slot, 0, keepdims=False),
                h.dtype)
            carry_out = (k_cache, v_cache, k_scale, v_scale)
        else:
            k_cache = k_cache.at[slot, positions].set(k[0])
            v_cache = v_cache.at[slot, positions].set(v[0])
            # Chunk queries vs the whole lane, masked to index <= q position.
            lane_k = jax.lax.dynamic_index_in_dim(k_cache, slot, 0, keepdims=False)
            lane_v = jax.lax.dynamic_index_in_dim(v_cache, slot, 0, keepdims=False)
            carry_out = (k_cache, v_cache)
        # Flash-style chunk attend: no [C, S_max] logits materialize, and
        # K blocks past the chunk's reach elide their DMAs — bandwidth
        # tracks the prompt's progress, not S_max (_chunk_attend).
        attn = _chunk_attend(cfg, quant, q, lane_k, lane_v, positions[0])
        h = h + _project(attn, lp["wo"], layer_lora, "o", slot_ids)
        hn2 = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        h = h + _mlp(cfg, lp, hn2, layer_lora, slot_ids)
        return h, carry_out

    xs = (params["layers"], per_layer_lora, cache["k"], cache["v"])
    if quant:
        xs = xs + (cache["k_scale"], cache["v_scale"])
    h, carry = jax.lax.scan(layer_fn, h, xs)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    last_h = jax.lax.dynamic_index_in_dim(h[0], last_index, 0, keepdims=False)
    last_logits = q_matmul(last_h, head).astype(jnp.float32)
    length_vec = cache["length"].at[slot].set(lane_end)
    out_cache = {"k": carry[0], "v": carry[1], "length": length_vec}
    if quant:
        out_cache["k_scale"], out_cache["v_scale"] = carry[2], carry[3]
    return last_logits, out_cache


def insert_prefill(
    cache: Params,
    k_prompt: jax.Array,  # [L, 1, S, K, hd] from prefill
    v_prompt: jax.Array,
    slot: jax.Array | int,
    length: jax.Array | int,
) -> Params:
    """Insert a prefilled sequence's KV into a decode slot (JetStream-style
    prefill->insert->generate).  ``length`` is the true prompt length; the
    padded tail beyond it is garbage but masked by ``cache['length']``.
    """
    k = cache["k"]
    v = cache["v"]
    if "k_scale" in cache:
        kq, ks = _kv_quantize(k_prompt)  # [L,1,S,K,hd] -> scales [L,1,S,K]
        vq, vs = _kv_quantize(v_prompt)
        k = jax.lax.dynamic_update_slice(k, kq, (0, slot, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, vq, (0, slot, 0, 0, 0))
        k_scale = jax.lax.dynamic_update_slice(
            cache["k_scale"], ks, (0, slot, 0, 0))
        v_scale = jax.lax.dynamic_update_slice(
            cache["v_scale"], vs, (0, slot, 0, 0))
        length_vec = cache["length"].at[slot].set(length)
        return {"k": k, "v": v, "k_scale": k_scale, "v_scale": v_scale,
                "length": length_vec}
    k = jax.lax.dynamic_update_slice(k, k_prompt.astype(k.dtype), (0, slot, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(v, v_prompt.astype(v.dtype), (0, slot, 0, 0, 0))
    length_vec = cache["length"].at[slot].set(length)
    return {"k": k, "v": v, "length": length_vec}
