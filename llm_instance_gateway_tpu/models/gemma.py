"""Gemma family entry points (2B/7B): tied embeddings, sqrt(d) embedding
scale, (1+w) RMSNorm, GeLU gating, MQA (2B) — all handled by config flags in
``models.transformer``.  BASELINE.json's smoke-test config is Gemma-2B on
v5e-1.
"""

from __future__ import annotations

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import GEMMA_2B, GEMMA_7B

CONFIGS = {
    "gemma-2b": GEMMA_2B,
    "gemma-7b": GEMMA_7B,
    "gemma-tiny": GEMMA_2B.tiny(),
}

init_params = transformer.init_params
init_decode_cache = transformer.init_decode_cache
insert_prefill = transformer.insert_prefill
prefill = transformer.prefill
decode_step = transformer.decode_step
