"""Paged KV cache: a shared block pool with per-slot block tables.

vLLM's PagedAttention memory model (the signal model the reference's
KV-threshold routing was tuned against — ``backend/vllm/metrics.go:30``
``gpu_cache_usage_perc`` = allocated blocks / total blocks), restated for
TPU/XLA constraints:

- The pool is ONE static array ``[L, n_blocks, block, Kh, hd]`` — shapes
  never depend on allocation state, so the decode step compiles once.
- Per-slot block tables ``[B, max_blocks_per_seq]`` map logical sequence
  blocks to pool blocks.  Allocation/free is host-side (the engine owns a
  free list); the device only ever sees the table contents change.
- Physical block 0 is reserved as the TRASH block: unallocated table
  entries and inactive rows point at it, so scatters stay in-bounds and
  masked-out garbage has a place to land (no dynamic shapes, no dropped
  scatter semantics to reason about).
- The decode read gathers each row's blocks back into a contiguous
  ``[B, S_max, Kh, hd]`` view (one XLA gather per layer) and reuses the
  exact same masked attention as the contiguous-lane path — so lane/paged
  parity is testable token-for-token.

Contiguous lanes (``transformer.init_decode_cache``) remain the default
fast path: they read the same bytes without the gather.  Paging buys
admission by ACTUAL usage — a pool smaller than ``slots x max_seq`` serves
more concurrent short sequences in the same HBM, with usage_perc telling
the gateway the truth about remaining headroom.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from llm_instance_gateway_tpu.models import lora as lora_lib
from llm_instance_gateway_tpu.models.configs import ModelConfig
from llm_instance_gateway_tpu.models.transformer import (
    _attn_proj,
    _chunk_attend,
    _kv_dequantize,
    _kv_quantize,
    _mlp,
    _project,
)
from llm_instance_gateway_tpu.ops.attention import (
    decode_attention,
    gather_pool_rows,
)
from llm_instance_gateway_tpu.ops.layers import apply_rope, rms_norm
from llm_instance_gateway_tpu.ops.quant import matmul as q_matmul

Params = dict[str, Any]

TRASH_BLOCK = 0  # physical block 0: scatter target for inactive/unallocated


def init_paged_cache(
    cfg: ModelConfig,
    batch: int,
    max_len: int,
    n_blocks: int,
    block: int,
    dtype=jnp.bfloat16,
    quantized: bool = False,
) -> Params:
    """Block pool + tables.  ``n_blocks`` EXCLUDES the trash block.

    ``quantized`` stores the pools int8 with per-(position, kv-head) f32
    scale pools (vLLM's quantized-paged-KV composition: the HBM halving
    and the admission-by-actual-usage win stack).  Scales index by the
    same physical block as the data, so prefix-cache block reuse — a table
    repoint, never a copy — carries them for free."""
    hd = cfg.resolved_head_dim
    max_blocks_per_seq = -(-max_len // block)
    shape = (cfg.n_layers, n_blocks + 1, block, cfg.n_kv_heads, hd)
    cache = {
        "k": jnp.zeros(shape, jnp.int8 if quantized else dtype),
        "v": jnp.zeros(shape, jnp.int8 if quantized else dtype),
        "tables": jnp.full((batch, max_blocks_per_seq), TRASH_BLOCK, jnp.int32),
        "length": jnp.zeros((batch,), jnp.int32),
    }
    if quantized:
        cache["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
    return cache


_gather_rows = gather_pool_rows  # canonical def: ops.attention (shared with
                                 # the paged kernel's fallback and tooling)


def _pool_update(pools: tuple, k: jax.Array, v: jax.Array,
                 phys_block: jax.Array, offset: jax.Array) -> tuple:
    """Scatter freshly-computed bf16 K/V into the layer's pool tuple at
    (phys_block, offset) — the ONE write seam every paged step shares.
    A 4-tuple (k, v, k_scale, v_scale) is a quantized pool: values are
    int8-quantized per (position, kv-head) on the way in."""
    if len(pools) == 4:
        k_pool, v_pool, ks_pool, vs_pool = pools
        kq, ks = _kv_quantize(k)
        vq, vs = _kv_quantize(v)
        return (k_pool.at[phys_block, offset].set(kq),
                v_pool.at[phys_block, offset].set(vq),
                ks_pool.at[phys_block, offset].set(ks),
                vs_pool.at[phys_block, offset].set(vs))
    k_pool, v_pool = pools
    return (k_pool.at[phys_block, offset].set(k),
            v_pool.at[phys_block, offset].set(v))


def _pool_rows(pools: tuple, tables: jax.Array, dtype=None) -> tuple:
    """Gather each table row's blocks into the contiguous lane view — the
    ONE read seam.  Quantized pools return (k, v) dequantized when ``dtype``
    is given (XLA fuses the multiply into the attention reads, so HBM still
    streams int8), or raw (k, v, k_scale, v_scale) for the int8-aware
    kernel when it is None."""
    if len(pools) == 4:
        rows = tuple(_gather_rows(p, tables) for p in pools)
        if dtype is None:
            return rows
        return (_kv_dequantize(rows[0], rows[2], dtype),
                _kv_dequantize(rows[1], rows[3], dtype))
    return _gather_rows(pools[0], tables), _gather_rows(pools[1], tables)


def decode_step_paged(
    cfg: ModelConfig,
    params: Params,
    cache: Params,           # init_paged_cache layout
    tokens: jax.Array,       # [B] int32
    positions: jax.Array,    # [B] int32
    lora_bufs: Params | None = None,
    slot_ids: jax.Array | None = None,
    active: jax.Array | None = None,  # [B] bool — rows allowed to WRITE
):
    """One decode step over the paged pool.

    Semantics identical to ``transformer.decode_step`` (parity-tested); the
    only differences are the scatter address (table-mapped block/offset) and
    the gather-then-attend read.  With ``active`` given, inactive rows
    write the trash block instead of their table-mapped cell — a reserved
    row's table may already hold a mid-stream chunk prompt's real blocks.
    """
    b = tokens.shape[0]
    if slot_ids is None:
        slot_ids = jnp.full((b,), -1, jnp.int32)
    block = cache["k"].shape[2]
    tables = cache["tables"]

    h = params["embed"][tokens]
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)

    per_layer_lora = None
    if lora_bufs is not None:
        per_layer_lora, _ = lora_lib.stack_for_scan(lora_bufs)

    lengths = positions + 1
    batch_idx = jnp.arange(b)
    # Physical write address of each row's current position.  Rows whose
    # table entry is unallocated — and rows the engine marked inactive —
    # write the trash block.
    phys_block = tables[batch_idx, positions // block]  # [B]
    if active is not None:
        phys_block = jnp.where(active, phys_block, TRASH_BLOCK)
    offset = positions % block
    quant = "k_scale" in cache

    def layer_fn(h, xs):
        lp, ll, *pools = xs
        layer_lora = None if ll is None else {**ll, "scale": lora_bufs["scale"]}
        hn = rms_norm(h, lp["attn_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        hd = cfg.resolved_head_dim
        q = _attn_proj(lp, "q", hn, layer_lora, slot_ids).reshape(b, cfg.n_heads, hd)
        k = _attn_proj(lp, "k", hn, layer_lora, slot_ids).reshape(b, cfg.n_kv_heads, hd)
        v = _attn_proj(lp, "v", hn, layer_lora, slot_ids).reshape(b, cfg.n_kv_heads, hd)
        q = apply_rope(q[:, None], positions[:, None], cfg.rope_theta, cfg.rope_scaling)[:, 0]
        k = apply_rope(k[:, None], positions[:, None], cfg.rope_theta, cfg.rope_scaling)[:, 0]
        pools = _pool_update(tuple(pools), k, v, phys_block, offset)
        if cfg.use_pallas_decode:
            from llm_instance_gateway_tpu.ops.pallas_decode_attention import (
                paged_decode_attention,
            )

            # DIRECT paged kernel: the block table rides the scalar
            # prefetch and each tile DMAs straight from the pool — no
            # gathered copy of the live cache materializes in HBM (the
            # old read paid gather write + kernel read).  int8 pools
            # stream half the bytes again, scales on the same
            # indirection; its auto-dispatch gathers + falls back off-TPU.
            attn = paged_decode_attention(
                q, pools[0], pools[1], tables, lengths, *pools[2:])
        else:
            attn = decode_attention(
                q, *_pool_rows(pools, tables, h.dtype), lengths)
        h = h + _project(attn.reshape(b, -1), lp["wo"], layer_lora, "o", slot_ids)
        hn2 = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        h = h + _mlp(cfg, lp, hn2, layer_lora, slot_ids)
        return h, pools

    xs = (params["layers"], per_layer_lora, cache["k"], cache["v"])
    if quant:
        xs = xs + (cache["k_scale"], cache["v_scale"])
    h, carry = jax.lax.scan(layer_fn, h, xs)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = q_matmul(h, head).astype(jnp.float32)
    new_cache = {"k": carry[0], "v": carry[1], "tables": tables,
                 "length": lengths}
    if quant:
        new_cache["k_scale"], new_cache["v_scale"] = carry[2], carry[3]
    return logits, new_cache


def extend_step_paged(
    cfg: ModelConfig,
    params: Params,
    cache: Params,           # init_paged_cache layout
    tokens: jax.Array,       # [B, C] int32 — C new tokens per slot
    positions: jax.Array,    # [B, C] int32 — absolute positions of each
    lora_bufs: Params | None = None,
    slot_ids: jax.Array | None = None,
    active: jax.Array | None = None,  # [B] bool — rows allowed to WRITE
):
    """Multi-token cached decode over the paged pool — the speculative
    verify/catch-up primitive (parity contract: ``transformer.extend_step``,
    tested token-for-token).  Each row's C tokens scatter through its block
    table and attend to the row's gathered view, causal within the new
    tokens and over the lane's history.  Positions past the table span —
    and every position of a row the engine marked inactive — route to the
    trash block (same rule as ``prefill_with_cache_paged``).
    Returns (logits [B, C, V] f32, new cache).
    """
    b, c = tokens.shape
    hd = cfg.resolved_head_dim
    if slot_ids is None:
        slot_ids = jnp.full((b,), -1, jnp.int32)
    block = cache["k"].shape[2]
    tables = cache["tables"]
    max_blocks = tables.shape[1]
    s_max = max_blocks * block
    batch_idx = jnp.arange(b)[:, None]  # [B, 1] broadcast over C

    writable = positions < s_max
    if active is not None:
        writable = writable & active[:, None]
    phys_block = jnp.where(
        writable,
        tables[batch_idx, jnp.clip(positions // block, 0, max_blocks - 1)],
        TRASH_BLOCK,
    )  # [B, C]
    offset = positions % block

    h = params["embed"][tokens]  # [B, C, D]
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)

    per_layer_lora = None
    if lora_bufs is not None:
        per_layer_lora, _ = lora_lib.stack_for_scan(lora_bufs)

    quant = "k_scale" in cache

    def layer_fn(h, xs):
        lp, ll, *pools = xs
        layer_lora = None if ll is None else {**ll, "scale": lora_bufs["scale"]}
        hn = rms_norm(h, lp["attn_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        q = _attn_proj(lp, "q", hn, layer_lora, slot_ids).reshape(
            b, c, cfg.n_heads, hd)
        k = _attn_proj(lp, "k", hn, layer_lora, slot_ids).reshape(
            b, c, cfg.n_kv_heads, hd)
        v = _attn_proj(lp, "v", hn, layer_lora, slot_ids).reshape(
            b, c, cfg.n_kv_heads, hd)
        q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        pools = _pool_update(tuple(pools), k, v, phys_block, offset)
        # Quantized pools dequant at the gathered view: XLA fuses the
        # multiply into the attention reads, so HBM still streams int8.
        k_rows, v_rows = _pool_rows(pools, tables, h.dtype)
        qg = q.reshape(b, c, cfg.n_kv_heads, cfg.q_per_kv, hd)
        logits = jnp.einsum(
            "bikgh,bjkh->bkgij", qg, k_rows,
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(hd).astype(jnp.float32)
        mask = jnp.arange(s_max)[None, None, :] <= positions[:, :, None]
        logits = jnp.where(mask[:, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(h.dtype)
        attn = jnp.einsum("bkgij,bjkh->bikgh", probs, v_rows).reshape(b, c, -1)
        h = h + _project(attn, lp["wo"], layer_lora, "o", slot_ids)
        hn2 = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        h = h + _mlp(cfg, lp, hn2, layer_lora, slot_ids)
        return h, pools

    xs = (params["layers"], per_layer_lora, cache["k"], cache["v"])
    if quant:
        xs = xs + (cache["k_scale"], cache["v_scale"])
    h, carry = jax.lax.scan(layer_fn, h, xs)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = q_matmul(h, head).astype(jnp.float32)
    new_cache = {"k": carry[0], "v": carry[1], "tables": tables,
                 "length": positions[:, -1] + 1}
    if quant:
        new_cache["k_scale"], new_cache["v_scale"] = carry[2], carry[3]
    return logits, new_cache


def insert_prefill_paged(
    cache: Params,
    k_prompt: jax.Array,   # [L, 1, S_bucket, Kh, hd] from prefill
    v_prompt: jax.Array,
    row: jax.Array | int,          # decode-slot row owning the table entries
    phys_blocks: jax.Array,        # [ceil(S_bucket/block)] int32 — pool
                                   # blocks for this prompt (trash-padded)
    table_row: jax.Array,          # [max_blocks_per_seq] int32 — the row's
                                   # FULL new table (allocated + trash tail)
    length: jax.Array | int,
) -> Params:
    """Insert a prefilled prompt's KV into allocated pool blocks.

    The prompt KV is reshaped to whole blocks and written with one scatter
    per pool array; the trailing partial block carries bucket padding into
    a real block (masked by ``length``), and any wholly-padding blocks are
    directed at the trash block by the engine.
    """
    lyr, _, s, kh, hd = k_prompt.shape
    block = cache["k"].shape[2]
    n_b = phys_blocks.shape[0]
    pad = n_b * block - s
    if pad:
        padding = [(0, 0), (0, 0), (0, pad), (0, 0), (0, 0)]
        k_prompt = jnp.pad(k_prompt, padding)
        v_prompt = jnp.pad(v_prompt, padding)
    if "k_scale" in cache:
        # Quantize at the insert seam (the prefill computes bf16 KV): one
        # scale per (layer, position, kv-head), scattered into the scale
        # pools at the same physical blocks as the data.
        kq, ks = _kv_quantize(k_prompt)  # [L,1,S',Kh,hd] -> [L,1,S',Kh]
        vq, vs = _kv_quantize(v_prompt)
        kb = kq.reshape(lyr, n_b, block, kh, hd)
        vb = vq.reshape(lyr, n_b, block, kh, hd)
        k = cache["k"].at[:, phys_blocks].set(kb)
        v = cache["v"].at[:, phys_blocks].set(vb)
        k_scale = cache["k_scale"].at[:, phys_blocks].set(
            ks.reshape(lyr, n_b, block, kh))
        v_scale = cache["v_scale"].at[:, phys_blocks].set(
            vs.reshape(lyr, n_b, block, kh))
        tables = cache["tables"].at[row].set(table_row)
        length_vec = cache["length"].at[row].set(length)
        return {"k": k, "v": v, "k_scale": k_scale, "v_scale": v_scale,
                "tables": tables, "length": length_vec}
    kb = k_prompt.reshape(lyr, n_b, block, kh, hd)
    vb = v_prompt.reshape(lyr, n_b, block, kh, hd)
    k = cache["k"].at[:, phys_blocks].set(kb.astype(cache["k"].dtype))
    v = cache["v"].at[:, phys_blocks].set(vb.astype(cache["v"].dtype))
    tables = cache["tables"].at[row].set(table_row)
    length_vec = cache["length"].at[row].set(length)
    return {"k": k, "v": v, "tables": tables, "length": length_vec}


def prefill_with_cache_paged(
    cfg: ModelConfig,
    params: Params,
    cache: Params,
    tokens: jax.Array,      # [C] int32 — one chunk for ONE row
    positions: jax.Array,   # [C] int32 — absolute positions
    row: jax.Array,         # scalar int32 — decode-slot row
    lane_end: jax.Array,    # scalar int32 — valid tokens after this chunk
    last_index: jax.Array,  # scalar int32 — chunk index of last REAL token
    lora_bufs: Params | None = None,
    lora_slot: jax.Array | int = -1,
):
    """Chunked prefill against the paged pool (parity with
    ``transformer.prefill_with_cache``): chunk K/V scatter through the row's
    block table; chunk queries attend to the row's gathered view."""
    c = tokens.shape[0]
    hd = cfg.resolved_head_dim
    block = cache["k"].shape[2]
    tables = cache["tables"]
    max_blocks = tables.shape[1]
    s_max = max_blocks * block
    slot_ids = jnp.full((1,), lora_slot, jnp.int32)

    per_layer_lora = None
    if lora_bufs is not None:
        per_layer_lora, _ = lora_lib.stack_for_scan(lora_bufs)

    table_row = jax.lax.dynamic_index_in_dim(tables, row, 0, keepdims=False)
    # Final-chunk pads can run past s_max: the lane path's scatter drops
    # them (OOB), but a clipped table lookup would alias the row's LAST
    # real block — route them to the trash block instead.
    in_bounds = positions < s_max
    phys_block = jnp.where(
        in_bounds,
        table_row[jnp.clip(positions // block, 0, max_blocks - 1)],
        TRASH_BLOCK,
    )  # [C]
    offset = positions % block

    h = params["embed"][tokens][None]
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)
    pos2d = positions[None]

    quant = "k_scale" in cache

    def layer_fn(h, xs):
        lp, ll, *pools = xs
        layer_lora = None if ll is None else {**ll, "scale": lora_bufs["scale"]}
        hn = rms_norm(h, lp["attn_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        q = _attn_proj(lp, "q", hn, layer_lora, slot_ids).reshape(1, c, cfg.n_heads, hd)
        k = _attn_proj(lp, "k", hn, layer_lora, slot_ids).reshape(1, c, cfg.n_kv_heads, hd)
        v = _attn_proj(lp, "v", hn, layer_lora, slot_ids).reshape(1, c, cfg.n_kv_heads, hd)
        q = apply_rope(q, pos2d, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, pos2d, cfg.rope_theta, cfg.rope_scaling)
        pools = _pool_update(tuple(pools), k[0], v[0], phys_block, offset)
        lane_k, lane_v = (r[0] for r in
                          _pool_rows(pools, table_row[None], h.dtype))
        # Flash-style chunk attend over the gathered lane view (shared
        # dispatch incl. the quant gate: transformer._chunk_attend).
        attn = _chunk_attend(cfg, quant, q, lane_k, lane_v, positions[0])
        h = h + _project(attn, lp["wo"], layer_lora, "o", slot_ids)
        hn2 = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
        h = h + _mlp(cfg, lp, hn2, layer_lora, slot_ids)
        return h, pools

    xs = (params["layers"], per_layer_lora, cache["k"], cache["v"])
    if quant:
        xs = xs + (cache["k_scale"], cache["v_scale"])
    h, carry = jax.lax.scan(layer_fn, h, xs)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    last_h = jax.lax.dynamic_index_in_dim(h[0], last_index, 0, keepdims=False)
    last_logits = q_matmul(last_h, head).astype(jnp.float32)
    length_vec = cache["length"].at[row].set(lane_end)
    new_cache = {"k": carry[0], "v": carry[1], "tables": tables,
                 "length": length_vec}
    if quant:
        new_cache["k_scale"], new_cache["v_scale"] = carry[2], carry[3]
    return last_logits, new_cache
