"""Fleet KV economy rollup: per-pod reuse efficiency and the cross-replica
prefix duplication index over the replicas' ``tpu:kv_*`` ledger families
(server/kv_ledger.py).

The engine side keeps a block-lifecycle ledger — per-state block counts
tiling the pool budget, a per-prefix reuse heatmap keyed by the
content-addressed prefix id, fragmentation/headroom histograms.  This
module answers the FLEET questions those per-pod tables can't:

- **Reuse efficiency per pod**: cumulative prompt tokens served from the
  prefix cache over total prompt tokens the pod saw
  (``reused / (reused + prefilled)``) — how much prefill compute the
  cache is actually buying on each replica, plus an EMA tokens/s rate of
  the savings.
- **Parked-KV share per pod**: the fraction of the pod's KV budget held
  by prefilled-but-unslotted handoff imports (``parked`` state /
  ``kv_blocks_total``) — capacity that serves nobody until a decode slot
  frees.
- **Fleet duplication index**: the per-pod ``kv_prefix_resident_blocks``
  tables JOIN on the prefix id (the hash chain is content-addressed and
  adapter-seeded, so one shared system prompt hashes identically on every
  replica): a prefix resident on ``k >= 2`` replicas carries
  ``sum(blocks) - max(blocks)`` duplicated blocks — HBM spent caching the
  same tokens twice — and its fleet-wide reuse traffic times
  ``(k - 1) / k`` is the tokens/s a single shared copy could serve
  (the dedup headroom a KV-affinity router or shared KV store would
  recover).  A prefix entering the duplicated set journals a
  ``kv_duplication`` event.

Mechanics mirror ``gateway/usage.py``: one ``tick()`` per observability
cadence (lazily from ``/debug/kv``), cumulative-counter deltas EMA-smoothed
into rates, state dropped for pods/prefixes that leave the exposition.
``set_remote_tables`` is the statebus/fleet seam: peer gateways' pod
residency tables overlay the join (publish-by-swap) so N fronts sharing a
fleet compute one duplication index.  ``tools/kv_report.py`` renders the
heatmap + duplication table from ``/debug/kv``.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import escape_label


@dataclass(frozen=True)
class KvObsConfig:
    # Weight of the newest tick's delta rate in the EMA (1.0 = raw).
    ema_alpha: float = 0.6
    # Residency on this many replicas makes a prefix "duplicated".
    min_replicas: int = 2
    # Exposition/debug cap on per-prefix rows (hottest first) — the
    # prefix id is a label, so an unbounded table is unbounded
    # cardinality.
    top_prefixes: int = 32


class KvObsRollup:
    """Thread-safe fleet KV rollup; ``tick()`` runs on the proxy's
    observability cadence (and lazily from ``/debug/kv``)."""

    def __init__(self, provider, cfg: KvObsConfig | None = None,
                 journal: "events_mod.EventJournal | None" = None,
                 clock=time.time):
        self.provider = provider
        self.cfg = cfg or KvObsConfig()
        self.journal = journal
        self._clock = clock
        self._lock = witness_lock("KvObsRollup._lock")
        # Cumulative-counter memory for delta rates.
        self._prev_pod_saved: dict[str, float] = {}     # pod -> reused toks
        self._prev_prefix_saved: dict[str, float] = {}  # prefix -> saved
        self._pod_rate: dict[str, float] = {}           # pod -> tok/s EMA
        self._prefix_rate: dict[str, float] = {}        # prefix -> tok/s EMA
        # Last tick's derived view (read by render/debug under the lock).
        self._pods: dict[str, dict] = {}
        self._dup_rows: list[dict] = []
        self._dup_totals: dict[str, float] = {
            "duplicated_prefixes": 0, "duplicated_blocks": 0,
            "duplicated_tokens": 0, "dedup_tokens_saved_per_s": 0.0}
        self._dup_prefixes: set[str] = set()
        # Peer-gateway residency overlay ({source: {"blocks": {prefix:
        # blocks}, "block_tokens": n}}) — swapped whole, never mutated,
        # so a concurrent tick joins either the old or the new view.
        self._remote_tables: dict[str, dict] = {}
        self.last_tick = 0.0
        self.ticks = 0

    # -- rollup --------------------------------------------------------------
    def maybe_tick(self, min_interval_s: float = 1.0) -> None:
        """On-demand rollup with a floor between passes — rate EMAs
        difference cumulative counters per PASS, so an unthrottled debug
        poller must not collapse every window to its own poll period."""
        if self._clock() - self.last_tick >= min_interval_s:
            self.tick()

    @staticmethod
    def _pod_view(m) -> dict | None:
        """One pod's ledger-derived row, or None for servers without the
        ``tpu:kv_*`` families (foreign engines, ledger disabled)."""
        total = int(getattr(m, "kv_blocks_total", 0) or 0)
        states = dict(getattr(m, "kv_blocks", None) or {})
        if total <= 0 and not states:
            return None
        parked = float(states.get("parked", 0))
        free = float(states.get("free", 0))
        reused = float(getattr(m, "prefix_reused_tokens", 0) or 0)
        prefilled = sum(
            v for (_model, _adapter, phase), v in
            (getattr(m, "adapter_tokens", None) or {}).items()
            if phase == "prefill")
        denom = reused + prefilled
        return {
            "blocks_total": total,
            "block_tokens": int(getattr(m, "kv_block_tokens", 0) or 0),
            "states": states,
            "usage": round(1.0 - free / total, 4) if total else 0.0,
            "parked_share": round(parked / total, 4) if total else 0.0,
            "reused_tokens": int(reused),
            "prefill_tokens": int(prefilled),
            "reuse_efficiency": round(reused / denom, 4) if denom else 0.0,
            "resident": {p: int(b) for p, b in
                         (getattr(m, "kv_prefix_resident_blocks", None)
                          or {}).items() if int(b) > 0},
            "hits": dict(getattr(m, "kv_prefix_hits", None) or {}),
            "saved": dict(getattr(m, "kv_prefix_tokens_saved", None) or {}),
        }

    def tick(self, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        pods: dict[str, dict] = {}
        for pm in self.provider.all_pod_metrics():
            view = self._pod_view(pm.metrics)
            if view is not None:
                pods[pm.pod.name] = view
        remote = self._remote_tables  # swap-published; read once
        cfg = self.cfg
        entered: list[tuple[str, int, int]] = []
        with self._lock:
            dt = now - self.last_tick if self.ticks else 0.0
            self.last_tick = now
            self.ticks += 1
            a = cfg.ema_alpha
            # Per-pod savings rate (tokens/s of prefill the cache absorbed).
            for name, view in pods.items():
                cur = float(view["reused_tokens"])
                prev = self._prev_pod_saved.get(name)
                self._prev_pod_saved[name] = cur
                if prev is not None and dt > 0:
                    rate = max(0.0, cur - prev) / dt
                    self._pod_rate[name] = (
                        a * rate + (1 - a) * self._pod_rate.get(name, 0.0))
                view["saved_tokens_per_s"] = round(
                    self._pod_rate.get(name, 0.0), 2)
            for table in (self._prev_pod_saved, self._pod_rate):
                for gone in [n for n in table if n not in pods]:
                    del table[gone]
            # The duplication join: local pods + the peer overlay.  A peer
            # front SHARING our pods ships the same pod names — local
            # wins, the overlay only adds pods we don't scrape ourselves.
            residency: dict[str, dict[str, int]] = {
                name: view["resident"] for name, view in pods.items()}
            block_tokens = max(
                [v["block_tokens"] for v in pods.values()] or [0])
            for source, tbl in remote.items():
                if source in residency or not isinstance(tbl, dict):
                    continue
                blocks = {str(p): int(b)
                          for p, b in (tbl.get("blocks") or {}).items()
                          if int(b) > 0}
                if blocks:
                    residency[source] = blocks
                    block_tokens = max(
                        block_tokens, int(tbl.get("block_tokens") or 0))
            by_prefix: dict[str, dict[str, int]] = {}
            for pod_name, blocks in residency.items():
                for prefix, n in blocks.items():
                    by_prefix.setdefault(prefix, {})[pod_name] = n
            # Fleet-wide per-prefix savings rate (cumulative across pods).
            fleet_saved: dict[str, float] = {}
            for view in pods.values():
                for prefix, v in view["saved"].items():
                    fleet_saved[prefix] = fleet_saved.get(prefix, 0.0) + v
            for prefix, cur in fleet_saved.items():
                prev = self._prev_prefix_saved.get(prefix)
                self._prev_prefix_saved[prefix] = cur
                if prev is not None and dt > 0:
                    rate = max(0.0, cur - prev) / dt
                    self._prefix_rate[prefix] = (
                        a * rate + (1 - a) * self._prefix_rate.get(
                            prefix, 0.0))
            live = set(fleet_saved) | set(by_prefix)
            for table in (self._prev_prefix_saved, self._prefix_rate):
                for prefix in [p for p in table if p not in live]:
                    del table[prefix]
            # Duplicated rows: k replicas hold the prefix; every copy
            # past the first is HBM spent re-caching the same tokens.
            rows = []
            dup_now: set[str] = set()
            for prefix, holders in by_prefix.items():
                k = len(holders)
                if k < cfg.min_replicas:
                    continue
                dup_now.add(prefix)
                dup_blocks = sum(holders.values()) - max(holders.values())
                rate = self._prefix_rate.get(prefix, 0.0)
                rows.append({
                    "prefix": prefix,
                    "replicas": k,
                    "blocks": {p: holders[p] for p in sorted(holders)},
                    "duplicated_blocks": dup_blocks,
                    "duplicated_tokens": dup_blocks * block_tokens,
                    "hits": int(sum(v["hits"].get(prefix, 0)
                                    for v in pods.values())),
                    "tokens_saved": int(fleet_saved.get(prefix, 0)),
                    # The reuse traffic a single shared copy could serve:
                    # (k-1)/k of the fleet hit rate lands on a duplicate.
                    "dedup_tokens_saved_per_s": round(
                        rate * (k - 1) / k, 2),
                })
            rows.sort(key=lambda r: (-r["duplicated_blocks"],
                                     -r["tokens_saved"], r["prefix"]))
            entered = [(r["prefix"], r["replicas"], r["duplicated_blocks"])
                       for r in rows if r["prefix"] not in self._dup_prefixes]
            self._dup_prefixes = dup_now
            self._pods = pods
            self._dup_rows = rows[:cfg.top_prefixes]
            self._dup_totals = {
                "duplicated_prefixes": len(rows),
                "duplicated_blocks": sum(r["duplicated_blocks"]
                                         for r in rows),
                "duplicated_tokens": sum(r["duplicated_tokens"]
                                         for r in rows),
                "dedup_tokens_saved_per_s": round(
                    sum(r["dedup_tokens_saved_per_s"] for r in rows), 2),
            }
        for prefix, replicas, blocks in entered:
            if self.journal is not None:
                self.journal.emit(events_mod.KV_DUPLICATION, prefix=prefix,
                                  replicas=replicas, blocks=blocks)

    # -- statebus / fleet seam ----------------------------------------------
    def set_remote_tables(self, tables: dict[str, dict]) -> None:
        """Replace the peer-derived residency overlay (``{source pod:
        {"blocks": {prefix: blocks}, "block_tokens": n}}``; empty = join
        local pods only).  Swapped whole so a concurrent tick reads a
        consistent view."""
        with self._lock:
            self._remote_tables = dict(tables)

    def local_tables(self) -> dict[str, dict]:
        """This gateway's locally-scraped residency tables in the overlay
        shape — what a peer feeds its ``set_remote_tables``."""
        with self._lock:
            return {name: {"blocks": dict(view["resident"]),
                           "block_tokens": view["block_tokens"]}
                    for name, view in self._pods.items()}

    # -- export ---------------------------------------------------------------
    def render(self) -> list[str]:
        """The ``gateway_kv_*`` families."""
        with self._lock:
            pods = {name: dict(view) for name, view in self._pods.items()}
            rows = list(self._dup_rows)
            totals = dict(self._dup_totals)
        lines = []
        if pods:
            lines.append("# TYPE gateway_kv_reuse_efficiency gauge")
            for name in sorted(pods):
                lines.append('gateway_kv_reuse_efficiency{pod="%s"} %.4f'
                             % (escape_label(name),
                                pods[name]["reuse_efficiency"]))
            lines.append("# TYPE gateway_kv_parked_share gauge")
            for name in sorted(pods):
                lines.append('gateway_kv_parked_share{pod="%s"} %.4f'
                             % (escape_label(name),
                                pods[name]["parked_share"]))
            lines.append("# TYPE gateway_kv_saved_tokens_per_s gauge")
            for name in sorted(pods):
                lines.append('gateway_kv_saved_tokens_per_s{pod="%s"} %.2f'
                             % (escape_label(name),
                                pods[name]["saved_tokens_per_s"]))
        lines += [
            "# TYPE gateway_kv_duplicated_prefixes gauge",
            "gateway_kv_duplicated_prefixes %d"
            % totals["duplicated_prefixes"],
            "# TYPE gateway_kv_duplicated_blocks gauge",
            "gateway_kv_duplicated_blocks %d" % totals["duplicated_blocks"],
            "# TYPE gateway_kv_dedup_tokens_saved_per_s gauge",
            "gateway_kv_dedup_tokens_saved_per_s %.2f"
            % totals["dedup_tokens_saved_per_s"],
        ]
        if rows:
            lines.append("# TYPE gateway_kv_prefix_replicas gauge")
            for r in rows:
                lines.append('gateway_kv_prefix_replicas{prefix="%s"} %d'
                             % (escape_label(r["prefix"]), r["replicas"]))
        return lines

    def debug_payload(self) -> dict:
        """The gateway's ``/debug/kv`` JSON body (also what
        ``tools/kv_report.py`` and the black-box dump embed)."""
        with self._lock:
            pods = {}
            for name, view in sorted(self._pods.items()):
                row = dict(view)
                resident = row.pop("resident")
                hits = row.pop("hits")
                saved = row.pop("saved")
                # The raw per-prefix tables fold into one table per pod;
                # kv_report joins them, lig-top only needs the scalars.
                row["prefixes"] = {
                    p: {"blocks": int(resident.get(p, 0)),
                        "hits": int(hits.get(p, 0)),
                        "tokens_saved": int(saved.get(p, 0))}
                    for p in sorted(set(resident) | set(hits) | set(saved))}
                pods[name] = row
            return {
                "pods": pods,
                "duplication": {**{k: (int(v) if isinstance(v, int) else v)
                                   for k, v in self._dup_totals.items()},
                                "prefixes": list(self._dup_rows)},
                "ticks": self.ticks,
                "last_tick": self.last_tick,
                "config": asdict(self.cfg),
            }
