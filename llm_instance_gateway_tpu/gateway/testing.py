"""Hermetic test harness for the gateway.

Parity: reference ``pkg/ext-proc/test/utils.go:21-80`` — ``StartExtProc``
wires a REAL gRPC ext-proc server + REAL scheduler over a fake metrics client
and an in-memory datastore with N fake pods; ``GenerateRequest`` and
``FakePod`` build inputs.  Used by the hermetic test and the load benchmark
(``test/benchmark/benchmark.go``).
"""

from __future__ import annotations

import json

from llm_instance_gateway_tpu.api.v1alpha1 import (
    Criticality,
    InferenceModel,
    InferenceModelSpec,
    InferencePool,
    InferencePoolSpec,
    TargetModel,
)
from llm_instance_gateway_tpu.gateway.datastore import Datastore
from llm_instance_gateway_tpu.gateway.extproc.service import build_grpc_server
from llm_instance_gateway_tpu.gateway.handlers.server import Server
from llm_instance_gateway_tpu.gateway.metrics_client import FakePodMetricsClient
from llm_instance_gateway_tpu.gateway.provider import Provider
from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
from llm_instance_gateway_tpu.gateway.types import Metrics, Pod, PodMetrics


def fake_pod(index: int, role: str = "collocated") -> Pod:
    """test/utils.go:74-80 (+ disaggregation role for role-split rigs)."""
    return Pod(name=f"pod-{index}", address=f"192.168.1.{index + 1}:8000",
               role=role)


def fake_metrics(
    queue: int = 0,
    kv: float = 0.0,
    adapters: dict[str, int] | None = None,
    max_adapters: int = 4,
    prefill: int = 0,
    adapter_tiers: dict[str, str] | None = None,
) -> Metrics:
    return Metrics(
        waiting_queue_size=queue,
        kv_cache_usage_percent=kv,
        active_adapters=dict(adapters or {}),
        max_active_adapters=max_adapters,
        prefill_queue_size=prefill,
        adapter_tiers=dict(adapter_tiers or {}),
    )


def generate_request(model: str, prompt: str = "test prompt") -> bytes:
    """test/utils.go:57-66."""
    return json.dumps(
        {"model": model, "prompt": prompt, "max_tokens": 100, "temperature": 0}
    ).encode()


def make_model(
    name: str,
    criticality: Criticality = Criticality.CRITICAL,
    targets: list[tuple[str, int]] | None = None,
) -> InferenceModel:
    return InferenceModel(
        name=name,
        spec=InferenceModelSpec(
            model_name=name,
            criticality=criticality,
            target_models=[TargetModel(n, w) for n, w in (targets or [])],
        ),
    )


def build_handler_server(
    pod_metrics: dict[Pod, Metrics],
    models: list[InferenceModel],
    scheduler_factory=None,
    **scheduler_kwargs,
):
    """The rig's in-process core: real handler ``Server`` + real scheduler
    over a refreshed ``Provider`` (so the native snapshot cache has a
    version to key on) and an in-memory datastore.  Returns the handler
    server; the loadgen's fast path drives ``server.process`` on it
    directly — no gRPC, no proto marshalling."""
    datastore = Datastore(pods=list(pod_metrics))
    datastore.set_pool(
        InferencePool(name="test-pool", spec=InferencePoolSpec(selector={"app": "t"}))
    )
    for model in models:
        datastore.store_model(model)
    client = FakePodMetricsClient(
        res={pod.name: m for pod, m in pod_metrics.items()}
    )
    provider = Provider(client, datastore)
    provider.refresh_pods_once()
    provider.refresh_metrics_once()
    if scheduler_factory is not None and scheduler_kwargs:
        raise TypeError(
            "scheduler_factory and scheduler kwargs are mutually exclusive "
            f"(kwargs {sorted(scheduler_kwargs)} would be silently dropped)")
    scheduler = (scheduler_factory(provider) if scheduler_factory is not None
                 else Scheduler(provider, **scheduler_kwargs))
    return Server(scheduler, datastore)


def start_ext_proc(
    pod_metrics: dict[Pod, Metrics],
    models: list[InferenceModel],
    port: int = 9002,
    scheduler_factory=None,
    **scheduler_kwargs,
):
    """StartExtProc (test/utils.go:21-51): real gRPC server, fake metrics.

    ``scheduler_factory(provider)`` overrides the default Python
    ``Scheduler`` (e.g. ``scheduling.native.make_scheduler`` for the C++
    hot path — the loadgen's A/B axis).
    Returns the started grpc server; caller must ``server.stop(None)``.
    """
    handler_server = build_handler_server(
        pod_metrics, models, scheduler_factory=scheduler_factory,
        **scheduler_kwargs)
    grpc_server = build_grpc_server(
        handler_server, handler_server.datastore, port=port)
    grpc_server.start()
    # Rigs that instrument the scheduler seams (loadgen's pick-funnel
    # block) reach the wrapped core through this attribute.
    grpc_server.handler_server = handler_server
    return grpc_server


def static_provider(pod_metrics: dict[Pod, Metrics]):
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider

    return StaticProvider(
        [PodMetrics(pod=p, metrics=m) for p, m in pod_metrics.items()]
    )
