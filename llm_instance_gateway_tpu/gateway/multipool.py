"""One EPP process serving several InferencePools.

The reference pins one EPP deployment per InferencePool — ``main.go``'s
``-serverPoolName`` flag names exactly one pool and every reconciler filters
to it (``/root/reference/pkg/ext-proc/main.go:33-45``), so an operator with
many small pools pays a gateway deployment per pool.  Here one process hosts
N fully independent pool stacks — each pool keeps its own datastore,
provider refresh loops, scheduler thresholds, admission queues, and
membership sources, i.e. exactly the single-pool components, unchanged —
and requests route to a pool by the InferenceModel named in the body
(InferenceModel.poolRef already binds every model to one pool, so the model
name is an unambiguous pool selector).

Both transports consume the same duck-typed surface as single-pool
``GatewayComponents``: ``handler_server`` (phase dispatch), ``datastore`` /
``provider`` / ``scheduler`` (read-mostly views that fan out or delegate to
the default pool).  ROADMAP item 14.
"""

from __future__ import annotations

import json
import logging

from llm_instance_gateway_tpu.gateway.handlers.messages import (
    ProcessingMessage,
    RequestBody,
)
from llm_instance_gateway_tpu.gateway.handlers.server import RequestContext

logger = logging.getLogger(__name__)


class MultiPoolServer:
    """Phase dispatcher that pins each request to one pool's handler core.

    The pool is chosen at the RequestBody phase (the first phase that names
    a model); earlier phases are pool-agnostic and later phases (response
    headers/body/trailers) replay to the pool the body phase picked, so
    usage accounting lands in the right pool's context.  Unroutable models
    fall through to the default pool's handler, which raises the same
    model-not-found error a single-pool gateway would (handlers/request.py
    no-passthrough parity).
    """

    def __init__(self, servers: dict[str, object], datastores: dict[str, object],
                 default: str):
        self._servers = servers
        self._datastores = datastores
        self._default = default
        # Conflicts already surfaced: a persistent cross-pool ambiguity sits
        # on the hot request path, so it logs ONCE per model, not per request.
        self._warned_conflicts: set[str] = set()

    @property
    def target_pod_header(self) -> str:
        return self._servers[self._default].target_pod_header

    @property
    def decode_pod_header(self) -> str:
        from llm_instance_gateway_tpu.gateway.handlers.server import (
            DEFAULT_DECODE_POD_HEADER,
        )

        return getattr(self._servers[self._default], "decode_pod_header",
                       DEFAULT_DECODE_POD_HEADER)

    def _route(self, body: bytes):
        """Returns (pool_name | None, parsed_body | None)."""
        try:
            parsed = json.loads(body or b"{}")
        except (ValueError, AttributeError):
            return None, None  # malformed body: default pool produces the 400
        if not isinstance(parsed, dict):
            return None, None
        model = parsed.get("model")
        if not isinstance(model, str) or not model:
            return None, parsed
        matches = [name for name, ds in self._datastores.items()
                   if ds.fetch_model(model) is not None]
        if len(matches) > 1 and model not in self._warned_conflicts:
            # Build/resync validation rejects cross-pool modelName
            # ambiguity, but per-object k8s watch events bypass it (each
            # pool's informer feeds its own reconciler) — surface the
            # conflict loudly instead of silently picking by iteration
            # order.
            self._warned_conflicts.add(model)
            logger.error(
                "model %r is bound in multiple pools %s (cross-pool "
                "modelName ambiguity slipped past validation); routing "
                "to %s", model, matches, matches[0])
        if matches:
            return matches[0], parsed
        return None, parsed

    def process(self, req_ctx: RequestContext, msg: ProcessingMessage):
        if isinstance(msg, RequestBody):
            pool, parsed = self._route(msg.body)
            if pool is None:
                pool = self._default
            else:
                logger.debug("request routed to pool %s", pool)
            req_ctx._pool = pool  # later phases replay to the same pool
            if parsed is not None:
                # The routed handler reuses this parse (handlers/request.py)
                # instead of decoding the body a second time.
                req_ctx._parsed_body = parsed
        pool = getattr(req_ctx, "_pool", self._default)
        return self._servers[pool].process(req_ctx, msg)


class _DatastoreView:
    """Union view over per-pool datastores (transport health/introspection)."""

    def __init__(self, datastores: dict[str, object], default: str):
        self._datastores = datastores
        self._default = default

    def has_synced_pool(self) -> bool:
        return all(ds.has_synced_pool() for ds in self._datastores.values())

    def get_pool(self):
        return self._datastores[self._default].get_pool()

    def fetch_model(self, model_name: str):
        for ds in self._datastores.values():
            m = ds.fetch_model(model_name)
            if m is not None:
                return m
        return None

    def all_models(self) -> list:
        return [m for ds in self._datastores.values() for m in ds.all_models()]

    def all_pods(self) -> list:
        return [p for ds in self._datastores.values() for p in ds.all_pods()]


class _ProviderView:
    def __init__(self, providers: dict[str, object]):
        self._providers = providers

    def get_pod_metrics(self, pod_name: str):
        for p in self._providers.values():
            if hasattr(p, "get_pod_metrics"):
                pm = p.get_pod_metrics(pod_name)
                if pm is not None:
                    return pm
        return None

    def all_pod_metrics(self) -> list:
        return [pm for p in self._providers.values()
                for pm in p.all_pod_metrics()]


class _SchedulerView:
    """Fan-out for process-wide knobs; reads delegate to the default pool
    (per-pool tuning flows through each pool's own document hot-reload)."""

    def __init__(self, schedulers: dict[str, object], default: str):
        self._schedulers = schedulers
        self._default = default

    @property
    def cfg(self):
        return self._schedulers[self._default].cfg

    def set_park_budget(self, budget: int) -> None:
        for s in self._schedulers.values():
            if hasattr(s, "set_park_budget"):
                s.set_park_budget(budget)


class MultiPoolComponents:
    """Drop-in aggregate of per-pool ``GatewayComponents``."""

    def __init__(self, pools: dict[str, object], default: str):
        if default not in pools:
            raise ValueError(f"default pool {default!r} not in {list(pools)}")
        self.pools = pools
        self.default_name = default
        self.handler_server = MultiPoolServer(
            {n: c.handler_server for n, c in pools.items()},
            {n: c.datastore for n, c in pools.items()},
            default,
        )
        self.datastore = _DatastoreView(
            {n: c.datastore for n, c in pools.items()}, default)
        self.provider = _ProviderView(
            {n: c.provider for n, c in pools.items()})
        self.scheduler = _SchedulerView(
            {n: c.scheduler for n, c in pools.items()}, default)

    @property
    def pool_reconciler(self):
        return self.pools[self.default_name].pool_reconciler

    def start_provider(self, pods_interval_s: float = 10.0,
                       metrics_interval_s: float = 0.05) -> None:
        for c in self.pools.values():
            c.start_provider(pods_interval_s=pods_interval_s,
                             metrics_interval_s=metrics_interval_s)

    def stop(self) -> None:
        for c in self.pools.values():
            c.stop()
