"""Gateway self-metrics: scheduler decisions, pick latency, shed rate,
per-phase request latency (TTFT / TPOT / e2e).

The reference EPP *consumes* Prometheus but never *exports* it (acknowledged
TODO, ``backend/provider.go:140``; SURVEY.md §5).  This module resolves that
gap: lightweight counters/histograms exposed in Prometheus text format by the
proxy's ``/metrics`` endpoint and the load rig.

Hand-rolled rather than prometheus_client so the request path stays at a few
dict operations under a lock-free fast path (GIL-atomic int adds).  Label
values are escaped through the server-side renderer's ``escape_label`` — one
hostile model name must not poison the exposition — and all latency families
render as TRUE Prometheus histograms (``_bucket`` lines with ``le=``) via the
shared ``tracing.render_histogram`` helper.
"""

from __future__ import annotations

import time

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.tracing import (
    LATENCY_BUCKETS,
    PICK_BUCKETS,
    Histogram,
    escape_label,  # one escaping impl for every exposition surface
    render_counter,
    render_histogram,
)

_BUCKETS = PICK_BUCKETS  # historical alias (tests, dashboards)

# Gateway-side phase-latency families, labeled by model and serving path
# ("collocated" vs "disaggregated") so the two pool topologies compare
# directly.  TTFT/TPOT definitions: streaming measures real chunk arrival;
# non-streaming uses the server-reported first-token time (``ttft_ms``) when
# present, and the prefill-hop completion on the two-hop path (the first
# token rides the handoff).
PHASE_FAMILIES = (
    ("ttft", "gateway_ttft_seconds"),
    ("tpot", "gateway_tpot_seconds"),
    ("e2e", "gateway_e2e_seconds"),
)


class GatewayMetrics:
    def __init__(self) -> None:
        self._lock = witness_lock("GatewayMetrics._lock")
        self.requests_total: dict[str, int] = {}  # by model
        self.scheduled_total: dict[str, int] = {}  # by target pod
        # Shed/error counters keyed by model; the None key is the unlabeled
        # fallback for pre-admission failures (body unparsed, model unknown)
        # so per-tenant shed rate is visible without losing those.
        self.shed_total: dict[str | None, int] = {}
        self.errors_total: dict[str | None, int] = {}
        # Subset of errors_total raised before record_request (admission
        # failures) — the SLO error-rate denominator widener; not its own
        # exposition family.
        self.errors_preadmission: dict[str | None, int] = {}
        self.tokens_prompt_total: dict[str, int] = {}  # by model
        self.tokens_completion_total: dict[str, int] = {}
        self.pick_latency = Histogram()
        # (model, path) -> Histogram for each phase family.
        self.phase_latency: dict[str, dict[tuple[str, str], Histogram]] = {
            key: {} for key, _ in PHASE_FAMILIES
        }
        self.lora_affinity_hits = 0  # picked pod already had the adapter
        # Resilience data path (gateway/resilience.py): retries performed
        # (by failure reason), hedges fired (by outcome), and client-side
        # stream disconnects (by model; None = model unknown).
        self.retries_total: dict[str, int] = {}
        self.hedges_total: dict[str, int] = {}
        self.client_disconnects_total: dict[str | None, int] = {}
        # Upstream keepalive pool (proxy TraceConfig hooks): connections
        # by (pod, created|reused).  The reuse ratio — reused / total — is
        # the pooled-relay health signal: near 0 means every request pays
        # a TCP handshake, near 1 means the pool is doing its job.
        self.upstream_connections_total: dict[tuple[str, str], int] = {}
        # Optional pool-signal source (set by the proxy): a callable
        # returning the provider's PodMetrics snapshot, re-exported at
        # render time so operators see per-replica prefix-cache hit volume
        # at the gateway — the observable a KV-affinity routing policy
        # would rank replicas by.
        self.pool_signals_fn = None

    # -- recording ---------------------------------------------------------
    def record_request(self, model: str) -> None:
        with self._lock:
            self.requests_total[model] = self.requests_total.get(model, 0) + 1

    def record_pick(self, pod_name: str, seconds: float, affinity_hit: bool) -> None:
        with self._lock:
            self.scheduled_total[pod_name] = self.scheduled_total.get(pod_name, 0) + 1
            self.pick_latency.observe(seconds)
            if affinity_hit:
                self.lora_affinity_hits += 1

    def record_shed(self, model: str | None = None) -> None:
        with self._lock:
            self.shed_total[model] = self.shed_total.get(model, 0) + 1

    def record_error(self, model: str | None = None,
                     pre_admission: bool = False) -> None:
        """``pre_admission`` marks errors raised BEFORE record_request
        fires (admission/parse failures): the SLO engine widens the
        error-rate denominator by exactly these, so requests that never
        entered requests_total still count once — not as a shrunken
        denominator that overstates the bad fraction."""
        with self._lock:
            self.errors_total[model] = self.errors_total.get(model, 0) + 1
            if pre_admission:
                self.errors_preadmission[model] = (
                    self.errors_preadmission.get(model, 0) + 1)

    def record_retry(self, reason: str) -> None:
        with self._lock:
            self.retries_total[reason] = self.retries_total.get(reason, 0) + 1

    def record_hedge(self, outcome: str) -> None:
        with self._lock:
            self.hedges_total[outcome] = (
                self.hedges_total.get(outcome, 0) + 1)

    def record_client_disconnect(self, model: str | None = None) -> None:
        with self._lock:
            self.client_disconnects_total[model] = (
                self.client_disconnects_total.get(model, 0) + 1)

    def record_upstream_conn(self, pod: str, reused: bool) -> None:
        key = (pod, "reused" if reused else "created")
        with self._lock:
            self.upstream_connections_total[key] = (
                self.upstream_connections_total.get(key, 0) + 1)

    def connection_reuse_ratio(self) -> float:
        """reused / (created + reused) across the pool; 0.0 before any
        upstream connection exists."""
        with self._lock:
            created = reused = 0
            for (pod, state), n in self.upstream_connections_total.items():
                if state == "reused":
                    reused += n
                else:
                    created += n
        total = created + reused
        return reused / total if total else 0.0

    def record_usage(self, model: str, prompt: int, completion: int) -> None:
        with self._lock:
            self.tokens_prompt_total[model] = (
                self.tokens_prompt_total.get(model, 0) + prompt
            )
            self.tokens_completion_total[model] = (
                self.tokens_completion_total.get(model, 0) + completion
            )

    def record_phase(self, model: str, path: str,
                     ttft_s: float | None = None,
                     tpot_s: float | None = None,
                     e2e_s: float | None = None) -> None:
        """Observe a finished request's phase latencies (None = unknown —
        e.g. a chat envelope without a server-reported first-token time)."""
        with self._lock:
            for key, value in (("ttft", ttft_s), ("tpot", tpot_s),
                               ("e2e", e2e_s)):
                if value is None:
                    continue
                table = self.phase_latency[key]
                h = table.get((model, path))
                if h is None:
                    h = table[(model, path)] = Histogram(LATENCY_BUCKETS)
                h.observe(max(0.0, value))

    def requests_snapshot(self) -> dict:
        """Locked copy of the per-model request counters — the usage
        rollup's admitted-traffic source (an unlocked ``dict()`` of a
        table gRPC threads mutate can die mid-resize)."""
        with self._lock:
            return dict(self.requests_total)

    def slo_snapshot(self) -> dict:
        """Copy-out of the counts the SLO engine evaluates (gateway/slo.py):
        phase-histogram states keyed by (model, path) per objective, plus
        the per-model request/shed/error counters.  One method so lock
        discipline stays HERE — the engine never touches internals."""
        with self._lock:
            return {
                "phase": {
                    key: {mk: h.state() for mk, h in table.items()}
                    for key, table in self.phase_latency.items()
                },
                "requests": dict(self.requests_total),
                # None keys are the pre-admission fallback (model unknown):
                # per-model objectives can't attribute them, drop here.
                "shed": {k: v for k, v in self.shed_total.items()
                         if k is not None},
                "errors": {k: v for k, v in self.errors_total.items()
                           if k is not None},
                "errors_pre": {k: v for k, v in
                               self.errors_preadmission.items()
                               if k is not None},
            }

    # -- export ------------------------------------------------------------
    @staticmethod
    def _counter_lines(family: str, table: dict, label: str) -> list[str]:
        """One counter family; a None key (or empty table) renders the
        unlabeled fallback line (shared renderer in tracing.py)."""
        return render_counter(family, table, label)

    def render(self) -> str:
        with self._lock:
            lines = self._counter_lines(
                "gateway_requests_total", self.requests_total, "model")
            lines += self._counter_lines(
                "gateway_scheduled_total", self.scheduled_total, "pod")
            lines += self._counter_lines(
                "gateway_shed_total", self.shed_total, "model")
            lines += self._counter_lines(
                "gateway_errors_total", self.errors_total, "model")
            lines += [
                "# TYPE gateway_lora_affinity_hits_total counter",
                f"gateway_lora_affinity_hits_total {self.lora_affinity_hits}",
            ]
            lines += self._counter_lines(
                "gateway_retries_total", self.retries_total, "reason")
            lines += self._counter_lines(
                "gateway_hedges_total", self.hedges_total, "outcome")
            lines += self._counter_lines(
                "gateway_client_disconnects_total",
                self.client_disconnects_total, "model")
            # Upstream keepalive-pool stats (two-label family, so not
            # through render_counter): per-pod created/reused counters and
            # the pool-wide reuse ratio gauge.
            lines.append("# TYPE gateway_upstream_connections_total counter")
            if not self.upstream_connections_total:
                lines.append("gateway_upstream_connections_total 0")
            created = reused = 0
            for (pod, state) in sorted(self.upstream_connections_total):
                n = self.upstream_connections_total[(pod, state)]
                if state == "reused":
                    reused += n
                else:
                    created += n
                lines.append(
                    "gateway_upstream_connections_total"
                    f'{{pod="{escape_label(pod)}",'
                    f'state="{escape_label(state)}"}} {n}')
            total_conns = created + reused
            lines += [
                "# TYPE gateway_upstream_connection_reuse_ratio gauge",
                "gateway_upstream_connection_reuse_ratio "
                f"{(reused / total_conns) if total_conns else 0.0:.4f}",
            ]
            lines += render_histogram(
                "gateway_pick_latency_seconds", self.pick_latency)
            for fam, table in (
                ("gateway_prompt_tokens_total", self.tokens_prompt_total),
                ("gateway_completion_tokens_total", self.tokens_completion_total),
            ):
                lines += self._counter_lines(fam, table, "model")
            for key, family in PHASE_FAMILIES:
                table = self.phase_latency[key]
                if not table:
                    continue
                lines.append(f"# TYPE {family} histogram")
                for (model, path) in sorted(table):
                    lines += render_histogram(
                        family, table[(model, path)],
                        labels={"model": model, "path": path},
                        type_line=False)
            pool_signals = self.pool_signals_fn
        if pool_signals is not None:
            # Outside the lock: the provider snapshot is its own O(pods)
            # copy, and render must not hold our lock across foreign code.
            # Counter TYPE + _total name: the source (tpu:prefix_reused_
            # tokens) is cumulative, so rate()/increase() must see counter
            # semantics; aggregate with sum() over the pod label.
            rows = []
            for pm in pool_signals():
                n = getattr(pm.metrics, "prefix_reused_tokens", 0)
                rows.append(
                    "gateway_pool_prefix_reused_tokens_total"
                    f'{{pod="{escape_label(pm.pod.name)}"}} {n}')
            lines.append(
                "# TYPE gateway_pool_prefix_reused_tokens_total counter")
            lines += rows
        return "\n".join(lines) + "\n"


class Timer:
    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self.t0
        return False
