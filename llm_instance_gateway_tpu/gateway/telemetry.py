"""Gateway self-metrics: scheduler decisions, pick latency, shed rate.

The reference EPP *consumes* Prometheus but never *exports* it (acknowledged
TODO, ``backend/provider.go:140``; SURVEY.md §5).  This module resolves that
gap: lightweight counters/histograms exposed in Prometheus text format by the
proxy's ``/metrics`` endpoint and the load rig.

Hand-rolled rather than prometheus_client so the request path stays at a few
dict operations under a lock-free fast path (GIL-atomic int adds).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)


@dataclass
class Histogram:
    buckets: tuple[float, ...] = _BUCKETS
    counts: list[int] = field(default_factory=lambda: [0] * (len(_BUCKETS) + 1))
    total: float = 0.0
    n: int = 0

    def observe(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += v
        self.n += 1

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return 0.0
        target = q * self.n
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")


class GatewayMetrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.requests_total: dict[str, int] = {}  # by model
        self.scheduled_total: dict[str, int] = {}  # by target pod
        self.shed_total = 0
        self.errors_total = 0
        self.tokens_prompt_total: dict[str, int] = {}  # by model
        self.tokens_completion_total: dict[str, int] = {}
        self.pick_latency = Histogram()
        self.lora_affinity_hits = 0  # picked pod already had the adapter
        # Optional pool-signal source (set by the proxy): a callable
        # returning the provider's PodMetrics snapshot, re-exported at
        # render time so operators see per-replica prefix-cache hit volume
        # at the gateway — the observable a KV-affinity routing policy
        # would rank replicas by.
        self.pool_signals_fn = None

    # -- recording ---------------------------------------------------------
    def record_request(self, model: str) -> None:
        with self._lock:
            self.requests_total[model] = self.requests_total.get(model, 0) + 1

    def record_pick(self, pod_name: str, seconds: float, affinity_hit: bool) -> None:
        with self._lock:
            self.scheduled_total[pod_name] = self.scheduled_total.get(pod_name, 0) + 1
            self.pick_latency.observe(seconds)
            if affinity_hit:
                self.lora_affinity_hits += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def record_usage(self, model: str, prompt: int, completion: int) -> None:
        with self._lock:
            self.tokens_prompt_total[model] = (
                self.tokens_prompt_total.get(model, 0) + prompt
            )
            self.tokens_completion_total[model] = (
                self.tokens_completion_total.get(model, 0) + completion
            )

    # -- export ------------------------------------------------------------
    def render(self) -> str:
        with self._lock:
            lines = [
                "# TYPE gateway_requests_total counter",
            ]
            for model, n in sorted(self.requests_total.items()):
                lines.append(f'gateway_requests_total{{model="{model}"}} {n}')
            lines.append("# TYPE gateway_scheduled_total counter")
            for pod, n in sorted(self.scheduled_total.items()):
                lines.append(f'gateway_scheduled_total{{pod="{pod}"}} {n}')
            lines += [
                "# TYPE gateway_shed_total counter",
                f"gateway_shed_total {self.shed_total}",
                "# TYPE gateway_errors_total counter",
                f"gateway_errors_total {self.errors_total}",
                "# TYPE gateway_lora_affinity_hits_total counter",
                f"gateway_lora_affinity_hits_total {self.lora_affinity_hits}",
                "# TYPE gateway_pick_latency_seconds summary",
                f"gateway_pick_latency_seconds_count {self.pick_latency.n}",
                f"gateway_pick_latency_seconds_sum {self.pick_latency.total}",
                f'gateway_pick_latency_seconds{{quantile="0.5"}} {self.pick_latency.quantile(0.5)}',
                f'gateway_pick_latency_seconds{{quantile="0.99"}} {self.pick_latency.quantile(0.99)}',
            ]
            for fam, table in (
                ("gateway_prompt_tokens_total", self.tokens_prompt_total),
                ("gateway_completion_tokens_total", self.tokens_completion_total),
            ):
                lines.append(f"# TYPE {fam} counter")
                for model, n in sorted(table.items()):
                    lines.append(f'{fam}{{model="{model}"}} {n}')
            pool_signals = self.pool_signals_fn
        if pool_signals is not None:
            # Outside the lock: the provider snapshot is its own O(pods)
            # copy, and render must not hold our lock across foreign code.
            # Counter TYPE + _total name: the source (tpu:prefix_reused_
            # tokens) is cumulative, so rate()/increase() must see counter
            # semantics; aggregate with sum() over the pod label.
            rows = []
            for pm in pool_signals():
                n = getattr(pm.metrics, "prefix_reused_tokens", 0)
                rows.append(
                    "gateway_pool_prefix_reused_tokens_total"
                    f'{{pod="{pm.pod.name}"}} {n}')
            lines.append(
                "# TYPE gateway_pool_prefix_reused_tokens_total counter")
            lines += rows
        return "\n".join(lines) + "\n"


class Timer:
    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self.t0
        return False
